"""Long-context attention: ring vs Ulysses sequence parallelism.

No reference analog (the reference is data-parallel only, SURVEY.md §5.7);
this demonstrates the framework's first-class long-context pillar: a
sequence too large for one chip's memory, sharded over the mesh, with
exact attention computed by either strategy — causal (decoder) by
default, bidirectional (encoder / BERT-family) with ``--encoder``.

Run (8 virtual chips):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/jax/jax_long_context.py [--encoder]
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.parallel import ring_attention, ulysses_attention


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--encoder", action="store_true",
                    help="bidirectional (causal=False) attention")
    args = ap.parse_args()
    causal = not args.encoder

    hvd.init()
    n = hvd.size()
    mesh = hvd.world_mesh()
    axis = hvd.WORLD_AXIS

    b, s_global, heads, dh = 1, 8192, 8, 64
    print(f"sequence {s_global} over {n} chips "
          f"({s_global // n} per chip)")
    rng = np.random.RandomState(0)
    shape = (b, s_global, heads, dh)
    q = jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.1)
    k = jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.1)
    v = jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.1)

    specs = dict(
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis), check_vma=False,
    )
    ring = jax.jit(jax.shard_map(
        lambda a, b_, c: ring_attention(a, b_, c, axis_name=axis,
                                        causal=causal),
        mesh=mesh, **specs))
    # flash-block ring: the TPU path (pallas kernels; interpret-mode and
    # slow on CPU, so the demo uses it only on real chips)
    ring_flash = jax.jit(jax.shard_map(
        lambda a, b_, c: ring_attention(a, b_, c, axis_name=axis,
                                        impl="flash", causal=causal),
        mesh=mesh, **specs))
    ulysses = jax.jit(jax.shard_map(
        lambda a, b_, c: ulysses_attention(a, b_, c, axis_name=axis,
                                           causal=causal),
        mesh=mesh, **specs))

    variants = [("ring", ring), ("ulysses", ulysses)]
    if jax.default_backend() == "tpu":
        variants.insert(1, ("ring_flash", ring_flash))

    outs = {}
    for name, fn in variants:
        out = jax.block_until_ready(fn(q, k, v))  # compile + run
        t0 = time.perf_counter()
        for _ in range(3):
            out = jax.block_until_ready(fn(q, k, v))
        dt = (time.perf_counter() - t0) / 3
        outs[name] = np.asarray(out)
        print(f"{name:8s}: {dt * 1e3:8.1f} ms/step  "
              f"out[0,0,0,:3]={outs[name][0, 0, 0, :3]}")

    # the strategies compute the SAME mathematical attention — cross-check
    # every variant that ran (incl. ring_flash on real chips).  Only CPU
    # f32 is exact; accelerator backends (TPU MXU bf16-input matmuls, GPU
    # TF32-class defaults) legitimately differ by ~1e-3 relative between
    # strategies.
    tight = jax.default_backend() == "cpu"
    rtol, atol = (1e-4, 1e-5) if tight else (2e-2, 1e-4)
    names = [n for n in outs if n != "ring"]
    for name in names:
        np.testing.assert_allclose(outs["ring"], outs[name],
                                   rtol=rtol, atol=atol)
    mode = "causal" if causal else "encoder"
    print(f"ring/{'/'.join(names)} agree ({mode} mode)")


if __name__ == "__main__":
    main()
