"""Pipeline-parallel training: GPipe microbatched stages over a pp axis.

No reference analog (SURVEY.md §2.6: PP absent upstream) — demonstrates
the framework's pipeline story end to end: each device owns ONE stage of
a deep residual MLP, microbatches flow through neighbor ppermute hops
(horovod_tpu.parallel.pipeline), and jax.grad OUTSIDE the shard_map
derives the backward schedule (the prescribed grad placement — see the
pipeline_apply docstring).

Run (8 virtual chips → 8 pipeline stages):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/jax/jax_pipeline_mlp.py
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.parallel.pipeline import pipeline_apply


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--microbatches", type=int, default=16)
    p.add_argument("--microbatch-size", type=int, default=8)
    p.add_argument("--width", type=int, default=64)
    p.add_argument("--steps", type=int, default=30)
    args = p.parse_args()

    hvd.init()
    n = hvd.size()
    devices = hvd.world_mesh().devices.reshape(-1)
    pp_mesh = Mesh(devices, ("pp",))
    m, mb, d = args.microbatches, args.microbatch_size, args.width

    # one residual tanh stage per device: params (stages, 2, d, d)
    rng = np.random.RandomState(0)
    ws = jnp.asarray(rng.randn(n, 2, d, d).astype(np.float32)
                     * (0.5 / np.sqrt(d)))

    def stage(w, h):
        w1, w2 = w[0, 0], w[0, 1]  # per-rank shard: stage dim of 1
        return h + jnp.tanh(h @ w1) @ w2

    # grad OUTSIDE the shard_map (prescribed; grad-inside yields
    # incorrect stage grads)
    fwd = jax.shard_map(
        lambda w, x: pipeline_apply(stage, w, x, num_microbatches=m,
                                    axis="pp"),
        mesh=pp_mesh, in_specs=(P("pp"), P()), out_specs=P(),
        check_vma=False,
    )

    def loss_fn(w, x, y):
        return ((fwd(w, x) - y) ** 2).mean()

    optimizer = optax.adam(3e-3)
    opt_state = optimizer.init(ws)

    @jax.jit
    def train_step(w, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(w, x, y)
        updates, opt_state = optimizer.update(grads, opt_state, w)
        return optax.apply_updates(w, updates), opt_state, loss

    # regression target: a fixed random rotation of the input
    x = jnp.asarray(rng.randn(m, mb, d).astype(np.float32))
    rot = np.linalg.qr(rng.randn(d, d))[0].astype(np.float32)
    y = jnp.asarray(np.asarray(x) @ rot)

    ws, opt_state, loss0 = train_step(ws, opt_state, x, y)
    jax.block_until_ready(loss0)  # compile
    t0 = time.perf_counter()
    losses = [float(loss0)]
    for _ in range(args.steps):
        ws, opt_state, loss = train_step(ws, opt_state, x, y)
        losses.append(float(loss))
    dt = time.perf_counter() - t0

    if hvd.rank() == 0:
        print(f"pp={n} stages, {m} microbatches x {mb}: "
              f"loss {losses[0]:.4f} -> {losses[-1]:.4f} "
              f"({args.steps} steps, {dt / args.steps * 1e3:.1f} ms/step)")
        assert losses[-1] < 0.5 * losses[0], "pipeline training not learning"


if __name__ == "__main__":
    main()
