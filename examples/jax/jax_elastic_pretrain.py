"""Elastic decoder-only LM pretraining (toy-scale Llama-pretrain analog).

Reference parity: BASELINE.md's tracked "elastic Llama-7B pretrain with
dynamic pod resize" config — the same structure (causal-LM loss, AdamW,
DistributedOptimizer gradient averaging, elastic commit/restore/sync with
an ElasticSampler over the corpus) at a size that runs anywhere.  Scale
up by swapping ``gpt_tiny`` for ``llama_7b`` (models/transformer.py) and
sharding the step over a mesh (docs/long-context.md).

Run:  tpurun -np 2 --min-np 1 --max-np 4 \
          --host-discovery-script ./discover.sh \
          python examples/jax/jax_elastic_pretrain.py
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models.transformer import Transformer, gpt_tiny


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--docs", type=int, default=256)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--commit-every", type=int, default=8)
    args = ap.parse_args()

    hvd.init()

    # Synthetic corpus: deterministic "documents" with local structure
    # (next token depends on the previous one) so the LM loss has
    # something to learn and falls measurably within an epoch.
    rs = np.random.RandomState(0)
    starts = rs.randint(0, 256, size=(args.docs, 1))
    steps = rs.randint(1, 4, size=(args.docs, args.seq_len))
    corpus = (np.cumsum(np.concatenate([starts, steps], axis=1), axis=1)
              % 256).astype(np.int32)  # (docs, seq_len+1)

    cfg = gpt_tiny()
    assert args.seq_len <= cfg.max_seq_len, "raise gpt_tiny max_seq_len"
    model = Transformer(cfg)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, args.seq_len), jnp.int32))
    # DistributedOptimizer: grads are averaged across the CURRENT world
    # before AdamW sees them — exactly the reference's wrapper contract,
    # and it keeps working as the world resizes.
    optimizer = hvd.DistributedOptimizer(optax.adamw(1e-2))

    sampler = hvd.elastic.ElasticSampler(len(corpus), shuffle=True)
    # first_loss lives IN the committed state: recovery is exec-restart
    # (docs/elastic.md), so a module-level variable would re-capture from
    # an already-trained batch after a fault and skew the final check
    state = hvd.elastic.TpuState(
        params=variables["params"],
        opt_state=optimizer.init(variables["params"]),
        sampler=sampler, epoch=0, batch=0, first_loss=-1.0,
    )

    @jax.jit
    def grad_step(params, tokens, targets):
        def loss_fn(p):
            logits = model.apply({"params": p}, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, targets).mean()

        return jax.value_and_grad(loss_fn)(params)

    @hvd.elastic.run
    def train(state):
        while state.epoch < args.epochs:
            if state.sampler.epoch != state.epoch:
                # entering a NEW epoch; on mid-epoch resume the restored
                # sampler already carries this epoch's progress
                state.sampler.set_epoch(state.epoch)
            indices = list(state.sampler)
            state.batch = 0
            loss = None  # this rank's shard can be empty (world > docs)
            while state.batch * args.batch_size < len(indices):
                lo = state.batch * args.batch_size
                idx = indices[lo:lo + args.batch_size]
                if not idx:
                    break
                seqs = corpus[idx]
                tokens = jnp.asarray(seqs[:, :-1])
                targets = jnp.asarray(seqs[:, 1:])
                loss, grads = grad_step(state.params, tokens, targets)
                # eager update => the wrapped optimizer's allreduce rides
                # the negotiated path across the current world
                updates, state.opt_state = optimizer.update(
                    grads, state.opt_state, state.params)
                state.params = optax.apply_updates(state.params, updates)
                if state.first_loss < 0:
                    state.first_loss = float(loss)
                state.sampler.record_batch(state.batch, args.batch_size)
                state.batch += 1
                if state.batch % args.commit_every == 0:
                    state.commit()
            state.batch = 0
            state.epoch += 1
            state.sampler.set_epoch(state.epoch)
            state.commit()
            if hvd.rank() == 0 and loss is not None:
                print(f"epoch {state.epoch} done (world={hvd.cross_size()}, "
                      f"loss={float(loss):.3f})")

    train(state)

    final = float(loss_of(model, state.params, corpus, args))
    if hvd.rank() == 0:
        if state.first_loss < 0:
            # ElasticSampler shards evenly (docs // world per rank), so a
            # world larger than the corpus trains zero batches everywhere
            print("no batches ran (docs < world size?); nothing to check")
            return
        print(f"first-batch loss {state.first_loss:.3f} "
              f"-> corpus loss {final:.3f}")
        # a 20% drop needs ~2 epochs at this scale; shorter runs only
        # have to improve at all
        factor = 0.8 if args.epochs >= 2 else 1.0
        assert final < state.first_loss * factor, (state.first_loss, final)
        print("ELASTIC_PRETRAIN_OK")


def loss_of(model, params, corpus, args):
    tokens = jnp.asarray(corpus[:64, :-1])
    targets = jnp.asarray(corpus[:64, 1:])
    logits = model.apply({"params": params}, tokens)
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, targets).mean()


if __name__ == "__main__":
    main()
