"""Synthetic-data training benchmark, the reference's headline example.

Reference parity: examples/pytorch/pytorch_synthetic_benchmark.py and
examples/tensorflow2/tensorflow2_synthetic_benchmark.py — same protocol
(synthetic ImageNet-shaped batches, warmup then timed iterations, report
img/sec per worker and total) on the TPU-native stack: the whole train
step (fwd, bwd, fused gradient allreduce, update) is ONE compiled XLA
program over the world mesh.

    python examples/jax/jax_synthetic_benchmark.py --model ResNet50
    tpurun -np 2 python examples/jax/jax_synthetic_benchmark.py  # CPU demo

``--data npy --data-path DIR`` (or ``--data folder``) feeds the step
through the ``horovod_tpu.data`` pipeline — per-rank sharded on-disk
arrays, worker-pool decode, double-buffered device prefetch — and prints
the pipeline's input-wait stats next to img/sec (docs/DATA.md).
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import data as hvd_data, models, training


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="ResNet50",
                   help="ResNet18/34/50/101/152 or ResNetTiny")
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-worker batch size (reference default)")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-iters", type=int, default=10,
                   help="timed iterations per measurement")
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--stem", default="space_to_depth",
                   choices=["conv", "space_to_depth"])
    p.add_argument("--data", default="synthetic",
                   choices=["synthetic", "npy", "folder"],
                   help="synthetic = device-resident; npy/folder stream "
                        "through the horovod_tpu.data pipeline")
    p.add_argument("--data-path", default=None)
    args = p.parse_args()

    hvd.init()
    model_cls = getattr(models, args.model)
    kwargs = {"dtype": jnp.bfloat16}
    if "Tiny" not in args.model:
        kwargs.update(num_classes=1000, stem=args.stem)
    model = model_cls(**kwargs)

    # per-worker means per-chip: the compiled step shards the global
    # batch over every chip of the world mesh (training.py P(axis))
    global_batch = args.batch_size * max(hvd.size(), 1)
    loader = None
    if args.data == "synthetic":
        images = jnp.asarray(
            np.random.RandomState(0)
            .randn(global_batch, args.image_size, args.image_size, 3)
            .astype(np.float32)
        )
        labels = jnp.asarray(
            np.random.RandomState(1).randint(0, 1000, size=(global_batch,))
        )
    else:
        # the drop-in loader, prefetched to device (docs/DATA.md).  The
        # compiled step takes the GLOBAL batch, and like the resident
        # path every process supplies it whole — so the loader is pinned
        # to the un-sharded spec here (per-rank sharding pairs with
        # per-process global-array assembly, out of scope for this demo)
        loader = hvd_data.make_loader(
            args.data, args.data_path, batch_size=global_batch,
            image_size=args.image_size,
            shard=hvd_data.ShardSpec(0, 1))
        if len(loader) == 0:
            raise SystemExit(
                f"dataset too small: needs >= {global_batch} samples "
                f"for one global batch")
        images, labels = next(iter(loader))
    optimizer = optax.sgd(0.01, momentum=0.9)
    state = training.create_train_state(
        model, optimizer, jax.random.PRNGKey(0), images[:2]
    )
    state = training.replicate_state(state)
    step = training.data_parallel_train_step(model, optimizer)

    loss = jnp.zeros(())
    for _ in range(args.warmup):
        state, loss = step(state, images, labels)
    float(loss)  # the only sync some remote backends honor

    if hvd.rank() == 0:
        print(f"Model: {args.model}, batch {args.batch_size}/worker, "
              f"{hvd.size()} workers")
    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        if loader is None:
            for _ in range(args.num_batches_per_iter):
                state, loss = step(state, images, labels)
            float(loss)
            n_batches = args.num_batches_per_iter
        else:
            state, loss = training.fit_epoch(step, state, loader, epoch=i)
            n_batches = max(len(loader), 1)
        dt = time.perf_counter() - t0
        rate = global_batch * n_batches / dt
        img_secs.append(rate)
        if hvd.rank() == 0:
            extra = (f"  (input wait "
                     f"{loader.stats().get('input_wait_ms_mean', 0)} "
                     "ms/batch)") if loader is not None else ""
            print(f"Iter #{i}: {rate:.1f} img/sec total{extra}")
    if hvd.rank() == 0:
        mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
        print(f"Img/sec total: {mean:.1f} +- {conf:.1f}")
        print(f"Img/sec per worker: {mean / hvd.size():.1f}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
