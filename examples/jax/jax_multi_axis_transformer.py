"""Multi-axis transformer pretraining: dp × sp × tp on one mesh.

No reference analog (SURVEY.md §2.6: TP/SP absent upstream) — this is the
framework's flagship composition: Megatron tensor parallelism × Ulysses
sequence parallelism × data parallelism, one compiled program.

Run (8 virtual chips → dp2 × sp2 × tp2):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/jax/jax_multi_axis_transformer.py
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.parallel import sharded as sh


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--sp", type=int, default=2)
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--seq", type=int, default=256)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--remat", default=None,
                   help="activation-remat policy per block "
                        "(none/dots/dots_no_batch/full — docs/OPTIM.md)")
    args = p.parse_args()

    hvd.init()
    mesh = sh.multi_axis_mesh(dp=args.dp, sp=args.sp, tp=args.tp)
    model = sh.MultiAxisTransformer(
        vocab=1024, d_model=args.d_model, num_heads=args.heads,
        num_layers=args.layers, seq_len=args.seq, dtype=jnp.bfloat16,
        remat_policy=args.remat,
    )
    variables, specs = sh.init_sharded(
        model, mesh, jax.random.PRNGKey(0), local_batch=2
    )
    optimizer = optax.adamw(3e-4)
    opt_state, ospecs = sh.init_opt_sharded(
        optimizer, variables, mesh, specs
    )
    step = sh.make_sharded_train_step(model, optimizer, mesh, specs,
                                      ospecs)

    rng = np.random.RandomState(0)
    batch = 2 * args.dp
    tok = jnp.asarray(rng.randint(0, 1024, (batch, args.seq)))
    tgt = jnp.asarray(np.roll(np.asarray(tok), -1, axis=1))

    variables, opt_state, loss = step(variables, opt_state, tok, tgt)
    jax.block_until_ready(loss)  # compile
    t0 = time.perf_counter()
    for i in range(args.steps):
        variables, opt_state, loss = step(variables, opt_state, tok, tgt)
        if i % 5 == 0 and hvd.rank() == 0:
            print(f"step {i}: loss {float(loss):.4f}")
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / args.steps
    if hvd.rank() == 0:
        tokens = batch * args.seq
        print(f"{dt * 1e3:.1f} ms/step, {tokens / dt:.0f} tokens/sec "
              f"(dp{args.dp} sp{args.sp} tp{args.tp})")


if __name__ == "__main__":
    main()
