#!/usr/bin/env python
"""Minimum end-to-end slice: MLP classification, data-parallel on the mesh.

Reference analog: examples/pytorch/pytorch_mnist.py (BASELINE.md tracked
config) — hvd.init, shard the data by worker, DistributedOptimizer,
rank-0-only logging.  Uses a synthetic MNIST-shaped dataset so it runs in
any sandbox (the reference's examples download real MNIST; swap in your
data pipeline's arrays to do the same).

Run on a virtual 8-chip mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/jax/jax_mnist.py --epochs 3
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import training
from horovod_tpu.models.simple import MLP


def synthetic_mnist(n=2048, seed=0):
    rng = np.random.RandomState(seed)
    # 10 gaussian blobs in pixel space -> learnable synthetic task
    centers = rng.randn(10, 28 * 28).astype(np.float32)
    labels = rng.randint(0, 10, size=n)
    images = centers[labels] + 0.3 * rng.randn(n, 28 * 28).astype(np.float32)
    return images.reshape(n, 28, 28, 1), labels.astype(np.int32)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=256,
                        help="global batch (split across workers)")
    parser.add_argument("--lr", type=float, default=0.01)
    args = parser.parse_args()

    hvd.init()
    if hvd.rank() == 0:
        print(f"workers={hvd.size()} backend={jax.default_backend()}")

    images, labels = synthetic_mnist()
    # reference pattern: scale LR by world size (examples/pytorch_mnist.py)
    optimizer = optax.sgd(args.lr * hvd.size(), momentum=0.9)
    model = MLP()
    state = training.create_train_state(
        model, optimizer, jax.random.PRNGKey(42), jnp.asarray(images[:2])
    )
    state = training.replicate_state(state)
    step = training.data_parallel_train_step(model, optimizer)

    n = images.shape[0]
    bs = args.batch_size
    for epoch in range(args.epochs):
        perm = np.random.RandomState(epoch).permutation(n)
        epoch_loss, t0 = 0.0, time.perf_counter()
        batches = 0
        for i in range(0, n - bs + 1, bs):
            idx = perm[i:i + bs]
            state, loss = step(
                state, jnp.asarray(images[idx]), jnp.asarray(labels[idx])
            )
            epoch_loss += float(loss)
            batches += 1
        if hvd.rank() == 0:
            print(
                f"epoch {epoch}: loss={epoch_loss / batches:.4f} "
                f"({time.perf_counter() - t0:.2f}s)"
            )

    # eval accuracy on the training blobs (sanity: should be ~1.0)
    logits = model.apply({"params": state.params}, jnp.asarray(images))
    acc = float((jnp.argmax(logits, -1) == jnp.asarray(labels)).mean())
    if hvd.rank() == 0:
        print(f"final accuracy: {acc:.4f}")
        assert acc > 0.9, "training failed to converge"


if __name__ == "__main__":
    main()
