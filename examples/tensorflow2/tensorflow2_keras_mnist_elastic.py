"""Elastic Keras MNIST-style training.

Reference analog: examples/elastic/tensorflow2/tensorflow2_keras_mnist_elastic.py
— model.fit inside an ``hvd.elastic.run`` wrapper with KerasState and the
commit/epoch-tracking callbacks; membership changes keep state and resume
from ``state.epoch``.  Synthetic MNIST-shaped data (no downloads).

Run:  tpurun -np 2 --min-np 1 --max-np 4 \
          --host-discovery-script ./discover.sh \
          python examples/tensorflow2/tensorflow2_keras_mnist_elastic.py
"""

import argparse
import os

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import numpy as np  # noqa: E402
import keras  # noqa: E402

import horovod_tpu.keras as hvd  # noqa: E402


def synthetic_mnist(n, seed):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, size=(n,))
    for i, label in enumerate(y):
        x[i, 2 * label: 2 * label + 3, :5] += 2.0
    return x, y


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.05)
    args = parser.parse_args()

    hvd.init()
    x, y = synthetic_mnist(2048, seed=hvd.cross_rank())

    keras.utils.set_random_seed(42)
    model = keras.Sequential([
        keras.Input(shape=(28, 28, 1)),
        keras.layers.Conv2D(16, 3, activation="relu"),
        keras.layers.MaxPooling2D(2),
        keras.layers.Flatten(),
        keras.layers.Dense(64, activation="relu"),
        keras.layers.Dense(10),
    ])
    opt = hvd.DistributedOptimizer(
        keras.optimizers.SGD(args.lr * hvd.cross_size(), momentum=0.9)
    )
    model.compile(
        optimizer=opt,
        loss=keras.losses.SparseCategoricalCrossentropy(from_logits=True),
        metrics=["accuracy"],
    )

    # KerasState captures model + optimizer; sync() broadcasts them from
    # rank 0 after every (re-)rendezvous, so no broadcast callback needed
    state = hvd.elastic.KerasState(model, batch=0, epoch=0)

    callbacks = [
        hvd.elastic.CommitStateCallback(state, batches_per_commit=8),
        hvd.elastic.UpdateBatchStateCallback(state),
        hvd.elastic.UpdateEpochStateCallback(state),
    ]

    @hvd.elastic.run
    def train(state):
        model.fit(
            x, y,
            batch_size=args.batch_size,
            epochs=args.epochs,
            initial_epoch=state.epoch,  # resume where the commit left off
            callbacks=callbacks,
            verbose=2 if hvd.rank() == 0 else 0,
        )

    train(state)

    if hvd.rank() == 0:
        _, acc = model.evaluate(x, y, verbose=0)
        print(f"final accuracy: {acc:.4f}")
        assert acc > 0.8, acc
        print("KERAS_ELASTIC_OK")


if __name__ == "__main__":
    main()
