"""Keras MNIST-style training with horovod_tpu.

Reference analog: examples/tensorflow2/tensorflow2_keras_mnist.py — the
canonical Keras usage: DistributedOptimizer, broadcast + metric-average
callbacks, per-rank data shard, rank-0 checkpointing.  Synthetic
MNIST-shaped data (this image has no dataset downloads).

Run:  tpurun -np 2 python examples/tensorflow2/tensorflow2_keras_mnist.py
Or single process: python examples/tensorflow2/tensorflow2_keras_mnist.py
"""

import argparse
import os

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import numpy as np  # noqa: E402
import keras  # noqa: E402

import horovod_tpu.keras as hvd  # noqa: E402


def synthetic_mnist(n, seed):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, size=(n,))
    # make the labels learnable: brighten a label-dependent patch
    for i, label in enumerate(y):
        x[i, 2 * label: 2 * label + 3, :5] += 2.0
    return x, y


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.05)
    args = parser.parse_args()

    hvd.init()

    # per-rank shard (reference: shard by hvd.rank() of hvd.size())
    x, y = synthetic_mnist(4096, seed=hvd.cross_rank())

    keras.utils.set_random_seed(42)  # identical init everywhere
    model = keras.Sequential([
        keras.Input(shape=(28, 28, 1)),
        keras.layers.Conv2D(16, 3, activation="relu"),
        keras.layers.MaxPooling2D(2),
        keras.layers.Flatten(),
        keras.layers.Dense(64, activation="relu"),
        keras.layers.Dense(10),
    ])

    # scale LR by world size, warm it up (reference recipe)
    opt = hvd.DistributedOptimizer(
        keras.optimizers.SGD(args.lr * hvd.cross_size(), momentum=0.9)
    )
    model.compile(
        optimizer=opt,
        loss=keras.losses.SparseCategoricalCrossentropy(from_logits=True),
        metrics=["accuracy"],
    )

    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(
            target_lr=args.lr * hvd.cross_size(), warmup_epochs=1,
            steps_per_epoch=len(x) // args.batch_size,
        ),
    ]
    verbose = 1 if hvd.rank() == 0 else 0
    hist = model.fit(x, y, batch_size=args.batch_size, epochs=args.epochs,
                     callbacks=callbacks, verbose=verbose)

    if hvd.rank() == 0:
        model.save("/tmp/hvd_tpu_keras_mnist.keras")
        final_acc = hist.history["accuracy"][-1]
        print(f"final accuracy: {final_acc:.4f}")
        assert final_acc > 0.5, "synthetic MNIST should be learnable"


if __name__ == "__main__":
    main()
