"""TF2 synthetic benchmark with DistributedGradientTape.

Reference analog: examples/tensorflow2/tensorflow2_synthetic_benchmark.py
— the script the reference docs point at for measuring img/sec: synthetic
image batches, timed steps, per-worker and total throughput.  A compact
conv net stands in for its Keras ResNet50 (the TPU-native ResNet50
benchmark is the repo-root bench.py; this example exercises the TF
adapter path end to end).

Run:  tpurun -np 2 python examples/tensorflow2/tensorflow2_synthetic_benchmark.py
"""

import argparse
import os
import time

os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import numpy as np  # noqa: E402
import tensorflow as tf  # noqa: E402
import keras  # noqa: E402

import horovod_tpu.tensorflow as hvd  # noqa: E402


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--image-size", type=int, default=64)
    parser.add_argument("--num-iters", type=int, default=10)
    parser.add_argument("--num-warmup", type=int, default=3)
    args = parser.parse_args()

    hvd.init()

    keras.utils.set_random_seed(1)
    model = keras.Sequential([
        keras.Input(shape=(args.image_size, args.image_size, 3)),
        keras.layers.Conv2D(32, 3, strides=2, activation="relu"),
        keras.layers.Conv2D(64, 3, strides=2, activation="relu"),
        keras.layers.GlobalAveragePooling2D(),
        keras.layers.Dense(100),
    ])
    opt = keras.optimizers.SGD(0.01)
    loss_fn = keras.losses.SparseCategoricalCrossentropy(from_logits=True)

    rng = np.random.RandomState(hvd.cross_rank())
    data = tf.constant(rng.rand(
        args.batch_size, args.image_size, args.image_size, 3
    ).astype(np.float32))
    target = tf.constant(rng.randint(0, 100, size=(args.batch_size,)))

    hvd.broadcast_variables(model.variables, root_rank=0)

    def step():
        with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
            loss = loss_fn(target, model(data, training=True))
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        return loss

    for _ in range(args.num_warmup):
        step()

    t0 = time.perf_counter()
    for _ in range(args.num_iters):
        step()
    dt = time.perf_counter() - t0

    img_sec = args.batch_size * args.num_iters / dt
    total = np.asarray(hvd.allreduce(
        tf.constant([img_sec]), op=hvd.Sum, name="img_sec_total"
    ))[0]
    if hvd.rank() == 0:
        print(f"Img/sec per worker: {img_sec:.1f}")
        print(f"Total img/sec on {hvd.cross_size()} worker(s): {total:.1f}")


if __name__ == "__main__":
    main()
