"""Keras estimator example: DataFrame in, trained Transformer out.

Reference analog: examples/spark/keras/keras_spark_mnist.py — the
estimator contract (`fit(df)` → model with `transform`).  Runs without
pyspark: fit() accepts a pandas DataFrame, a dict of arrays, or (shown
here) any iterable of row-chunks — the fully streaming input path,
where the driver's memory high-water is one chunk + one filling shard
per worker (spark/sharding.py).  With pyspark installed, pass a Spark
DataFrame instead; it streams through toLocalIterator the same way.

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    python examples/spark/keras_spark_estimator.py
"""

import argparse
import os
import tempfile

import numpy as np


def synthetic_chunks(n_chunks=20, rows=256, seed=0):
    """A stream of row-chunks: y = x @ w + noise (never materialized
    as one array — stands in for a larger-than-memory table)."""
    rng = np.random.RandomState(seed)
    w = np.asarray([0.5, -2.0, 1.0, 3.0], np.float32)
    for _ in range(n_chunks):
        x = rng.randn(rows, 4).astype(np.float32)
        yield {
            "features": x,
            "label": (x @ w + 0.01 * rng.randn(rows)).astype(np.float32),
        }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-proc", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--work-dir", default=None)
    args = ap.parse_args()

    import keras

    from horovod_tpu.spark import LocalStore
    from horovod_tpu.spark.keras import KerasEstimator

    work = args.work_dir or tempfile.mkdtemp(prefix="hvd_spark_example_")
    keras.utils.set_random_seed(0)
    model = keras.Sequential([
        keras.Input(shape=(4,)),
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(1),
    ])
    est = KerasEstimator(
        model=model,
        optimizer=keras.optimizers.SGD(0.05),
        loss="mse",
        store=LocalStore(work),
        batch_size=64,
        epochs=args.epochs,
        num_proc=args.num_proc,
        validation=0.1,
        shard_rows=1024,  # small shards: workers stream one at a time
    )
    trained = est.fit(synthetic_chunks())
    print(f"run_id={est.run_id} store={work}")
    print("train loss per epoch:", [round(v, 4) for v in
                                    trained.history["loss"]])
    print("val loss per epoch:  ", [round(v, 4) for v in
                                    trained.history["val_loss"]])

    probe = next(synthetic_chunks(n_chunks=1, rows=8, seed=99))
    out = trained.transform(probe)
    err = float(np.mean((out["label__output"].ravel() - probe["label"])
                        ** 2))
    print(f"holdout mse: {err:.4f}")
    assert err < 0.5, err
    return 0


if __name__ == "__main__":
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    raise SystemExit(main())
