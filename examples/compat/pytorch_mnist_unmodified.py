"""A reference-STYLE torch training script with UNCHANGED imports.

This file is written the way a Horovod user writes theirs (reference:
the horovod examples' pytorch_mnist.py pattern — SURVEY.md §2.3 public
surface): ``import horovod.torch as hvd``, ``hvd.init()``,
``hvd.DistributedOptimizer``, ``broadcast_parameters``/
``broadcast_optimizer_state``, metric averaging via ``hvd.allreduce`` —
and it must run under ``horovodrun -np N python <this file>`` with ZERO
edits on the TPU backend (the ``horovod`` alias package +
``horovodrun`` console script make that literal; BASELINE.md north
star).  The model/data are synthetic so the script is self-contained.
"""

import argparse

import torch
import torch.nn as nn
import torch.nn.functional as F
import torch.utils.data

import horovod.torch as hvd


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(784, 128)
        self.fc2 = nn.Linear(128, 10)

    def forward(self, x):
        x = F.relu(self.fc1(x.view(x.shape[0], -1)))
        return F.log_softmax(self.fc2(x), dim=1)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--lr", type=float, default=0.05)
    args = parser.parse_args()

    hvd.init()
    torch.manual_seed(1234)

    # synthetic separable "MNIST": class k lights up pixel block k
    n = 512
    labels = torch.randint(0, 10, (n,))
    data = 0.05 * torch.randn(n, 1, 28, 28)
    for i in range(n):
        k = int(labels[i])
        data[i, 0, k * 2:(k + 1) * 2, :] += 1.0

    dataset = torch.utils.data.TensorDataset(data, labels)
    sampler = torch.utils.data.distributed.DistributedSampler(
        dataset, num_replicas=hvd.size(), rank=hvd.rank())
    loader = torch.utils.data.DataLoader(
        dataset, batch_size=args.batch_size, sampler=sampler)

    model = Net()
    optimizer = torch.optim.SGD(model.parameters(),
                                lr=args.lr * hvd.size())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())

    for epoch in range(args.epochs):
        sampler.set_epoch(epoch)
        model.train()
        for batch, target in loader:
            optimizer.zero_grad()
            loss = F.nll_loss(model(batch), target)
            loss.backward()
            optimizer.step()

    model.eval()
    with torch.no_grad():
        pred = model(data).argmax(dim=1)
        acc = (pred == labels).float().mean()
    # metric averaging across ranks, the reference idiom
    acc = hvd.allreduce(acc, name="avg_accuracy")
    if hvd.rank() == 0:
        print(f"UNMODIFIED_OK accuracy={float(acc):.3f} "
              f"world={hvd.size()}")
        assert float(acc) > 0.85, float(acc)


if __name__ == "__main__":
    main()
