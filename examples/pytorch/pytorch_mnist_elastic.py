"""Elastic MNIST with the torch adapter.

Reference parity: examples/elastic/pytorch/pytorch_mnist_elastic.py —
the commit/restore/sync elastic loop (SURVEY.md §3.4) over a torch
model: ``TorchState(model=..., optimizer=...)``, an ``ElasticSampler``
that reshards remaining work on every membership change, and
``@hvd.elastic.run`` wrapping the epoch loop.

Run::

    tpurun --min-np 1 --max-np 4 \
        --host-discovery-script ./discover.sh \
        python examples/pytorch/pytorch_mnist_elastic.py

where discover.sh prints the current "host:slots" lines.  Uses
synthetic MNIST-shaped data (no dataset download in this image).
"""

import argparse

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(784, 128)
        self.fc2 = nn.Linear(128, 10)

    def forward(self, x):
        return F.log_softmax(self.fc2(F.relu(self.fc1(x))), dim=1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--n", type=int, default=4096)
    args = ap.parse_args()

    hvd.init()
    rng = np.random.RandomState(0)
    images = torch.from_numpy(rng.randn(args.n, 784).astype(np.float32))
    labels = torch.from_numpy(rng.randint(0, 10, size=(args.n,)))

    model = Net()
    optimizer = torch.optim.SGD(
        model.parameters(), lr=0.01 * hvd.cross_size(), momentum=0.9
    )
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters()
    )

    sampler = hvd.elastic.ElasticSampler(args.n, shuffle=True)
    state = hvd.elastic.TorchState(
        model=model, optimizer=optimizer, sampler=sampler,
        epoch=0, batch=0,
    )

    def on_reset():
        # keep the linear-scaling rule in force across resizes
        for g in optimizer.param_groups:
            g["lr"] = 0.01 * hvd.cross_size()
        print(f"[rank {hvd.cross_rank()}] world resized to "
              f"{hvd.cross_size()}; lr -> {0.01 * hvd.cross_size():.3f}",
              flush=True)

    state.register_reset_callbacks([on_reset])

    @hvd.elastic.run
    def train(state):
        loss = torch.zeros(())  # defined even if a resumed epoch is empty
        while state.epoch < args.epochs:
            if state.sampler.epoch != state.epoch:
                # entering a NEW epoch.  On a mid-epoch resume/resize the
                # restored sampler already carries this epoch's progress;
                # set_epoch would wipe it and a stale batch offset would
                # slice a shard computed for the new world.
                state.sampler.set_epoch(state.epoch)
            # this rank's REMAINING shard for the current world; batch
            # indices restart at 0 relative to it on every (re)entry
            indices = list(state.sampler)
            state.batch = 0
            while state.batch * args.batch_size < len(indices):
                lo = state.batch * args.batch_size
                take = indices[lo:lo + args.batch_size]
                if not take:
                    break
                x, y = images[take], labels[take]
                optimizer.zero_grad()
                loss = F.nll_loss(model(x), y)
                loss.backward()
                optimizer.step()
                state.sampler.record_batch(state.batch, args.batch_size)
                state.batch += 1
                if state.batch % 10 == 0:
                    state.commit()
            if hvd.cross_rank() == 0:
                print(f"epoch {state.epoch}: loss={float(loss):.4f} "
                      f"world={hvd.cross_size()}", flush=True)
            state.epoch += 1
            state.batch = 0
            state.sampler.set_epoch(state.epoch)
            state.commit()
        return float(loss)

    final = train(state)
    if hvd.cross_rank() == 0:
        print(f"final loss: {final:.4f}", flush=True)


if __name__ == "__main__":
    main()
