"""PyTorch synthetic benchmark through the torch adapter.

Reference analog: examples/pytorch/pytorch_synthetic_benchmark.py — THE
script the reference's docs point at for img/sec measurements (and the
source of BASELINE.md's ~330 img/s V100 figure).  Same protocol: a conv
net on synthetic batches, warmup then timed iterations, per-worker and
total throughput printed by rank 0.

The torch adapter is a CPU bridge (TPU compute is the JAX surface), so
absolute numbers here measure the adapter path, not the chip — bench.py
is the TPU-native headline.

Run:  tpurun -np 2 python examples/pytorch/pytorch_synthetic_benchmark.py

``--data npy --data-path DIR`` feeds real on-disk arrays through the
``horovod_tpu.data`` pipeline instead of a resident synthetic batch:
``device_put=False`` makes the loader yield host numpy batches (sharded
per rank, decoded on the worker pool, prefetched one batch ahead) and
``torch.from_numpy`` wraps them zero-copy — the drop-in loader pattern
for every torch script (see docs/DATA.md).
"""

import argparse
import time

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd
from horovod_tpu import data as hvd_data


class SmallConvNet(nn.Module):
    def __init__(self, num_classes=100):
        super().__init__()
        self.c1 = nn.Conv2d(3, 32, 3, stride=2)
        self.c2 = nn.Conv2d(32, 64, 3, stride=2)
        self.fc = nn.Linear(64, num_classes)

    def forward(self, x):
        x = F.relu(self.c1(x))
        x = F.relu(self.c2(x))
        x = x.mean(dim=(2, 3))
        return self.fc(x)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--image-size", type=int, default=64)
    parser.add_argument("--num-iters", type=int, default=10)
    parser.add_argument("--num-warmup", type=int, default=3)
    parser.add_argument("--data", default="synthetic",
                        choices=["synthetic", "npy", "folder"])
    parser.add_argument("--data-path", default=None,
                        help="dataset root for --data npy/folder")
    args = parser.parse_args()

    hvd.init()
    torch.manual_seed(0)
    model = SmallConvNet()
    optimizer = torch.optim.SGD(model.parameters(), lr=0.01)

    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters()
    )

    if args.data == "synthetic":
        rng = np.random.RandomState(hvd.cross_rank())
        batches = None
        data = torch.as_tensor(rng.rand(
            args.batch_size, 3, args.image_size, args.image_size
        ).astype(np.float32))
        target = torch.as_tensor(
            rng.randint(0, 100, size=(args.batch_size,))
        )
    else:
        # the drop-in loader: host numpy batches (device_put=False — the
        # torch bridge owns placement), sharded per rank over the live
        # topology, worker-pool decoded, prefetched one batch ahead
        loader = hvd_data.make_loader(
            args.data, args.data_path, batch_size=args.batch_size,
            image_size=args.image_size, device_put=False)

        def batches():
            epoch = 0
            while True:
                loader.set_epoch(epoch)
                for inputs, labels in loader:
                    # NHWC (decode layout) -> NCHW, zero-copy wrap
                    yield (torch.from_numpy(
                               np.ascontiguousarray(
                                   inputs.transpose(0, 3, 1, 2))),
                           # benchmark net has 100 classes; fold labels in
                           torch.from_numpy(labels.astype(np.int64) % 100))
                epoch += 1

        batches = batches()

    def step():
        nonlocal_data = (data, target) if batches is None else next(batches)
        optimizer.zero_grad()
        loss = F.cross_entropy(model(nonlocal_data[0]), nonlocal_data[1])
        loss.backward()
        optimizer.step()

    for _ in range(args.num_warmup):
        step()
    t0 = time.perf_counter()
    for _ in range(args.num_iters):
        step()
    dt = time.perf_counter() - t0

    img_sec = args.batch_size * args.num_iters / dt
    total = hvd.allreduce(
        torch.tensor([img_sec]), op=hvd.Sum, name="img_sec_total"
    )
    if hvd.rank() == 0:
        print(f"Img/sec per worker: {img_sec:.1f}")
        print(f"Total img/sec on {hvd.cross_size()} worker(s): "
              f"{float(total[0]):.1f}")


if __name__ == "__main__":
    main()
