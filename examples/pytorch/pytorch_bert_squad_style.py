"""BERT-SQuAD-style fine-tune through the torch adapter.

Reference parity: BASELINE.md's tracked config "PyTorch BERT-Large
SQuAD fine-tune (allreduce + allgather, fp16 fusion)".  The real
BERT-Large weights/dataset are not in this image, so this exercises the
SAME collective mechanics at toy scale: a bidirectional transformer
encoder with a span-prediction head, gradients averaged through
``DistributedOptimizer(compression=Compression.fp16)`` (the fp16
fusion-path wire format), and per-rank predictions gathered with
``hvd.allgather`` for the global metric — the SQuAD eval pattern.

Run::

    tpurun -np 2 python examples/pytorch/pytorch_bert_squad_style.py
"""

import argparse

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class TinyBert(nn.Module):
    """Bidirectional encoder + span head (start/end logits)."""

    def __init__(self, vocab=1000, d_model=64, heads=4, layers=2,
                 seq_len=64):
        super().__init__()
        self.embed = nn.Embedding(vocab, d_model)
        self.pos = nn.Parameter(torch.zeros(seq_len, d_model))
        layer = nn.TransformerEncoderLayer(
            d_model, heads, dim_feedforward=4 * d_model,
            batch_first=True, dropout=0.0,
        )
        self.encoder = nn.TransformerEncoder(layer, layers)
        self.span = nn.Linear(d_model, 2)  # start/end logits

    def forward(self, tokens):
        h = self.encoder(self.embed(tokens) + self.pos[None])
        return self.span(h)  # (B, S, 2)


def synthetic_squad(n, seq_len, vocab, seed):
    """Contexts where the 'answer span' is marked by a sentinel token —
    learnable, so loss decrease proves the distributed fine-tune works."""
    rng = np.random.RandomState(seed)
    tokens = rng.randint(3, vocab, size=(n, seq_len))
    starts = rng.randint(1, seq_len - 4, size=(n,))
    ends = starts + rng.randint(1, 4, size=(n,))
    for i in range(n):
        tokens[i, starts[i]] = 1  # answer-start sentinel
        tokens[i, ends[i]] = 2    # answer-end sentinel
    return (torch.from_numpy(tokens),
            torch.from_numpy(starts), torch.from_numpy(ends))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--n", type=int, default=512)
    ap.add_argument("--seq-len", type=int, default=64)
    args = ap.parse_args()

    hvd.init()
    torch.manual_seed(0)
    tokens, starts, ends = synthetic_squad(
        args.n, args.seq_len, vocab=1000, seed=0)

    model = TinyBert(seq_len=args.seq_len)
    optimizer = torch.optim.Adam(model.parameters(),
                                 lr=1e-3 * hvd.cross_size())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)
    # fp16 compression: the reference BERT config's fused fp16 allreduce
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=hvd.Compression.fp16,
    )

    # equal-length rank shards (truncate the tail): ragged shards would
    # give ranks different optimizer-step counts and deadlock the
    # per-step gradient allreduces
    per = len(tokens) // hvd.cross_size()
    lo = hvd.cross_rank() * per
    t, s, e = (tokens[lo:lo + per], starts[lo:lo + per],
               ends[lo:lo + per])

    for epoch in range(args.epochs):
        perm = torch.randperm(len(t))
        losses = []
        for lo in range(0, len(t) - args.batch_size + 1, args.batch_size):
            idx = perm[lo:lo + args.batch_size]
            optimizer.zero_grad()
            logits = model(t[idx])  # (B, S, 2)
            loss = (F.cross_entropy(logits[..., 0], s[idx])
                    + F.cross_entropy(logits[..., 1], e[idx]))
            loss.backward()
            optimizer.step()
            losses.append(float(loss))
        mean_loss = float(hvd.allreduce(
            torch.tensor(np.mean(losses)), op=hvd.Average))
        if hvd.cross_rank() == 0:
            print(f"epoch {epoch}: loss={mean_loss:.4f} "
                  f"world={hvd.cross_size()}", flush=True)

    # SQuAD-style eval: every rank predicts its shard, predictions
    # allgather to a global exact-match score
    with torch.no_grad():
        logits = model(t)
        pred_start = logits[..., 0].argmax(dim=1)
        pred_end = logits[..., 1].argmax(dim=1)
    local = torch.stack(
        [pred_start == s, pred_end == e], dim=1).all(dim=1)
    all_match = hvd.allgather(local.to(torch.float32))
    if hvd.cross_rank() == 0:
        em = float(all_match.mean())
        print(f"global exact-match: {em:.3f} over {len(all_match)} "
              "examples", flush=True)
        assert em > 0.5, em


if __name__ == "__main__":
    main()
