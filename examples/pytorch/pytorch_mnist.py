"""MNIST with the torch adapter.

Reference parity: examples/pytorch/pytorch_mnist.py — the canonical
reference training script, unchanged in structure: hvd.init, data sharded
by rank, DistributedOptimizer with grad hooks, parameter broadcast from
rank 0, metric allreduce.  Only the import line differs.

Run: tpurun -np 2 python examples/pytorch/pytorch_mnist.py --epochs 1
(uses synthetic MNIST-shaped data when no dataset is available — this
image has no torchvision download access).
"""

import argparse

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F
import torch.utils.data

import horovod_tpu.torch as hvd


class Net(nn.Module):
    """The reference's LeNet-style MNIST model."""

    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(1, 10, kernel_size=5)
        self.conv2 = nn.Conv2d(10, 20, kernel_size=5)
        self.conv2_drop = nn.Dropout2d()
        self.fc1 = nn.Linear(320, 50)
        self.fc2 = nn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2_drop(self.conv2(x)), 2))
        x = x.view(-1, 320)
        x = F.relu(self.fc1(x))
        x = F.dropout(x, training=self.training)
        return F.log_softmax(self.fc2(x), dim=1)


def synthetic_mnist(n=2048, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 1, 28, 28).astype(np.float32)
    y = rng.randint(0, 10, size=(n,))
    return torch.utils.data.TensorDataset(
        torch.from_numpy(x), torch.from_numpy(y)
    )


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--use-adasum", action="store_true")
    p.add_argument("--fp16-allreduce", action="store_true")
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(42)

    dataset = synthetic_mnist()
    # shard the dataset by rank (reference: DistributedSampler)
    sampler = torch.utils.data.distributed.DistributedSampler(
        dataset, num_replicas=hvd.cross_size(), rank=hvd.cross_rank()
    )
    loader = torch.utils.data.DataLoader(
        dataset, batch_size=args.batch_size, sampler=sampler
    )

    model = Net()
    # scale lr by world size (reference idiom)
    optimizer = torch.optim.SGD(
        model.parameters(), lr=args.lr * hvd.cross_size(), momentum=0.5
    )
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=compression,
        op=hvd.Adasum if args.use_adasum else hvd.Average,
    )
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    model.train()
    for epoch in range(args.epochs):
        sampler.set_epoch(epoch)
        for batch_idx, (data, target) in enumerate(loader):
            optimizer.zero_grad()
            loss = F.nll_loss(model(data), target)
            loss.backward()
            optimizer.step()
            if batch_idx % 10 == 0 and hvd.rank() == 0:
                print(f"epoch {epoch} batch {batch_idx} "
                      f"loss {loss.item():.4f}")
        # averaged epoch metric (reference: metric_average helper)
        avg = hvd.allreduce(torch.tensor(loss.item()), name="avg_loss")
        if hvd.rank() == 0:
            print(f"epoch {epoch} avg loss {float(avg):.4f}")


if __name__ == "__main__":
    main()
