"""High-level state synchronization helpers.

Reference parity: horovod/torch/functions.py (broadcast_parameters,
broadcast_optimizer_state, broadcast_object) and the allgather_object
helper (SURVEY.md §2.3).  These are the primitives checkpoints-resume and
elastic ``State.sync()`` build on (SURVEY.md §5.3/§5.4).
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .common import basics
from .common.process_sets import ProcessSet
from .ops import collective_ops


def broadcast_parameters(
    params: Any, root_rank: int = 0,
    process_set: Optional[ProcessSet] = None,
) -> Any:
    """Broadcast a parameter pytree from ``root_rank`` to all workers.

    Reference: horovod/torch/functions.py broadcast_parameters — used at
    train start so every worker begins from identical weights.  Functional
    (returns the new pytree) because JAX arrays are immutable.
    """
    return collective_ops.broadcast(params, root_rank, process_set=process_set)


def broadcast_optimizer_state(
    opt_state: Any, root_rank: int = 0,
    process_set: Optional[ProcessSet] = None,
) -> Any:
    """Reference: horovod/torch/functions.py broadcast_optimizer_state.

    optax states are pytrees of arrays plus static leaves; array leaves are
    broadcast, non-array leaves (step schedules etc.) are taken from the
    local copy — they are deterministic replicas by construction.
    """
    leaves, treedef = jax.tree_util.tree_flatten(opt_state)
    array_idx = [
        i for i, l in enumerate(leaves)
        if isinstance(l, (jax.Array, np.ndarray))
    ]
    if array_idx:
        arrays = [leaves[i] for i in array_idx]
        arrays = collective_ops.broadcast(
            arrays, root_rank, process_set=process_set
        )
        for i, a in zip(array_idx, arrays):
            leaves[i] = a
    return jax.tree_util.tree_unflatten(treedef, leaves)


def broadcast_object(
    obj: Any, root_rank: int = 0, name: Optional[str] = None,
    process_set: Optional[ProcessSet] = None,
) -> Any:
    """Pickle-based object broadcast (reference: horovod/torch/mpi_ops.py
    broadcast_object: serialize on root, bcast size then payload)."""
    st = basics._require_init()
    if not st.engine.multi_process:
        return obj
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    sz = collective_ops.broadcast(
        jnp.asarray([payload.size], jnp.int32), root_rank,
        process_set=process_set,
    )
    size = int(np.asarray(sz)[0])
    # root_rank names a chip; its *owning process* supplies the payload
    # (with multiple local chips, rank() != root_rank even on the owner)
    if st.topology.owns_rank(root_rank):
        buf = payload
    else:
        buf = np.zeros(size, dtype=np.uint8)
    out = collective_ops.broadcast(
        jnp.asarray(buf), root_rank, process_set=process_set
    )
    return pickle.loads(np.asarray(out).tobytes())


def allgather_object(
    obj: Any, name: Optional[str] = None,
    process_set: Optional[ProcessSet] = None,
) -> list:
    """Reference: horovod/torch/mpi_ops.py allgather_object — returns the
    list of every worker's object."""
    st = basics._require_init()
    if not st.engine.multi_process:
        return [obj]
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    sizes = collective_ops.allgather(
        jnp.asarray([payload.size], jnp.int32), process_set=process_set
    )
    sizes = np.asarray(sizes)
    max_size = int(sizes.max())
    padded = np.zeros(max_size, dtype=np.uint8)
    padded[: payload.size] = payload
    gathered = collective_ops.allgather(
        jnp.asarray(padded)[None], process_set=process_set
    )
    gathered = np.asarray(gathered)
    return [
        pickle.loads(gathered[i, : int(sizes[i])].tobytes())
        for i in range(gathered.shape[0])
    ]
