"""Training-loop callbacks.

Reference parity: horovod/keras/callbacks.py + horovod/_keras/callbacks.py
(SURVEY.md §2.3): BroadcastGlobalVariablesCallback, MetricAverageCallback,
LearningRateWarmupCallback, LearningRateScheduleCallback — re-expressed
for the optax/flax training loop.

The learning-rate callbacks need a mutable LR.  The optax-idiomatic
equivalent is ``optax.inject_hyperparams``, which turns the learning rate
into a leaf of ``opt_state`` that can be rewritten between steps without
recompiling::

    optimizer = optax.inject_hyperparams(optax.sgd)(learning_rate=0.1)
    loop = hvd.callbacks.TrainLoop(state, callbacks=[
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.LearningRateWarmupCallback(target_lr=0.1 * hvd.size(),
                                                 warmup_epochs=5,
                                                 steps_per_epoch=100),
        hvd.callbacks.MetricAverageCallback(),
    ])
    for epoch in range(epochs):
        loop.on_epoch_begin(epoch)
        for batch, (x, y) in enumerate(loader):
            loop.on_batch_begin(batch)
            loop.state, loss = step(loop.state, x, y)
            loop.on_batch_end(batch, {"loss": float(loss)})
        logs = loop.on_epoch_end(epoch, {"loss": epoch_loss})

For fully-static schedules, prefer :func:`warmup_schedule` (a plain optax
schedule baked into the compiled step) — the TPU-native spelling.
"""

from __future__ import annotations

import math
import time as _time
from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .common import basics
from .metrics import instruments as _metrics
from .ops import collective_ops
from .ops.reduce_ops import Average

_STEP_TIME = _metrics.STEP_DURATION.labels("jax")


# -- LR plumbing -------------------------------------------------------------


def _find_hyperparams(opt_state):
    """Locate InjectHyperparams states inside an opt_state tree."""
    found = []

    def visit(node):
        hp = getattr(node, "hyperparams", None)
        if isinstance(hp, dict) and "learning_rate" in hp:
            found.append(node)
        if isinstance(node, tuple):
            for child in node:
                visit(child)

    visit(opt_state)
    return found


def get_lr(opt_state) -> float:
    nodes = _find_hyperparams(opt_state)
    if not nodes:
        raise ValueError(
            "no injected learning_rate found; build the optimizer with "
            "optax.inject_hyperparams (see horovod_tpu.callbacks docstring)"
        )
    return float(np.asarray(nodes[0].hyperparams["learning_rate"]))


def set_lr(opt_state, lr: float):
    """Return a copy of the opt_state with the injected learning-rate
    leaf replaced (functional — the input state is left untouched, so
    checkpoint snapshots and rollback copies stay valid)."""

    def rebuild(node):
        hp = getattr(node, "hyperparams", None)
        if isinstance(hp, dict) and "learning_rate" in hp and \
                hasattr(node, "_replace"):
            new_hp = dict(hp)
            new_hp["learning_rate"] = jnp.asarray(
                lr, jnp.asarray(hp["learning_rate"]).dtype
            )
            node = node._replace(hyperparams=new_hp)
        if isinstance(node, tuple):
            if hasattr(node, "_replace"):  # namedtuple: rebuild fields
                return node._replace(**{
                    f: rebuild(getattr(node, f)) for f in node._fields
                    if isinstance(getattr(node, f), tuple)
                })
            return type(node)(rebuild(c) for c in node)
        return node

    if not _find_hyperparams(opt_state):
        raise ValueError(
            "no injected learning_rate found; build the optimizer with "
            "optax.inject_hyperparams (see horovod_tpu.callbacks docstring)"
        )
    return rebuild(opt_state)


# -- loop + callback protocol ------------------------------------------------


class Callback:
    loop: "TrainLoop"

    def set_loop(self, loop: "TrainLoop") -> None:
        self.loop = loop

    def on_train_begin(self) -> None: ...

    def on_epoch_begin(self, epoch: int) -> None: ...

    def on_batch_begin(self, batch: int) -> None: ...

    def on_batch_end(self, batch: int, logs: Optional[dict] = None) -> None:
        ...

    def on_epoch_end(self, epoch: int,
                     logs: Optional[dict] = None) -> Optional[dict]: ...


class TrainLoop:
    """Thin callback host around a TrainState (stands in for the Keras
    ``model`` object the reference callbacks mutate)."""

    def __init__(self, state, callbacks: List[Callback]):
        self.state = state
        self.callbacks = callbacks
        self.epoch = 0
        self.batch = 0
        for cb in callbacks:
            cb.set_loop(self)
        self._began = False

    # lr accessors proxy into the live opt_state
    @property
    def lr(self) -> float:
        return get_lr(self.state.opt_state)

    @lr.setter
    def lr(self, value: float) -> None:
        self.state = self.state.replace(
            opt_state=set_lr(self.state.opt_state, value)
        )

    def on_epoch_begin(self, epoch: int) -> None:
        if not self._began:
            self._began = True
            for cb in self.callbacks:
                cb.on_train_begin()
        self.epoch = epoch
        for cb in self.callbacks:
            cb.on_epoch_begin(epoch)

    def on_batch_begin(self, batch: int) -> None:
        self.batch = batch
        self._batch_t0 = _time.perf_counter()
        for cb in self.callbacks:
            cb.on_batch_begin(batch)

    def on_batch_end(self, batch: int, logs: Optional[dict] = None) -> None:
        t0 = getattr(self, "_batch_t0", None)
        if t0 is not None:
            _STEP_TIME.observe(_time.perf_counter() - t0)
            self._batch_t0 = None
        for cb in self.callbacks:
            cb.on_batch_end(batch, logs)

    def on_epoch_end(self, epoch: int,
                     logs: Optional[dict] = None) -> Optional[dict]:
        for cb in self.callbacks:
            out = cb.on_epoch_end(epoch, logs)
            if out is not None:
                logs = out
        return logs


# -- the reference callbacks -------------------------------------------------


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast initial state from root so all workers start identical
    (reference: keras/callbacks.py BroadcastGlobalVariablesCallback)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self) -> None:
        from . import functions

        st = self.loop.state
        params = functions.broadcast_parameters(
            st.params, root_rank=self.root_rank
        )
        opt_state = functions.broadcast_optimizer_state(
            st.opt_state, root_rank=self.root_rank
        )
        new = st.replace(params=params, opt_state=opt_state)
        if getattr(st, "batch_stats", None) is not None:
            new = new.replace(batch_stats=functions.broadcast_parameters(
                st.batch_stats, root_rank=self.root_rank
            ))
        self.loop.state = new


class MetricAverageCallback(Callback):
    """Average epoch metrics over workers before reporting (reference:
    keras/callbacks.py MetricAverageCallback)."""

    def on_epoch_end(self, epoch: int,
                     logs: Optional[dict] = None) -> Optional[dict]:
        if not logs:
            return logs
        out = dict(logs)
        for k, v in logs.items():
            if isinstance(v, (int, float, np.floating, np.integer)) or (
                hasattr(v, "shape") and getattr(v, "shape", None) == ()
            ):
                reduced = collective_ops.allreduce(
                    jnp.asarray(float(v)), op=Average, name=f"metric.{k}"
                )
                out[k] = float(np.asarray(reduced))
        return out


class LearningRateWarmupCallback(Callback):
    """Linear LR warmup over the first epochs (reference:
    keras/callbacks.py LearningRateWarmupCallback, after Goyal et al. —
    ramp from ``target_lr / size`` to ``target_lr``, adjusted every batch
    at epoch + batch/steps_per_epoch granularity)."""

    def __init__(self, target_lr: float, warmup_epochs: float = 5,
                 steps_per_epoch: Optional[int] = None,
                 initial_lr: Optional[float] = None, verbose: bool = False):
        self.target_lr = target_lr
        self.warmup_epochs = warmup_epochs
        self.steps_per_epoch = steps_per_epoch
        self.initial_lr = initial_lr
        self.verbose = verbose
        self._current_epoch = 0

    def _initial(self) -> float:
        if self.initial_lr is not None:
            return self.initial_lr
        size = basics.size() if basics.is_initialized() else 1
        return self.target_lr / size

    def on_epoch_begin(self, epoch: int) -> None:
        self._current_epoch = epoch

    def on_batch_begin(self, batch: int) -> None:
        if self._current_epoch >= self.warmup_epochs:
            return
        if self.steps_per_epoch:
            progress = (self._current_epoch +
                        batch / self.steps_per_epoch) / self.warmup_epochs
        else:
            progress = self._current_epoch / self.warmup_epochs
        progress = min(max(progress, 0.0), 1.0)
        init = self._initial()
        self.loop.lr = init + (self.target_lr - init) * progress

    def on_epoch_end(self, epoch: int,
                     logs: Optional[dict] = None) -> Optional[dict]:
        # fires exactly on the epoch that crosses warmup_epochs — also for
        # fractional warmup_epochs (e.g. 2.5 pins the target at epoch 2)
        if epoch < self.warmup_epochs <= epoch + 1:
            self.loop.lr = self.target_lr
            if self.verbose:
                print(f"Epoch {epoch + 1}: finished gradual learning rate "
                      f"warmup to {self.target_lr}.")
        return logs


class LearningRateScheduleCallback(Callback):
    """Piecewise LR schedule (reference: keras/callbacks.py
    LearningRateScheduleCallback): within [start_epoch, end_epoch) the LR
    is ``initial_lr * multiplier(epoch)`` (or a constant multiplier)."""

    def __init__(self, initial_lr: float,
                 multiplier: Union[float, Callable[[int], float]],
                 start_epoch: int = 0, end_epoch: Optional[int] = None,
                 staircase: bool = True,
                 steps_per_epoch: Optional[int] = None):
        self.initial_lr = initial_lr
        self.multiplier = multiplier
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self._current_epoch = 0

    def _mult(self, epoch: float) -> float:
        if callable(self.multiplier):
            return self.multiplier(epoch)
        return self.multiplier

    def _in_range(self, epoch: float) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def on_epoch_begin(self, epoch: int) -> None:
        self._current_epoch = epoch
        # staircase, or smooth mode without per-batch granularity
        # available: adjust at epoch boundaries (reference behavior —
        # never silently skip the schedule)
        if (self.staircase or not self.steps_per_epoch) and \
                self._in_range(epoch):
            self.loop.lr = self.initial_lr * self._mult(epoch)

    def on_batch_begin(self, batch: int) -> None:
        if self.staircase or not self.steps_per_epoch:
            return
        epoch = self._current_epoch + batch / self.steps_per_epoch
        if self._in_range(epoch):
            self.loop.lr = self.initial_lr * self._mult(epoch)


# -- TPU-native static schedules --------------------------------------------


def warmup_schedule(target_lr: float, warmup_steps: int,
                    initial_lr: Optional[float] = None) -> optax.Schedule:
    """Optax schedule form of LearningRateWarmupCallback — bake the warmup
    into the compiled step (no host round-trip per batch)."""
    if initial_lr is None:
        initial_lr = target_lr / (
            basics.size() if basics.is_initialized() else 1
        )
    return optax.linear_schedule(initial_lr, target_lr, warmup_steps)
