"""horovod_tpu: a TPU-native distributed training framework with the
capabilities of Horovod (reference: rondogency/horovod — see SURVEY.md).

Public surface mirrors ``horovod.torch`` / ``horovod.tensorflow``
(SURVEY.md §2.3): ``init``/``shutdown``, rank/size topology queries, eager
async collectives with handles, ``DistributedOptimizer``,
``broadcast_parameters``, elastic state, process sets — plus the TPU-native
additions: the in-jit SPMD collective module (``hvd.spmd``), mesh access,
and the per-rank ``run_per_rank`` harness.

Typical JAX use::

    import horovod_tpu as hvd
    hvd.init()
    mesh = hvd.world_mesh()
    # ... shard batch over mesh axis "hvd"; inside the train step:
    grads = hvd.spmd.allreduce(grads)           # psum over ICI
    # or wrap the optimizer once:
    opt = hvd.DistributedOptimizer(optax.adam(1e-3))
"""

from __future__ import annotations

from .utils import jax_compat as _jax_compat

_jax_compat.install()  # jax.shard_map spelling on older jax images

from .common import basics as _basics
from .common.basics import (
    init,
    shutdown,
    is_initialized,
    rank,
    local_process_count,
    local_rank,
    size,
    local_size,
    cross_rank,
    cross_size,
    is_homogeneous,
    xla_built,
    nccl_built,
    mpi_enabled,
    mpi_built,
    mpi_threads_supported,
    gloo_built,
    gloo_enabled,
    ccl_built,
    cuda_built,
    rocm_built,
    ddl_built,
    native_built,
    start_timeline,
    stop_timeline,
)
from .common.exceptions import (
    HorovodInternalError,
    HostsUpdatedInterrupt,
    HorovodTpuError,
)
from .common.process_sets import ProcessSet, global_process_set
from .common.topology import WORLD_AXIS, DCN_AXIS, ICI_AXIS
from .ops import spmd_ops as spmd
from .ops.collective_ops import (
    Handle,
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    alltoall,
    alltoall_async,
    barrier,
    broadcast,
    broadcast_async,
    grouped_allgather,
    grouped_allreduce,
    grouped_allreduce_async,
    grouped_reducescatter,
    grouped_reducescatter_async,
    join,
    poll,
    reducescatter,
    reducescatter_async,
    synchronize,
)
from .ops.flash_attention import flash_attention
from .ops.reduce_ops import Adasum, Average, Max, Min, Product, ReduceOp, Sum
from .ops.spmd_ops import run_per_rank
from .functions import (
    allgather_object,
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
)
from . import callbacks, chaos, checkpoint, data, elastic, guard, metrics
from . import trace
from .compression import Compression
from .sync_batch_norm import SyncBatchNorm
from .optim import (
    DistributedOptimizer,
    ZeroDistributedOptimizer,
    ZeroSpmdOptimizer,
    allreduce_gradients,
    with_gradient_accumulation,
    zero_opt_state_specs,
)

__version__ = "0.1.0"


def add_process_set(ranks) -> ProcessSet:
    """Register a new process set (reference: horovod/common/process_sets.py
    add_process_set).  Must be called symmetrically on every process; the
    set's member processes are mirrored into the native controller so
    negotiation counts readiness against the set, not the world."""
    st = _basics._require_init()
    ps = ranks if isinstance(ranks, ProcessSet) else ProcessSet(ranks)
    ps = st.process_set_registry.add(ps)
    if st.controller is not None and st.controller.is_native:
        procs = sorted({
            getattr(st.topology.devices[r], "process_index", 0)
            for r in ps.ranks
        })
        st.controller.register_process_set(ps.process_set_id, procs)
    return ps


def remove_process_set(process_set: ProcessSet) -> None:
    """Reference: horovod/common/process_sets.py remove_process_set."""
    st = _basics._require_init()
    set_id = process_set.process_set_id
    st.process_set_registry.remove(process_set)
    if st.controller is not None and st.controller.is_native:
        st.controller.remove_process_set(set_id)


def process_set_ids():
    return _basics._require_init().process_set_registry.ids()


def world_mesh():
    """The 1-D world mesh (every chip, axis ``"hvd"``)."""
    return _basics._require_init().process_set_registry.get(0).mesh


def hierarchical_mesh(num_groups=None):
    """2-D (dcn, ici) mesh for two-level reductions (reference analog:
    local/cross communicators of NCCLHierarchicalAllreduce)."""
    return _basics._require_init().topology.hierarchical_mesh(num_groups)
