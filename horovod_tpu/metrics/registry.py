"""Process-wide metric registry: Counters, Gauges, Histograms.

Reference analog: the per-component stats the native core already keeps
(ResponseCache hit/miss counters, StallInspector pending table,
ParameterManager score samples) — generalized into the metrics-registry
shape production training stacks expose to Prometheus.  The design goals
follow the stall-inspector's: negligible hot-path cost (one dict lookup
is pre-resolved away via labeled children, one lock, one float add — no
allocation), thread-safety everywhere (metrics are bumped from the
training thread, the C++ exec callback thread, the torch submit worker
and autograd threads concurrently), and a single process-wide registry
(``REGISTRY``) so every subsystem lands in one exposition.

Locking is striped per metric child, not per registry: two threads
bumping different counters (or different label sets of one counter)
never contend; the registry-level lock is only taken on child creation
and on ``collect()``.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "counter", "gauge", "histogram", "DEFAULT_LATENCY_BUCKETS",
]

#: Latency buckets in seconds, tuned for collective dispatch: the native
#: negotiation cycle is ~1 ms, a cached eager collective lands in the
#: 0.1-10 ms decades, a cold compile or a cross-DCN fused burst in the
#: 0.1-10 s decades.
DEFAULT_LATENCY_BUCKETS = (
    .0001, .00025, .0005, .001, .0025, .005, .01, .025, .05, .1,
    .25, .5, 1.0, 2.5, 5.0, 10.0,
)

_RESERVED_LABELS = frozenset({"le"})


def _validate_name(name: str) -> None:
    if not name or not all(
        c.isalnum() or c in "_:" for c in name
    ) or name[0].isdigit():
        raise ValueError(f"invalid metric name {name!r}")


class _Child:
    """One (metric, label-values) time series.  Holds its own lock so
    concurrent bumps of different series never contend."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def get(self) -> float:
        with self._lock:
            return self._value


class _CounterChild(_Child):
    __slots__ = ()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            self._value += amount


class _GaugeChild(_Child):
    __slots__ = ("_fn",)

    def __init__(self):
        super().__init__()
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    def set_function(self, fn: Optional[Callable[[], float]]) -> None:
        """Evaluate ``fn`` at collection time instead of a stored value
        (for values owned elsewhere, e.g. the native core's ctypes
        getters — polling at scrape keeps the hot path untouched)."""
        with self._lock:
            self._fn = fn

    def get(self) -> float:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:
            return float(fn())
        except Exception:
            return float("nan")


class _HistogramChild:
    """Fixed-bucket histogram.  ``observe`` is allocation-free: a bisect
    into the precomputed bounds and two float adds under one lock."""

    __slots__ = ("_lock", "_bounds", "_counts", "_sum", "_count")

    def __init__(self, bounds: Tuple[float, ...]):
        self._lock = threading.Lock()
        self._bounds = bounds  # ascending, without the +Inf bucket
        self._counts = [0] * (len(bounds) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        idx = bisect.bisect_left(self._bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    def get(self) -> dict:
        with self._lock:
            return {
                "buckets": list(self._counts),
                "sum": self._sum,
                "count": self._count,
            }


class _Metric:
    """Base: owns the labeled children table."""

    kind = "untyped"

    def __init__(self, name: str, documentation: str,
                 labelnames: Sequence[str] = ()):
        _validate_name(name)
        for ln in labelnames:
            if ln in _RESERVED_LABELS:
                raise ValueError(f"label name {ln!r} is reserved")
            _validate_name(ln)
        self.name = name
        self.documentation = documentation
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            # unlabeled: one implicit child, pre-created so the hot path
            # is a direct attribute call
            self._default = self._new_child()
            self._children[()] = self._default

    def _new_child(self):
        raise NotImplementedError

    def labels(self, *labelvalues, **labelkw):
        """Child for one label-value tuple.  Call once at setup and keep
        the returned child: the lookup here allocates the key tuple."""
        if labelkw:
            if labelvalues:
                raise ValueError("pass label values either positionally "
                                 "or by keyword, not both")
            try:
                labelvalues = tuple(
                    labelkw[ln] for ln in self.labelnames
                )
            except KeyError as e:
                raise ValueError(f"missing label {e.args[0]!r}") from None
        if len(labelvalues) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, got "
                f"{labelvalues!r}"
            )
        key = tuple(str(v) for v in labelvalues)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._new_child()
                    self._children[key] = child
        return child

    def samples(self) -> List[Tuple[Tuple[str, ...], object]]:
        """Snapshot of (label_values, state) for every child."""
        with self._lock:
            items = list(self._children.items())
        return [(k, c.get()) for k, c in items]


class Counter(_Metric):
    """Monotonically increasing count (Prometheus counter)."""

    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default.inc(amount)

    def get(self) -> float:
        return self._default.get()


class Gauge(_Metric):
    """Point-in-time value (Prometheus gauge)."""

    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._default.set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default.inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default.dec(amount)

    def set_function(self, fn: Optional[Callable[[], float]]) -> None:
        self._default.set_function(fn)

    def get(self) -> float:
        return self._default.get()


class Histogram(_Metric):
    """Fixed-bucket histogram (Prometheus histogram)."""

    kind = "histogram"

    def __init__(self, name: str, documentation: str,
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        if any(math.isinf(b) for b in bounds):
            bounds = tuple(b for b in bounds if not math.isinf(b))
        self._bounds = bounds
        super().__init__(name, documentation, labelnames)

    def _new_child(self):
        return _HistogramChild(self._bounds)

    @property
    def bucket_bounds(self) -> Tuple[float, ...]:
        return self._bounds

    def observe(self, value: float) -> None:
        self._default.observe(value)

    def get(self) -> dict:
        return self._default.get()


class MetricsRegistry:
    """Holds the process's metrics; collection is a consistent-enough
    snapshot (each child snapshots under its own lock)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self._polls: List[Callable[[], None]] = []

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                raise ValueError(
                    f"metric {metric.name!r} is already registered"
                )
            self._metrics[metric.name] = metric
        return metric

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def register_poll(self, fn: Callable[[], None]) -> None:
        """Run ``fn`` before every collection — the hook instrumentation
        uses to refresh pull-style gauges (e.g. native-core stats over
        ctypes) only when someone is actually looking."""
        with self._lock:
            self._polls.append(fn)

    def unregister_poll(self, fn: Callable[[], None]) -> None:
        with self._lock:
            try:
                self._polls.remove(fn)
            except ValueError:
                pass

    def collect(self) -> List[_Metric]:
        with self._lock:
            polls = list(self._polls)
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for fn in polls:
            try:
                fn()
            except Exception:
                pass  # a broken poll must never break exposition
        return metrics

    def clear(self) -> None:
        """Drop every metric and poll hook (tests only)."""
        with self._lock:
            self._metrics.clear()
            self._polls.clear()


#: The process-wide default registry every subsystem instruments into.
REGISTRY = MetricsRegistry()


def _get_or_create(cls, name: str, documentation: str,
                   labelnames: Sequence[str], registry: MetricsRegistry,
                   **kwargs):
    m = registry.get(name)
    if m is not None:
        if not isinstance(m, cls) or m.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name!r} already registered with a different "
                f"type or label set"
            )
        if "buckets" in kwargs:
            # same normalization as Histogram.__init__, so the check
            # compares what the caller would actually have gotten
            want = tuple(sorted(
                float(b) for b in kwargs["buckets"]
                if not math.isinf(float(b))
            ))
            if m.bucket_bounds != want:
                raise ValueError(
                    f"histogram {name!r} already registered with "
                    f"different buckets {m.bucket_bounds} (asked for "
                    f"{want})"
                )
        return m
    try:
        return registry.register(cls(name, documentation, labelnames,
                                     **kwargs))
    except ValueError:
        # lost a creation race: the winner's instance is authoritative
        m = registry.get(name)
        if m is None:
            raise
        return m


def counter(name: str, documentation: str,
            labelnames: Sequence[str] = (),
            registry: MetricsRegistry = REGISTRY) -> Counter:
    """Get-or-create a :class:`Counter` (idempotent — safe to call at
    module import and after re-init)."""
    return _get_or_create(Counter, name, documentation, labelnames,
                          registry)


def gauge(name: str, documentation: str,
          labelnames: Sequence[str] = (),
          registry: MetricsRegistry = REGISTRY) -> Gauge:
    """Get-or-create a :class:`Gauge`."""
    return _get_or_create(Gauge, name, documentation, labelnames, registry)


def histogram(name: str, documentation: str,
              labelnames: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
              registry: MetricsRegistry = REGISTRY) -> Histogram:
    """Get-or-create a :class:`Histogram`."""
    return _get_or_create(Histogram, name, documentation, labelnames,
                          registry, buckets=buckets)
