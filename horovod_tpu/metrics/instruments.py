"""The framework's standard metric instruments, in one place.

Every instrumented subsystem (ops engine, native controller, elastic
driver/worker, framework adapters) imports its instruments from here so
the metric names, label sets and bucket layouts stay consistent — the
catalogue in docs/METRICS.md mirrors this file.

Import cost is a handful of registry insertions; no jax, no ctypes, no
framework imports — safe from any layer (including the elastic driver,
which runs before jax ever loads).
"""

from __future__ import annotations

from .registry import DEFAULT_LATENCY_BUCKETS, counter, gauge, histogram

# -- data plane (ops/engine.py, ops/collective_ops.py) -----------------------

#: Wall time of one compiled-collective dispatch (async hand-off to XLA,
#: not data-ready) by engine cache-key op kind.
DISPATCH_LATENCY = histogram(
    "hvd_tpu_collective_dispatch_seconds",
    "Dispatch wall time of one compiled XLA collective, by program kind",
    ["op"],
)

#: Executable-cache outcome per compile lookup (the reference's
#: ResponseCache analog for compiled programs).
EXEC_CACHE = counter(
    "hvd_tpu_executable_cache_total",
    "Engine executable-cache lookups by outcome (hit/miss)",
    ["event"],
)

#: Public collective API submissions, by op and dispatch path
#: (native = C++ background controller, eager = in-line engine).
COLLECTIVES = counter(
    "hvd_tpu_collectives_total",
    "Collective submissions by op and dispatch path",
    ["op", "path"],
)

#: Payload bytes submitted to collectives, by op.
COLLECTIVE_BYTES = counter(
    "hvd_tpu_collective_bytes_total",
    "Tensor bytes submitted to collectives, by op",
    ["op"],
)

#: Modeled bytes the engine's sum-family collectives moved on the fast
#: intra-slice fabric (ring model, ops/comm_model.py; booked at dispatch).
COLLECTIVE_ICI_BYTES = counter(
    "hvd_tpu_collective_ici_bytes_total",
    "Modeled intra-slice (ICI) fabric bytes moved by engine collectives",
)

#: Same, for the slow inter-slice fabric — THE number hierarchical
#: routing + DCN wire compression exist to shrink (docs/COLLECTIVES.md).
COLLECTIVE_DCN_BYTES = counter(
    "hvd_tpu_collective_dcn_bytes_total",
    "Modeled inter-slice (DCN) fabric bytes moved by engine collectives",
)

#: End-to-end latency of a negotiated collective: enqueue() to future
#: resolution (includes negotiation, fusion and execution).
OP_LATENCY = histogram(
    "hvd_tpu_collective_latency_seconds",
    "Enqueue-to-resolution latency of negotiated collectives, by op",
    ["op"],
)

# -- backward/collective overlap (ops/overlap.py) ----------------------------

#: Stream-byte share of gradient collectives that trail ALL backward
#: compute in the compiled step — the static exposed-comm fraction the
#: bucket schedule exists to shrink (1.0 = unoverlapped jax.grad step;
#: ~ last-bucket share when the schedule interleaves).  Set from the
#: lowered program by ``ops.overlap.record_overlap_metrics``.
OVERLAP_EXPOSED_FRACTION = gauge(
    "hvd_tpu_overlap_exposed_comm_fraction",
    "Stream-byte fraction of gradient collectives trailing all backward "
    "compute in the compiled step (static schedule view)",
)

#: How early each bucket's collective launches: matmul-class compute ops
#: still scheduled after the launch point (0 = the bucket trails; the
#: torch bridge observes parameters still awaiting gradients instead).
OVERLAP_LAUNCH_LEAD = histogram(
    "hvd_tpu_overlap_bucket_launch_lead",
    "Backward compute remaining when a bucket's collective launches "
    "(compute ops after launch; torch: params still pending)",
    buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128),
)

#: Bucket-size/tier trials the BucketAutotuner has scored.
OVERLAP_AUTOTUNE_TRIALS = counter(
    "hvd_tpu_overlap_autotune_trials_total",
    "Bucket-schedule candidates scored by the overlap autotuner",
)

#: The pinned (converged) bucket size; 0 until convergence.
OVERLAP_AUTOTUNE_PINNED_BYTES = gauge(
    "hvd_tpu_overlap_autotune_pinned_bucket_bytes",
    "Bucket bytes of the overlap autotuner's pinned winning plan",
)

# -- sharded optimizer (optim.py ZeRO wrappers) ------------------------------

#: Flattened-gradient bytes submitted to the ZeRO reduce-scatter (padded
#: buffer bytes per exchange; incremented at submission).
OPTIM_RS_BYTES = counter(
    "hvd_tpu_optim_reducescatter_bytes_total",
    "Flattened gradient bytes submitted to the ZeRO reduce-scatter",
)

#: Updated-parameter shard bytes submitted to the ZeRO allgather.
OPTIM_AG_BYTES = counter(
    "hvd_tpu_optim_allgather_bytes_total",
    "Updated parameter-shard bytes submitted to the ZeRO allgather",
)

#: This rank's sharded optimizer-state bytes (the ZeRO partition — about
#: 1/world_size of the replicated state; set at wrapper init).
OPTIM_STATE_SHARD_BYTES = gauge(
    "hvd_tpu_optim_state_shard_bytes",
    "Sharded optimizer-state bytes held by this rank (ZeRO partition)",
)

# -- native controller (native/controller.py) --------------------------------

#: Entries currently awaiting a fused response (TensorQueue + pending
#: negotiation; the reference's stall-inspector pending table).
ENQUEUE_DEPTH = gauge(
    "hvd_tpu_enqueue_depth",
    "Collectives submitted but not yet resolved on this rank",
)

#: Fill ratio of the padded fusion buffer on the host-pack path
#: (payload bytes / padded bytes; 1.0 = no padding waste).
FUSION_UTILIZATION = histogram(
    "hvd_tpu_fusion_buffer_utilization_ratio",
    "Fusion-buffer fill ratio (payload/padded) of host-packed responses",
    buckets=(0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0),
)

#: Entries fused into one negotiated response.
FUSED_ENTRIES = histogram(
    "hvd_tpu_fused_entries_per_response",
    "Tensor entries fused into one negotiated response",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
)

#: Native-core stats refreshed at scrape time (registry poll hooks):
NATIVE_CACHE_HITS = gauge(
    "hvd_tpu_native_response_cache_hits",
    "Cumulative native ResponseCache hits (bit-vector bypass cycles)",
)
NATIVE_CACHE_MISSES = gauge(
    "hvd_tpu_native_response_cache_misses",
    "Cumulative native ResponseCache misses (full request encodings)",
)
NATIVE_PENDING = gauge(
    "hvd_tpu_native_pending_collectives",
    "Stall-inspector pending count inside the native core",
)
NATIVE_CYCLE_TIME_MS = gauge(
    "hvd_tpu_native_cycle_time_ms",
    "Background-loop cycle time (autotune may move it)",
)
NATIVE_FUSION_THRESHOLD = gauge(
    "hvd_tpu_native_fusion_threshold_bytes",
    "Fusion threshold (autotune may move it)",
)
NATIVE_AUTOTUNE_ACTIVE = gauge(
    "hvd_tpu_native_autotune_active",
    "1 while the parameter autotuner is still searching",
)
NATIVE_LAST_REQUEST_BYTES = gauge(
    "hvd_tpu_native_last_request_bytes",
    "Bytes of this rank's last non-empty negotiation report",
)

# -- input pipeline (data/) ---------------------------------------------------

#: Device-ready batches staged in the prefetch queue at consume time.
#: 0 sustained = the host cannot keep up (input-bound); ~depth = healthy.
DATA_PREFETCH_DEPTH = gauge(
    "hvd_tpu_data_prefetch_depth",
    "Device-ready batches currently staged in the prefetch queue",
)

#: Time the training thread blocked in next() waiting for a device batch —
#: THE input-starvation signal (0 when the pipeline is fully overlapped).
DATA_HOST_WAIT = histogram(
    "hvd_tpu_data_host_wait_seconds",
    "Training-thread wait for the next prefetched batch (input starvation)",
)

#: Host-side cost of producing one batch: source read + decode + collate
#: (worker-pool time, overlapped with device compute when healthy).
DATA_BATCH_PRODUCE = histogram(
    "hvd_tpu_data_batch_produce_seconds",
    "Host-side decode/collate time per batch (worker pool)",
)

#: Host->device staging cost of one batch (cast + device_put dispatch).
DATA_DEVICE_PUT = histogram(
    "hvd_tpu_data_device_put_seconds",
    "Host-to-device transfer staging time per prefetched batch",
)

#: Batches delivered to the training thread, by source kind.
DATA_BATCHES = counter(
    "hvd_tpu_data_batches_total",
    "Batches delivered by the input pipeline, by source kind",
    ["source"],
)

# -- inference serving (serving/ — docs/SERVING.md) --------------------------

#: Per-token emission latency: ``first`` = arrival to first token (TTFT,
#: includes queueing — the head-of-line-blocking signal), ``inter`` =
#: gap between consecutive tokens of one request (TPOT).  p50/p99 come
#: from the histogram quantiles.
SERVE_TOKEN_LATENCY = histogram(
    "hvd_tpu_serve_token_latency_seconds",
    "Per-token emission latency (first = TTFT incl. queueing, inter = TPOT)",
    ["kind"],
    buckets=DEFAULT_LATENCY_BUCKETS + (25.0, 60.0),
)

#: Requests waiting for admission (staged + pending; live).
SERVE_QUEUE_DEPTH = gauge(
    "hvd_tpu_serve_queue_depth",
    "Requests waiting for admission to the decode batch",
)

#: Fraction of allocatable KV blocks owned by running sequences —
#: sustained ~1.0 with a deep queue means the pool (not compute) caps
#: the batch; grow HVD_TPU_SERVE_NUM_BLOCKS.
SERVE_KV_OCCUPANCY = gauge(
    "hvd_tpu_serve_kv_block_occupancy_ratio",
    "Allocated fraction of the paged KV cache's block pool",
)

#: Sequences preempted (LIFO recompute eviction) because the pool ran
#: dry mid-growth; sustained nonzero = admission is overcommitting.
SERVE_EVICTIONS = counter(
    "hvd_tpu_serve_evictions_total",
    "Sequences evicted from the decode batch to reclaim KV blocks",
)

#: Engine steps by kind (mixed = chunked prefill riding the decode
#: batch / decode-only) — the interleave ratio.
SERVE_STEPS = counter(
    "hvd_tpu_serve_steps_total",
    "Serving engine steps executed, by kind",
    ["kind"],
)

#: Prompt blocks served straight from the prefix cache at admission
#: (refcount bump, zero prefill compute for the span).
SERVE_PREFIX_HITS = counter(
    "hvd_tpu_serve_prefix_hits_total",
    "Prompt KV blocks mapped from the prefix cache at admission",
)

#: Full prompt blocks that had to be prefilled because no cached
#: prefix covered them; hits/(hits+misses) is the prefix hit rate.
SERVE_PREFIX_MISSES = counter(
    "hvd_tpu_serve_prefix_misses_total",
    "Full prompt KV blocks prefilled for lack of a cached prefix",
)

#: Prefill chunks packed into mixed steps (Sarathi-style chunked
#: prefill — each chunk rides a decode step instead of stalling it).
SERVE_PREFILL_CHUNKS = counter(
    "hvd_tpu_serve_prefill_chunks_total",
    "Prefill chunks executed inside mixed prefill+decode steps",
)

#: Fraction of allocatable KV blocks holding prefix-cache content
#: (referenced by live sequences or parked on the reclaim LRU).
SERVE_KV_CACHED = gauge(
    "hvd_tpu_serve_kv_cached_blocks_ratio",
    "Fraction of the KV block pool holding prefix-cache content",
)

#: Request lifecycle events (submitted/completed).
SERVE_REQUESTS = counter(
    "hvd_tpu_serve_requests_total",
    "Serving request lifecycle events",
    ["event"],
)

#: Requests shed (pre-admission) or cancelled (in-flight) because
#: their deadline budget (``Request.deadline_s`` /
#: ``HVD_TPU_SERVE_DEADLINE``) was already spent — tokens a client has
#: stopped waiting for are never computed.
SERVE_DEADLINE_EXCEEDED = counter(
    "hvd_tpu_serve_deadline_exceeded_total",
    "Serving requests shed or cancelled past their deadline budget",
)

#: Per-chip ICI bytes the tensor-sharded step's row-parallel psums
#: stream (2 per decoder layer; modeled via
#: ops.comm_model.modeled_serve_psum_bytes, == the lowered program's
#: all_reduce inventory).  Stays 0 on an unsharded engine.
SERVE_SHARD_PSUM_BYTES = counter(
    "hvd_tpu_serve_shard_psum_bytes_total",
    "Per-chip ICI bytes streamed by the sharded serving step's psums",
)

#: KV blocks resident per shard of the tensor-sharded pool.  Under
#: kv-head sharding every chip holds ALL blocks (each at its
#: num_kv_heads/shards head slice) — the gauge equals the pool size,
#: pinning that block tables and allocator state replicate rather than
#: partition (docs/SERVING.md).
SERVE_KV_BLOCKS_PER_SHARD = gauge(
    "hvd_tpu_serve_kv_blocks_per_shard",
    "KV blocks resident on each shard of the tensor-sharded pool",
)

#: Tokens proposed by the speculative drafter and fed to verify steps
#: (docs/SERVING.md speculative section).
SERVE_SPEC_DRAFTED = counter(
    "hvd_tpu_serve_spec_drafted_tokens_total",
    "Draft tokens fed to speculative verify steps",
)

#: Drafted tokens the greedy verifier accepted; accepted/drafted is the
#: fleet-wide acceptance rate (each verify step also emits one
#: non-drafted bonus token, so tokens/step = 1 + accepted/steps).
SERVE_SPEC_ACCEPTED = counter(
    "hvd_tpu_serve_spec_accepted_tokens_total",
    "Draft tokens accepted by greedy verification",
)

#: Drafted tokens rejected by verification — their speculative KV tail
#: is rolled back (block-aligned truncation; docs/SERVING.md).
SERVE_SPEC_ROLLED_BACK = counter(
    "hvd_tpu_serve_spec_rolled_back_tokens_total",
    "Draft tokens rejected and rolled back from the paged KV tail",
)

#: Per-request draft acceptance rate (accepted/drafted over the
#: request's lifetime), observed at completion for requests that ran
#: at least one verify step — the distribution behind the when-does-
#: speculation-pay threshold (docs/SERVING.md).
SERVE_SPEC_ACCEPT_RATE = histogram(
    "hvd_tpu_serve_spec_accept_rate",
    "Per-request speculative-draft acceptance rate at completion",
    buckets=(0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0),
)

#: In-flight requests migrated off a lost replica, by recovery path:
#: ``warm`` = a verified KV block chain re-registered on the survivor
#: (chain hashes checked end to end), ``cold`` = prompt+generated
#: re-prefilled through the prefix cache (docs/SERVING.md fault
#: tolerance).
SERVE_MIGRATIONS = counter(
    "hvd_tpu_serve_migrations_total",
    "Requests migrated to a surviving replica, by recovery path",
    ["path"],  # warm / cold
)

#: Hedged-dispatch outcomes (``HVD_TPU_SERVE_HEDGE``): ``won`` = the
#: hedge finished first (primary cancelled), ``lost`` = the primary
#: finished first (hedge cancelled), ``suppressed`` = the retry budget
#: or the target's load guard withheld the hedge.
SERVE_HEDGES = counter(
    "hvd_tpu_serve_hedges_total",
    "Hedged dispatches by outcome",
    ["outcome"],  # won / lost / suppressed
)

#: Wall seconds from detecting a replica loss to each of its requests
#: being re-dispatched (or completed from its watermark) — the
#: recovery-latency SLO the serve_bench ``migration_ms`` column reads.
SERVE_RECOVERY_SECONDS = histogram(
    "hvd_tpu_serve_recovery_seconds",
    "Seconds from replica-loss detection to a request's re-dispatch",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0),
)

#: Prefill→decode tier handoffs in the disaggregated fleet, by path:
#: ``warm`` = the kvsnap chain re-registered on the decode replica (its
#: decode re-prefixes from cache), ``cold`` = the snapshot was dropped
#: or rejected and the decode replica re-prefilled (docs/FLEET.md).
SERVE_HANDOFFS = counter(
    "hvd_tpu_serve_handoffs_total",
    "Prefill-to-decode tier handoffs, by transfer path",
    ["path"],  # warm / cold
)

#: Wall time of one tier handoff: prefill-complete pickup to the
#: request queued on its decode replica (chain verify + page write +
#: re-submit) — the latency the two-hop deadline filter budgets for.
SERVE_HANDOFF_SECONDS = histogram(
    "hvd_tpu_serve_handoff_seconds",
    "Seconds from prefill-complete pickup to decode-tier re-dispatch",
    buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0),
)

#: Paged-KV payload bytes that crossed a replica boundary warm (tier
#: handoffs and replica-loss migrations): K/V pages + token streams as
#: measured on the wire — the number ``modeled_kvsnap_bytes`` must
#: reproduce exactly (modeled == measured, comm_model idiom).
SERVE_MIGRATED_BYTES = counter(
    "hvd_tpu_serve_migrated_kv_bytes_total",
    "Paged-KV snapshot bytes moved between replicas on warm paths",
)

# -- fleet autoscaling + routing (fleet/ — docs/FLEET.md) --------------------

#: Capacity the policy engine last decided the fleet should converge
#: to (training workers or serving replicas, per the autoscaler's
#: ``kind`` label) — desired vs the live world-size/replica gauges is
#: the convergence view.
FLEET_DESIRED_SIZE = gauge(
    "hvd_tpu_fleet_desired_size",
    "Capacity the autoscale policy last decided on, by fleet kind",
    ["kind"],  # train / serve
)

#: Applied scale actions, by fleet kind and direction.
FLEET_SCALE_EVENTS = counter(
    "hvd_tpu_fleet_scale_events_total",
    "Scale actions the autoscaler applied, by fleet kind and direction",
    ["kind", "direction"],  # direction: out / in
)

#: Serving replicas by lifecycle state (ready/draining); retired
#: replicas leave the gauge.
FLEET_REPLICAS = gauge(
    "hvd_tpu_fleet_replicas",
    "Serving replicas currently held by the router, by lifecycle state",
    ["state"],
)

#: Router placement outcomes: ``affinity`` = prefix-index hit chose
#: the replica, ``least_queue`` = no cached prefix anywhere (fallback),
#: ``round_robin`` = the non-affinity baseline mode.
FLEET_ROUTED = counter(
    "hvd_tpu_fleet_routed_total",
    "Requests placed by the fleet router, by placement rule",
    ["route"],
)

#: The router's sliding-window p99 TTFT — the SLO signal its policy
#: evaluates (the per-replica histograms stay the durable record).
FLEET_ROUTER_P99_TTFT = gauge(
    "hvd_tpu_fleet_router_p99_ttft_seconds",
    "Sliding-window p99 time-to-first-token observed by the fleet router",
)

#: Preemption notices honored: SIGTERM grace -> planned snapshot ->
#: clean leave (fleet/preemption.py; the chaos ``fleet.preempt`` site).
FLEET_PREEMPTIONS = counter(
    "hvd_tpu_fleet_preemptions_total",
    "Preemption notices this worker honored with a planned leave",
)

#: Replicas the router marked suspect (ejected from placement, work
#: re-routed) after ``HVD_TPU_FLEET_REPLICA_ERRORS`` consecutive
#: submit/step errors or a healthz stall trip.
FLEET_REPLICA_SUSPECTS = counter(
    "hvd_tpu_fleet_replica_suspects_total",
    "Serving replicas marked suspect and ejected by the fleet router",
)

# -- integrity guard (guard.py — docs/FAULT_TOLERANCE.md, silent corruption) -

#: Detector evaluations at cadence, by check kind (finite sentinel /
#: EMA loss spike / cross-rank digest agreement).
GUARD_CHECKS = counter(
    "hvd_tpu_guard_checks_total",
    "Integrity-guard detector evaluations, by check kind",
    ["check"],  # finite / spike / digest
)

#: Detector trips — a check that found something wrong, by kind.
GUARD_TRIPS = counter(
    "hvd_tpu_guard_trips_total",
    "Integrity-guard detector trips (corruption signals), by check kind",
    ["check"],  # finite / spike / digest
)

#: Attribution outcomes after a digest mismatch: ``self`` = this rank
#: was named corrupt (quarantine path), ``peer`` = another rank was,
#: ``unattributed`` = no majority and no recompute vote (rollback-only).
GUARD_ATTRIBUTIONS = counter(
    "hvd_tpu_guard_attributions_total",
    "Corruption attribution outcomes after a cross-rank digest mismatch",
    ["outcome"],  # self / peer / unattributed
)

#: Rollbacks to the last verified checkpoint (poisoned-window discards).
GUARD_ROLLBACKS = counter(
    "hvd_tpu_guard_rollbacks_total",
    "Auto-rollbacks to the last integrity-verified checkpoint",
)

#: Newest step whose cross-rank agreement check passed — checkpoints at
#: or before it are trustable rollback targets.
GUARD_LAST_VERIFIED = gauge(
    "hvd_tpu_guard_last_verified_step",
    "Newest training step that passed the cross-rank integrity check",
)

#: Hosts the elastic driver quarantined after an integrity attribution
#: (every slot of the attributed worker's host leaves the spawn pool).
GUARD_QUARANTINES = counter(
    "hvd_tpu_guard_quarantined_hosts_total",
    "Hosts quarantined by the elastic driver after integrity attribution",
)

# -- elastic (runner/elastic_driver.py, elastic/worker.py) -------------------

ELASTIC_WORLD_SIZE = gauge(
    "hvd_tpu_elastic_world_size",
    "Member processes of the current elastic epoch",
)
ELASTIC_EPOCH = gauge(
    "hvd_tpu_elastic_epoch",
    "Current elastic rendezvous epoch",
)
ELASTIC_RENDEZVOUS = counter(
    "hvd_tpu_elastic_rendezvous_total",
    "Completed rendezvous epochs handed out by the driver",
)
ELASTIC_SPAWNS = counter(
    "hvd_tpu_elastic_workers_spawned_total",
    "Worker processes spawned by the elastic driver",
)
ELASTIC_FAILURES = counter(
    "hvd_tpu_elastic_worker_failures_total",
    "Worker processes that exited non-zero (slot blacklisted)",
)
ELASTIC_RESTARTS = counter(
    "hvd_tpu_elastic_restarts_total",
    "Exec-restarts this worker performed (planned + failure recovery)",
)
ELASTIC_RESTART_SECONDS = gauge(
    "hvd_tpu_elastic_last_restart_seconds",
    "Cost split of this worker's most recent exec-restart",
    ["phase"],  # persist / reboot / restore / total
)
ELASTIC_SNAPSHOT_BYTES = gauge(
    "hvd_tpu_elastic_last_snapshot_bytes",
    "Serialized state bytes carried across the last exec-restart",
)

# -- fault tolerance (chaos/, common/retry.py, native heartbeats) ------------

#: Chaos faults actually injected, by site and action (0 in production:
#: the gauge existing proves chaos was OFF, not unmeasured).
CHAOS_INJECTIONS = counter(
    "hvd_tpu_chaos_injections_total",
    "Chaos faults injected, by site and action",
    ["site", "action"],
)

#: Native heartbeat read-deadline expiries (a peer went silent past
#: HVD_TPU_HEARTBEAT_TIMEOUT); mirrored from the core by delta at
#: scrape time (a true counter — ``_total``/rate() semantics hold).
HEARTBEAT_MISSES = counter(
    "hvd_tpu_heartbeat_misses_total",
    "Heartbeat deadlines missed by peers on the negotiation channel",
)

#: Attempts one retry_call() needed before success/exhaustion, by site.
RETRY_ATTEMPTS = histogram(
    "hvd_tpu_retry_attempts",
    "Attempts per retry_call invocation, by site",
    ["site"],
    buckets=(1, 2, 3, 5, 8, 13, 21, 34),
)

#: Wall time from fault detection to training resumed (filled by the
#: elastic worker: restart total; and by auto-resume restores).
RECOVERY_SECONDS = gauge(
    "hvd_tpu_recovery_seconds",
    "Wall time of the most recent failure recovery, by phase",
    # restart / auto_resume / planned (preemption leave) /
    # rollback (guard: corruption detection -> post-boot verified resume)
    ["phase"],
)

# -- adapters (torch/optimizer.py, keras/callbacks.py) -----------------------

STEP_DURATION = histogram(
    "hvd_tpu_step_duration_seconds",
    "Training step wall time, by adapter",
    ["adapter"],
    buckets=DEFAULT_LATENCY_BUCKETS + (25.0, 60.0),
)

GRAD_NORM = gauge(
    "hvd_tpu_grad_norm",
    "Global gradient L2 norm after averaging, by adapter",
    ["adapter"],
)

#: Last epoch-end value of each Keras logged metric
#: (keras.callbacks.TelemetryCallback mirrors model.fit logs here).
KERAS_EPOCH_METRIC = gauge(
    "hvd_tpu_keras_epoch_metric",
    "Last epoch-end value of each Keras logged metric",
    ["metric"],
)

# -- distributed tracing (trace/) --------------------------------------------

#: Flight-recorder crash bundles written, by trigger reason
#: (chaos_kill / quarantine / rollback / preempt / restart /
#: slo_breach — docs/TRACING.md).
TRACE_BUNDLES = counter(
    "hvd_tpu_trace_bundles_total",
    "Flight-recorder crash bundles written, by trigger reason",
    ["reason"],
)

# -- process identity --------------------------------------------------------

PROCESS_INFO = gauge(
    "hvd_tpu_process_info",
    "Static process identity (value is always 1)",
    ["rank", "local_rank", "size", "num_processes"],
)
