"""Cluster-wide metric snapshots over the existing collectives.

Per-worker endpoints give per-rank views; operators also want job-wide
numbers without scraping every host.  This module rides the framework's
own data plane: each rank serializes its registry to JSON, the bytes are
allgathered (the engine's uneven-dim0 path handles per-rank size
differences), and every rank — in practice rank 0 — merges the results:

  * counters and histograms sum across ranks (histograms bucket-wise;
    mismatched bucket bounds fall back to sum/count only);
  * gauges stay per-rank, surfaced with a synthetic leading ``rank``
    label (a job-wide "mean of step-time gauges" hides exactly the
    straggler a gauge exists to show).

``cluster_snapshot`` is a COLLECTIVE: every member of the process set
must call it at the same point (the same SPMD-symmetry contract every
named collective already carries).  Call it from a rank-symmetric spot —
an epoch-end callback, a periodic reporter — never from a single rank.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

import numpy as np

from .registry import REGISTRY, Histogram, MetricsRegistry

__all__ = ["snapshot", "merge_snapshots", "cluster_snapshot",
           "SNAPSHOT_VERSION"]

SNAPSHOT_VERSION = 1


def snapshot(registry: MetricsRegistry = REGISTRY) -> Dict[str, Any]:
    """Serialize the registry to a JSON-safe dict (one rank's view)."""
    metrics: Dict[str, Any] = {}
    for metric in registry.collect():
        entry: Dict[str, Any] = {
            "kind": metric.kind,
            "doc": metric.documentation,
            "labelnames": list(metric.labelnames),
            "series": [
                [list(labelvalues), state]
                for labelvalues, state in metric.samples()
            ],
        }
        if isinstance(metric, Histogram):
            entry["buckets"] = list(metric.bucket_bounds)
        metrics[metric.name] = entry
    return {"version": SNAPSHOT_VERSION, "metrics": metrics}


def _merge_series(kind: str, dst: Dict[tuple, Any], rank: int,
                  series: List[Any]) -> None:
    for labelvalues, state in series:
        if kind == "gauge":
            key = (str(rank),) + tuple(labelvalues)
            dst[key] = state
        elif kind == "histogram":
            key = tuple(labelvalues)
            prev = dst.get(key)
            if prev is None:
                dst[key] = {
                    "buckets": list(state["buckets"]),
                    "sum": state["sum"], "count": state["count"],
                }
            elif len(prev["buckets"]) == len(state["buckets"]):
                prev["buckets"] = [
                    a + b for a, b in zip(prev["buckets"],
                                          state["buckets"])
                ]
                prev["sum"] += state["sum"]
                prev["count"] += state["count"]
            else:  # bound mismatch across ranks: keep sum/count only
                prev["buckets"] = []
                prev["sum"] += state["sum"]
                prev["count"] += state["count"]
        else:  # counter
            key = tuple(labelvalues)
            dst[key] = dst.get(key, 0.0) + float(state)


def merge_snapshots(snaps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-rank snapshots into one job-wide view (see module
    docstring for the per-kind semantics)."""
    merged: Dict[str, Any] = {}
    for rank, snap in enumerate(snaps):
        for name, entry in snap.get("metrics", {}).items():
            m = merged.get(name)
            if m is None:
                labelnames = list(entry["labelnames"])
                if entry["kind"] == "gauge":
                    labelnames = ["rank"] + labelnames
                m = merged[name] = {
                    "kind": entry["kind"],
                    "doc": entry["doc"],
                    "labelnames": labelnames,
                    "series": {},
                }
                if "buckets" in entry:
                    m["buckets"] = entry["buckets"]
            _merge_series(entry["kind"], m["series"], rank,
                          entry["series"])
    # back to JSON-safe lists
    for m in merged.values():
        m["series"] = [
            [list(k), v] for k, v in sorted(m["series"].items())
        ]
    return {"version": SNAPSHOT_VERSION, "ranks": len(snaps),
            "metrics": merged}


def cluster_snapshot(registry: MetricsRegistry = REGISTRY,
                     process_set=None,
                     name: str = "hvd_tpu.metrics.snapshot",
                     ) -> Dict[str, Any]:
    """Gather every member rank's snapshot and merge (COLLECTIVE — every
    member must call; see module docstring).  Returns the merged job-wide
    snapshot on every rank; per-rank raw snapshots ride along under
    ``"per_rank"``."""
    import jax.numpy as jnp

    from ..common import basics
    from ..ops import collective_ops as _ops

    local = snapshot(registry)
    payload = np.frombuffer(
        json.dumps(local, sort_keys=True).encode(), dtype=np.uint8
    )
    basics._require_init()
    gathered = np.asarray(_ops.allgather(
        jnp.asarray(payload), name=name, process_set=process_set,
    ))
    # recover the per-rank boundaries: each rank's payload length differs,
    # so gather the lengths too (a tiny (1,)-shaped collective)
    lengths = np.asarray(_ops.allgather(
        jnp.asarray([payload.size], jnp.int32),
        name=name + ".len", process_set=process_set,
    )).astype(int)
    snaps, off = [], 0
    for n in lengths:
        chunk = gathered[off:off + n]
        off += n
        try:
            snaps.append(json.loads(bytes(chunk.tobytes()).decode()))
        except (ValueError, UnicodeDecodeError):
            snaps.append({"version": SNAPSHOT_VERSION, "metrics": {}})
    merged = merge_snapshots(snaps)
    merged["per_rank"] = snaps
    return merged
