"""horovod_tpu.metrics: cluster-wide telemetry & health.

The observability layer the timeline writer and profiler bridge don't
cover (those trace *one run for offline analysis*; this exposes *live,
queryable state* — per-step throughput, collective latency, stall and
elastic-membership metrics).  Three pieces:

  * :mod:`.registry`   — process-wide Counters / Gauges / Histograms;
  * :mod:`.exposition` — Prometheus text format + the per-worker
    ``/metrics`` + ``/healthz`` HTTP endpoint (``HVD_TPU_METRICS_PORT``);
  * :mod:`.aggregate`  — job-wide snapshots merged over the framework's
    own allgather.

Quick use::

    import horovod_tpu as hvd
    from horovod_tpu import metrics

    hvd.init()                      # HVD_TPU_METRICS_PORT=9090 serves
                                    # /metrics on 9090+local_rank
    steps = metrics.counter("my_app_steps", "training steps")
    steps.inc()
    print(metrics.render())         # Prometheus text, ad hoc
    job = metrics.cluster_snapshot()  # collective: all ranks call

See docs/METRICS.md for the metric catalogue.
"""

from __future__ import annotations

from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    DEFAULT_LATENCY_BUCKETS,
    counter,
    gauge,
    histogram,
)
from .exposition import (
    ENV_METRICS_PORT,
    health_snapshot,
    http_server,
    maybe_start_from_env,
    register_health_source,
    render,
    start_http_server,
    stop_http_server,
    unregister_health_source,
)
from .aggregate import cluster_snapshot, merge_snapshots, snapshot

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "DEFAULT_LATENCY_BUCKETS", "counter", "gauge", "histogram",
    "ENV_METRICS_PORT", "render", "start_http_server", "stop_http_server",
    "http_server", "maybe_start_from_env", "register_health_source",
    "unregister_health_source", "health_snapshot",
    "snapshot", "merge_snapshots", "cluster_snapshot",
]
