"""Prometheus text exposition + per-worker HTTP endpoint.

Renders a :class:`~horovod_tpu.metrics.registry.MetricsRegistry` in the
Prometheus text format (version 0.0.4) and serves it from a tiny
stdlib-only ``http.server`` endpoint per worker:

  * ``GET /metrics``  — the registry, Prometheus text format;
  * ``GET /healthz``  — JSON health summary reflecting the registered
    health sources (stall inspector, background-loop liveness, elastic
    membership state); HTTP 200 when healthy, 503 otherwise.

The endpoint is OFF by default.  ``HVD_TPU_METRICS_PORT`` enables it:

  * unset / empty / negative — disabled (no socket is ever bound);
  * ``0``                    — bind an ephemeral port (tests, one-offs;
    read the chosen port back from ``server.port``);
  * ``N > 0``                — bind port ``N + local_rank`` (every worker
    process on a host needs its own port; rank offsetting mirrors how
    the launcher offsets per-worker service ports).

Health sources follow the same registration shape as metrics: any
subsystem calls :func:`register_health_source` with a callable returning
``(healthy: bool, details: dict)``; ``/healthz`` aggregates them.
"""

from __future__ import annotations

import json
import math
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import unquote

from ..utils.logging import get_logger
from .registry import REGISTRY, Histogram, MetricsRegistry

__all__ = [
    "render", "start_http_server", "stop_http_server", "http_server",
    "maybe_start_from_env", "register_health_source",
    "unregister_health_source", "health_snapshot", "ENV_METRICS_PORT",
    "ENV_METRICS_BIND", "register_control_handler",
    "unregister_control_handler",
]

ENV_METRICS_PORT = "HVD_TPU_METRICS_PORT"
# bind address for the endpoint; default "" = all interfaces (the usual
# Prometheus-exporter convention).  Set 127.0.0.1 on multi-tenant hosts.
ENV_METRICS_BIND = "HVD_TPU_METRICS_BIND"

CONTENT_TYPE_LATEST = "text/plain; version=0.0.4; charset=utf-8"


# -- text format -------------------------------------------------------------


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v) == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def _escape_help(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n")


def _labels_str(names: Tuple[str, ...], values: Tuple[str, ...],
                extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = [
        f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)
    ] + [f'{n}="{_escape_label(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def render(registry: MetricsRegistry = REGISTRY) -> str:
    """Render the registry in the Prometheus text format 0.0.4."""
    out = []
    for metric in registry.collect():
        out.append(f"# HELP {metric.name} "
                   f"{_escape_help(metric.documentation)}")
        out.append(f"# TYPE {metric.name} {metric.kind}")
        if isinstance(metric, Histogram):
            for labelvalues, state in metric.samples():
                cumulative = 0
                for bound, n in zip(metric.bucket_bounds,
                                    state["buckets"]):
                    cumulative += n
                    ls = _labels_str(metric.labelnames, labelvalues,
                                     (("le", _fmt_value(bound)),))
                    out.append(
                        f"{metric.name}_bucket{ls} {cumulative}"
                    )
                cumulative += state["buckets"][-1]
                ls = _labels_str(metric.labelnames, labelvalues,
                                 (("le", "+Inf"),))
                out.append(f"{metric.name}_bucket{ls} {cumulative}")
                ls = _labels_str(metric.labelnames, labelvalues)
                out.append(
                    f"{metric.name}_sum{ls} {_fmt_value(state['sum'])}"
                )
                out.append(f"{metric.name}_count{ls} {state['count']}")
        else:
            # counters carry their conventional _total suffix in their
            # declared name (text format 0.0.4 exposes it verbatim)
            for labelvalues, value in metric.samples():
                ls = _labels_str(metric.labelnames, labelvalues)
                out.append(f"{metric.name}{ls} {_fmt_value(value)}")
    return "\n".join(out) + "\n" if out else ""


# -- health sources ----------------------------------------------------------

_health_lock = threading.Lock()
_health_sources: Dict[str, Callable[[], Tuple[bool, dict]]] = {}


def register_health_source(name: str,
                           fn: Callable[[], Tuple[bool, dict]]) -> None:
    """Register a health contributor.  ``fn`` returns ``(healthy,
    details)``; it is called on every ``/healthz`` request, so it must be
    cheap and must not block (poll counters, don't take slow locks)."""
    with _health_lock:
        _health_sources[name] = fn


def unregister_health_source(name: str) -> None:
    with _health_lock:
        _health_sources.pop(name, None)


def health_snapshot() -> Tuple[bool, dict]:
    """Aggregate every registered health source: overall AND of the
    per-source verdicts plus their detail dicts."""
    with _health_lock:
        sources = dict(_health_sources)
    healthy = True
    details: dict = {}
    for name, fn in sorted(sources.items()):
        try:
            ok, d = fn()
        except Exception as e:
            ok, d = False, {"error": f"{type(e).__name__}: {e}"}
        healthy = healthy and bool(ok)
        details[name] = {"healthy": bool(ok), **d}
    return healthy, details


# -- control handlers --------------------------------------------------------

_control_lock = threading.Lock()
_control_handlers: Dict[str, Callable[[Dict[str, str]], Tuple[int, dict]]] \
    = {}


def register_control_handler(name: str,
                             fn: Callable[[Dict[str, str]],
                                          Tuple[int, dict]],
                             ) -> None:
    """Mount a small control surface at ``GET /control/<name>`` on the
    worker's endpoint (the same registration shape as health sources).
    ``fn`` receives the parsed query parameters and returns
    ``(http_status, json_dict)``; it must be cheap and thread-safe —
    it runs on the scrape server's threads.  First user: the fleet
    autoscaler's runtime-settable SLO targets
    (``/control/fleet/targets``, docs/FLEET.md)."""
    with _control_lock:
        _control_handlers[name] = fn


def unregister_control_handler(name: str) -> None:
    with _control_lock:
        _control_handlers.pop(name, None)


# -- HTTP endpoint -----------------------------------------------------------


def _deny_remote(client_ip: str) -> bool:
    """The PR-13 control-surface rule: the scrape surface (/metrics,
    /healthz) is read-only and serves anyone, but mutating or verbose
    surfaces (/control/*, /trace) answer loopback peers only unless
    ``HVD_TPU_CONTROL_REMOTE=1`` opts remote callers in (put a real
    proxy in front then).  Factored out so the gate is unit-testable
    with arbitrary client addresses."""
    return (not client_ip.startswith("127.") and client_ip != "::1"
            and os.environ.get("HVD_TPU_CONTROL_REMOTE", "") != "1")


class _Handler(BaseHTTPRequestHandler):
    registry: MetricsRegistry = REGISTRY

    def do_GET(self):  # noqa: N802 (stdlib handler signature)
        path = self.path.split("?", 1)[0]
        if path in ("/metrics", "/metrics/"):
            body = render(self.registry).encode()
            self._reply(200, CONTENT_TYPE_LATEST, body)
        elif path in ("/healthz", "/health", "/healthz/"):
            healthy, details = health_snapshot()
            body = json.dumps(
                {"status": "ok" if healthy else "unhealthy",
                 "sources": details},
                sort_keys=True,
            ).encode()
            self._reply(200 if healthy else 503, "application/json", body)
        elif path.startswith("/control/") or path in ("/trace", "/trace/"):
            if _deny_remote(self.client_address[0]):
                self._reply(403, "text/plain",
                            b"control surface is loopback-only "
                            b"(HVD_TPU_CONTROL_REMOTE=1 opts in)\n")
                return
            if path.startswith("/control/"):
                name = path[len("/control/"):].rstrip("/")
            else:
                # /trace is the span-recorder export (docs/TRACING.md),
                # mounted through the same control-handler registry
                name = "trace"
            with _control_lock:
                fn = _control_handlers.get(name)
            if fn is None:
                self._reply(404, "text/plain", b"no such control\n")
                return
            query = self.path.split("?", 1)[1] if "?" in self.path else ""
            params = {}
            for pair in query.split("&"):
                if "=" in pair:
                    k, v = pair.split("=", 1)
                    params[unquote(k)] = unquote(v)
            try:
                code, payload = fn(params)
            except Exception as e:
                code, payload = 400, {"error": f"{type(e).__name__}: {e}"}
            self._reply(code, "application/json",
                        json.dumps(payload, sort_keys=True).encode())
        elif path == "/":
            # advertise /trace only where its handler is mounted (a
            # process that never ran trace install would 404 the link)
            with _control_lock:
                has_trace = "trace" in _control_handlers
            body = (b'<html><body><a href="/metrics">/metrics</a> '
                    b'<a href="/healthz">/healthz</a>'
                    + (b' <a href="/trace">/trace</a>' if has_trace
                       else b'')
                    + b'</body></html>')
            self._reply(200, "text/html", body)
        else:
            self._reply(404, "text/plain", b"not found\n")

    def _reply(self, code: int, ctype: str, body: bytes) -> None:
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper went away mid-reply

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass


class MetricsHTTPServer:
    """One worker's scrape endpoint: a ThreadingHTTPServer on a daemon
    thread (scrapes never touch the training thread)."""

    def __init__(self, port: int, addr: str = "",
                 registry: MetricsRegistry = REGISTRY):
        handler = type("_BoundHandler", (_Handler,),
                       {"registry": registry})
        self._httpd = ThreadingHTTPServer((addr, port), handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.5},
            name="hvd_tpu_metrics_http", daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


_server_lock = threading.Lock()
_server: Optional[MetricsHTTPServer] = None


def http_server() -> Optional[MetricsHTTPServer]:
    """The process's running endpoint, or None when disabled."""
    return _server


def start_http_server(port: int, addr: str = "",
                      registry: MetricsRegistry = REGISTRY,
                      ) -> MetricsHTTPServer:
    """Start (or return the already-running) endpoint.  ``port=0`` binds
    an ephemeral port; read it back from ``.port``."""
    global _server
    with _server_lock:
        if _server is None:
            _server = MetricsHTTPServer(port, addr, registry)
            get_logger().info(
                "metrics: /metrics + /healthz on port %d", _server.port
            )
        return _server


def stop_http_server() -> None:
    global _server
    with _server_lock:
        srv, _server = _server, None
    if srv is not None:
        srv.close()


def maybe_start_from_env(local_rank: int = 0,
                         registry: MetricsRegistry = REGISTRY,
                         env_var: str = ENV_METRICS_PORT,
                         ) -> Optional[MetricsHTTPServer]:
    """Init-time hook: start the endpoint iff ``env_var`` (default
    ``HVD_TPU_METRICS_PORT``) opts in (see module docstring for the port
    convention).  Never raises — an unbindable port logs a warning and
    leaves metrics collection (which is independent of exposition) fully
    functional.  The elastic driver passes its own ``env_var`` because it
    shares a host with worker 0."""
    raw = os.environ.get(env_var, "").strip()
    if not raw:
        return None
    try:
        base = int(raw)
    except ValueError:
        get_logger().warning(
            "metrics: ignoring non-integer %s=%r", env_var, raw
        )
        return None
    if base < 0:
        return None
    port = base + local_rank if base > 0 else 0
    if port > 65535:
        get_logger().warning(
            "metrics: %s=%d + local_rank %d exceeds 65535; endpoint "
            "disabled", env_var, base, local_rank,
        )
        return None
    try:
        return start_http_server(
            port, addr=os.environ.get(ENV_METRICS_BIND, ""),
            registry=registry,
        )
    except (OSError, OverflowError) as e:
        get_logger().warning(
            "metrics: cannot bind port %d (%s); endpoint disabled",
            port, e,
        )
        return None
