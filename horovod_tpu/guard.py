"""horovod_tpu.guard — step-level integrity defense against silent
data corruption (SDC).

Every other robustness layer in this repo defends against processes
that *die* (heartbeats, chaos kills, exec-restart, preemption drains);
this one defends against processes that *lie*: a chip computing wrong
gradients ("Cores that don't count", Hochschild et al.; Meta's SDC
fleet studies), a rank whose parameters silently desync, a checkpoint
that unpickles but is garbage.  The transport-level MACs (PR 2) cannot
see corruption that happens *inside* the math — by the time a wrong
value is on the wire it is correctly signed.

The closed loop: **detect → attribute → quarantine → roll back →
converge**, automatically (docs/FAULT_TOLERANCE.md, silent corruption):

* **Cheap always-on detectors** — a NaN/Inf sentinel over loss+grads
  and a per-step gradient digest, both computed ON DEVICE inside the
  compiled step (:func:`step_diag`: elementwise folds, zero
  collectives); plus a host-side EMA loss-spike detector.  The device
  values stay on device; ONE bounded host sync per
  ``HVD_TPU_GUARD_CADENCE`` steps pulls the window.
* **Cross-rank agreement** — post-allreduce gradients (or the ZeRO
  exchange's post-allgather updates) and a param fingerprint must be
  BIT-identical across data-parallel ranks.  At cadence each rank
  publishes its window of per-step u64 digests (a few bytes) through
  an exchange (the framework allgather, or a shared-directory board
  for environments without cross-process collectives) and compares.
* **Attribution** — on disagreement, find the FIRST divergent step in
  the window.  With >2 ranks the majority digest names the minority
  rank(s).  On a pairwise tie, each rank redundantly RECOMPUTES the
  sampled microbatch of the divergent step (caller-provided
  ``recompute`` hook) and compares with what it published: a transient
  flip in my own compute shows up as self-inconsistency, so the faulty
  rank attributes ITSELF; a second exchange round shares the verdicts.
* **Response** — the attributed rank reports ``failing`` (integrity
  flag) on the PR-3 notify path — the elastic driver QUARANTINES its
  whole host (spawn blacklist, the fleet scale-down bookkeeping) — and
  exits.  Survivors roll back: checkpoints newer than the last
  *verified* step are discarded (they are inside the poisoned window;
  :func:`checkpoint.discard_newer_than`), the live state is dropped
  (an exec-restart with NO snapshot), and post-boot auto-resume
  restores the newest surviving — checksummed and verified —
  checkpoint.  ``hvd_tpu_recovery_seconds{phase="rollback"}`` books
  the wall time across the restart.

Exactness contract (the standing oracle discipline): the guarded step
is BIT-identical to the unguarded step when no fault fires — the
digest/sentinel are pure extra outputs over the same dataflow — and
the disabled path (``HVD_TPU_GUARD=0``) lowers to a program with ZERO
guard collectives (the in-step detectors add none even when enabled;
the digest exchange rides the host control plane at cadence).
tools/guard_bench.py pins both, plus the ≤2% overhead bar.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .common.retry import env_float, env_int
from .metrics import instruments as _metrics
from .utils.logging import get_logger

__all__ = [
    "IntegrityError", "IntegrityGuard", "Verdict", "CollectiveExchange",
    "FileBoardExchange", "device_allfinite", "device_digest", "host_digest",
    "step_diag",
]

ENV_GUARD = "HVD_TPU_GUARD"
ENV_CADENCE = "HVD_TPU_GUARD_CADENCE"
ENV_SPIKE = "HVD_TPU_GUARD_SPIKE"
ENV_EMA = "HVD_TPU_GUARD_EMA"
ENV_BOARD = "HVD_TPU_GUARD_BOARD"
ENV_TIMEOUT = "HVD_TPU_GUARD_EXCHANGE_TIMEOUT"
#: wall-clock rollback start, carried ACROSS the exec-restart boundary
#: (the PR-3 restart-cost idiom) so recovery_seconds{phase="rollback"}
#: spans detection to post-boot resume
ENV_ROLLBACK_T0 = "HVD_TPU_GUARD_ROLLBACK_T0"
#: board generation, bumped by every rollback and carried across the
#: exec-restart: the post-rollback re-run REVISITS the poisoned window's
#: steps, and a pre-rollback board file for the same step must read as
#: absent (still being re-posted), never as fresh — deleting the files
#: instead was a race (a slower peer mid-gather loses the entry it was
#: about to read and blocks out its whole exchange timeout)
ENV_GEN = "HVD_TPU_GUARD_GEN"
#: rollback-loop fuse, carried across the exec-restart: consecutive
#: rollbacks that never get PAST the step that tripped them mean the
#: fault reproduces deterministically — a real training divergence
#: (lr blowup, bad batch), not transient corruption — and restarting
#: forever would burn the fleet while hiding the real error.  The
#: count resets once a verified check passes the recorded trip step.
ENV_ROLLBACK_COUNT = "HVD_TPU_GUARD_ROLLBACK_COUNT"
ENV_ROLLBACK_STEP = "HVD_TPU_GUARD_ROLLBACK_STEP"
ENV_MAX_ROLLBACKS = "HVD_TPU_GUARD_MAX_ROLLBACKS"
#: newest verified step, carried across the exec-restart: a SECOND
#: trip after a rollback restart must discard only past the same
#: watermark — a fresh guard resetting to 0 would hand
#: discard_newer_than(0) the whole ring, wiping the very checkpoints
#: the first rollback verified and resumed from
ENV_VERIFIED = "HVD_TPU_GUARD_VERIFIED_STEP"

#: exit code of a self-attributed (quarantining) rank — distinct from
#: generic failures in the driver's logs
QUARANTINE_EXIT = 86

_MIX = 0x9E3779B1  # odd golden-ratio constant (second digest lane)


class IntegrityError(RuntimeError):
    """Raised by :meth:`IntegrityGuard.respond` in non-elastic contexts
    when corruption is detected: the caller owns recovery (reload a
    verified checkpoint).  Elastic workers never see it — the guard
    exec-restarts them through the rollback path instead."""


# -- digests -----------------------------------------------------------------
#
# A pair of mod-2^32 multiply-accumulate lanes over the bit patterns of
# every leaf ("u64 digest": 2 x uint32).  Lane 0 weights word i of leaf
# k by the ODD multiplier (2*i + 2*k + 1), so a single flipped bit b
# changes it by ±2^b * odd ≠ 0 (mod 2^32) — any single-bit flip is
# PROVABLY detected; lane 1 re-weights by an odd golden-ratio mix for
# cheap extra entropy against multi-bit cancellation.  Both the device
# (jax) and host (numpy) folds produce identical values (test-pinned),
# so host-loop trainers and compiled steps share one digest space.


def _device_words(x):
    """A leaf's bit pattern as a flat uint32 vector (device)."""
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(x)
    if x.dtype == jnp.bool_:
        return x.astype(jnp.uint32).ravel()
    nbytes = jnp.dtype(x.dtype).itemsize
    if nbytes == 1:
        return jax.lax.bitcast_convert_type(x, jnp.uint8).astype(
            jnp.uint32).ravel()
    if nbytes == 2:
        return jax.lax.bitcast_convert_type(x, jnp.uint16).astype(
            jnp.uint32).ravel()
    # 4-byte leaves bitcast 1:1; 8-byte leaves split into a trailing
    # (2,) uint32 axis — raveled, the low/high words interleave in the
    # same order numpy's little-endian uint32 view produces
    return jax.lax.bitcast_convert_type(x, jnp.uint32).ravel()


def _host_words(a: np.ndarray) -> np.ndarray:
    a = np.ascontiguousarray(a)
    if a.dtype == np.bool_:
        return a.astype(np.uint32).ravel()
    if a.dtype.itemsize == 8:
        # mirror jnp.asarray under default (x64-disabled) jax: 64-bit
        # hosts leaves land on device as their 32-bit counterparts, so
        # the host fold must digest the same downcast bits
        import jax

        if not jax.config.jax_enable_x64:
            kind = a.dtype.kind
            a = np.ascontiguousarray(a.astype(
                {"f": np.float32, "i": np.int32, "u": np.uint32}.get(
                    kind, np.float32)))
    if a.dtype.itemsize == 1:
        return a.view(np.uint8).astype(np.uint32).ravel()
    if a.dtype.itemsize == 2:
        return a.view(np.uint16).astype(np.uint32).ravel()
    return a.view(np.uint32).ravel()


def device_digest(tree) -> Any:
    """(2,) uint32 content digest of every leaf's bit pattern, computed
    on device (pure elementwise+reduce ops, NO collectives — safe to
    add to any step program without changing its existing dataflow)."""
    import jax
    import jax.numpy as jnp

    lane0 = jnp.zeros((), jnp.uint32)
    lane1 = jnp.zeros((), jnp.uint32)
    for k, leaf in enumerate(jax.tree_util.tree_leaves(tree)):
        w = _device_words(leaf)
        idx = jnp.arange(w.size, dtype=jnp.uint32)
        m = idx * jnp.uint32(2) + jnp.uint32(2 * k + 1)
        lane0 = lane0 + jnp.sum(w * m, dtype=jnp.uint32)
        lane1 = lane1 + jnp.sum(w * (m * jnp.uint32(_MIX)),
                                dtype=jnp.uint32)
    return jnp.stack([lane0, lane1])


def host_digest(tree) -> np.ndarray:
    """Numpy mirror of :func:`device_digest` — identical values for
    identical contents (pinned by tests), so host-loop trainers (the
    chaos-soak worker, torch-style loops) share the digest space."""
    import jax

    lane0 = np.uint64(0)
    lane1 = np.uint64(0)
    mask = np.uint64(0xFFFFFFFF)
    for k, leaf in enumerate(jax.tree_util.tree_leaves(tree)):
        w = _host_words(np.asarray(leaf)).astype(np.uint64)
        idx = np.arange(w.size, dtype=np.uint64)
        m = (idx * np.uint64(2) + np.uint64(2 * k + 1)) & mask
        # products wrap mod 2^64; 2^32 | 2^64 so the final mod-2^32
        # fold equals the device's per-element uint32 wraparound
        with np.errstate(over="ignore"):
            lane0 = (lane0 + np.sum(w * m, dtype=np.uint64)) & mask
            lane1 = (lane1 + np.sum(w * ((m * np.uint64(_MIX)) & mask),
                                    dtype=np.uint64)) & mask
    return np.array([lane0, lane1], np.uint32)


def device_allfinite(tree) -> Any:
    """Scalar bool: every float leaf is NaN/Inf-free (int leaves pass)."""
    import jax
    import jax.numpy as jnp

    ok = jnp.asarray(True)
    for leaf in jax.tree_util.tree_leaves(tree):
        leaf = jnp.asarray(leaf)
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            ok = ok & jnp.all(jnp.isfinite(leaf))
    return ok


def step_diag(loss, grads) -> Dict[str, Any]:
    """The guarded step's extra outputs: the always-on detectors,
    evaluated on device inside the compiled step.  ``digest`` is over
    the POST-reduction gradients (what must be bit-identical across
    data-parallel ranks); ``finite`` covers loss and gradients.

    SCOPE (docs/FAULT_TOLERANCE.md): agreement on post-reduction
    values catches corruption in the exchange, the wire, the optimizer
    update and state desync.  A wrong LOCAL gradient folded into the
    allreduce is corrupted IDENTICALLY on every rank (local grads
    differ by design — different batches — so they cannot be compared
    directly); catching that class needs a redundant recompute of the
    sampled microbatch — the host-loop ``tap_grads`` path and the
    attribution ``recompute`` hook do exactly that, the compiled path
    does not re-execute."""
    return {
        "finite": device_allfinite((loss, grads)),
        "digest": device_digest(grads),
    }


def _canon(digest) -> bytes:
    """Any digest form (device array, numpy, bytes, hex str) to the
    canonical 8-byte wire form."""
    if isinstance(digest, bytes):
        return digest
    if isinstance(digest, str):
        return bytes.fromhex(digest)
    return np.asarray(digest, np.uint32).tobytes()


# -- exchanges ---------------------------------------------------------------


class FileBoardExchange:
    """Digest exchange over a shared directory ("board"): each rank
    publishes ``<key>.rank<R>`` atomically (tmp + rename) and polls for
    its peers under a timeout.  The exchange for environments whose
    processes share a filesystem but cannot run cross-process
    collectives (the chaos-soak contract on CPU-host jax; the same
    HVD_TPU_SOAK_LOCAL_SYNC-style substitution PR 3 established).

    Entries carry a GENERATION header (``HVD_TPU_GUARD_GEN``, bumped by
    every rollback and inherited across the exec-restart): the
    post-rollback re-run revisits the poisoned window's step numbers,
    and a pre-rollback entry for the same key must read as *absent*
    (the peer will overwrite it), never as fresh — a clean peer's stale
    digest happens to be value-identical (deterministic re-run), but a
    quarantined rank's stale entry is poisoned, and rank renumbering
    after a shrink could hand it to a different worker.  Entries are
    never deleted mid-job (deleting raced slower peers out of entries
    they were mid-gather on); the board directory is per-job temp
    space.  Production fleets use :class:`CollectiveExchange`."""

    def __init__(self, directory: str, *, timeout: float = 30.0,
                 poll: float = 0.02, generation: Optional[int] = None):
        self.directory = directory
        self.timeout = timeout
        self.poll = poll
        self.generation = (env_int(ENV_GEN, 0)
                           if generation is None else int(generation))
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str, rank: int) -> str:
        return os.path.join(self.directory, f"{key}.rank{rank}")

    def gather(self, key: str, payload: bytes, *, world: int,
               rank: int) -> List[Optional[bytes]]:
        gen = b"%08x\n" % self.generation
        tmp = self._path(key, rank) + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(gen + payload)
        os.replace(tmp, self._path(key, rank))  # atomic publish
        out: List[Optional[bytes]] = [None] * world
        out[rank] = payload
        deadline = time.monotonic() + self.timeout
        missing = [r for r in range(world) if r != rank]
        while missing and time.monotonic() < deadline:
            for r in list(missing):
                try:
                    with open(self._path(key, r), "rb") as f:
                        blob = f.read()
                except FileNotFoundError:
                    continue
                try:
                    file_gen = int(blob[:8], 16)
                except ValueError:
                    continue  # torn write: re-poll
                if file_gen < self.generation:
                    continue  # pre-rollback entry: peer will overwrite
                out[r] = blob[9:]
                missing.remove(r)
            if missing:
                time.sleep(self.poll)
        return out


class CollectiveExchange:
    """Digest exchange over the framework's own allgather
    (:func:`horovod_tpu.functions.allgather_object`) — a few bytes on
    the negotiated control plane, the production default."""

    def gather(self, key: str, payload: bytes, *, world: int,
               rank: int) -> List[Optional[bytes]]:
        from . import functions

        del key  # the collective itself sequences the rounds
        out = functions.allgather_object(payload)
        if len(out) != world:
            return out + [None] * (world - len(out))
        return out


# -- verdicts ----------------------------------------------------------------


@dataclasses.dataclass
class Verdict:
    """Outcome of one cadence check."""

    step: int
    ok: bool
    kind: str  # verified | partial | nan | mismatch
    #: ranks named as corrupt (majority vote or recompute self-report)
    attributed: List[int] = dataclasses.field(default_factory=list)
    #: True when THIS rank is in ``attributed`` (quarantine path)
    self_attributed: bool = False
    #: first step in the window whose gradient digests diverged (None:
    #: the divergence predates the window — param-only drift)
    divergent_step: Optional[int] = None
    #: advisory loss-spike flag (EMA detector; never fails the verdict
    #: by itself — spikes have benign causes, digests do not)
    spike: bool = False
    detail: str = ""


class IntegrityGuard:
    """The closed loop's driver (module docstring).  One instance per
    training process; host-side and framework-agnostic — compiled-step
    trainers feed it device diagnostics (:func:`step_diag`), host-loop
    trainers feed it :func:`host_digest` values through
    :meth:`tap_grads`/:meth:`observe_grads`."""

    def __init__(self, *, enabled: bool = True, cadence: int = 16,
                 spike: float = 10.0, ema_alpha: float = 0.9,
                 world: int = 1, rank: int = 0, exchange=None,
                 ckpt_dir: Optional[str] = None,
                 exit_fn: Callable[[int], None] = os._exit):
        if cadence < 1:
            raise ValueError(f"cadence must be >= 1, got {cadence}")
        self.enabled = bool(enabled)
        self.cadence = int(cadence)
        self.spike = float(spike)
        self.ema_alpha = float(ema_alpha)
        self.world = int(world)
        self.rank = int(rank)
        self.exchange = exchange
        self.ckpt_dir = ckpt_dir
        self._exit = exit_fn
        # inherited across a rollback exec-restart (module env notes):
        # the re-run's watermark starts where the verified ring ends,
        # never at 0
        self.last_verified_step = env_int(ENV_VERIFIED, 0)
        self.last_rollback_s: Optional[float] = None
        #: rollback-loop fuse state (module env docstrings): trips of
        #: the SAME step accumulate until a verified check passes it
        self.max_rollbacks = max(1, env_int(ENV_MAX_ROLLBACKS, 3))
        self._rollback_count = env_int(ENV_ROLLBACK_COUNT, 0)
        self._rollback_barrier = env_int(ENV_ROLLBACK_STEP, -1)
        self._ema: Optional[float] = None
        self._ema_n = 0
        self._window: List[tuple] = []  # (step, digest as given)
        self._lock = threading.Lock()
        self._pdigest_fn = None
        t0 = os.environ.pop(ENV_ROLLBACK_T0, None)
        if t0 is not None:
            try:
                self.last_rollback_s = max(0.0, time.time() - float(t0))
                _metrics.RECOVERY_SECONDS.labels("rollback").set(
                    self.last_rollback_s)
                get_logger().info(
                    "guard: rollback completed in %.2fs (detection -> "
                    "post-boot resume)", self.last_rollback_s)
            except ValueError:
                pass

    @classmethod
    def from_env(cls, *, world: Optional[int] = None,
                 rank: Optional[int] = None,
                 ckpt_dir: Optional[str] = None,
                 exchange=None, **overrides) -> "IntegrityGuard":
        """Build from the ``HVD_TPU_GUARD_*`` knobs (docs/running.md).
        ``HVD_TPU_GUARD_BOARD`` selects the shared-directory exchange;
        otherwise multi-process worlds default to the framework
        allgather (:class:`CollectiveExchange`)."""
        if world is None or rank is None:
            from .common import basics

            if basics.is_initialized():
                world = basics.cross_size() if world is None else world
                rank = basics.cross_rank() if rank is None else rank
            else:
                world = 1 if world is None else world
                rank = 0 if rank is None else rank
        if exchange is None and world > 1:
            board = os.environ.get(ENV_BOARD)
            timeout = env_float(ENV_TIMEOUT, 30.0)
            if board:
                exchange = FileBoardExchange(board, timeout=timeout)
            else:
                exchange = CollectiveExchange()
        kw = dict(
            enabled=bool(env_int(ENV_GUARD, 0)),
            cadence=env_int(ENV_CADENCE, 16),
            spike=env_float(ENV_SPIKE, 10.0),
            ema_alpha=env_float(ENV_EMA, 0.9),
        )
        kw.update(overrides)
        return cls(world=world, rank=rank, exchange=exchange,
                   ckpt_dir=ckpt_dir, **kw)

    # -- per-step feeds ------------------------------------------------------

    def due(self, step: int) -> bool:
        """True on cadence steps (and never on step 0)."""
        return self.enabled and step > 0 and step % self.cadence == 0

    def tap_grads(self, array):
        """Host-loop gradient tap: the ``guard.grad`` chaos site — a
        ``flipbit`` rule here IS the silent-corruption drill (the
        returned, possibly-corrupted array is what the trainer applies,
        exactly as a lying chip would hand it over)."""
        from . import chaos as _chaos

        if _chaos.active:
            return _chaos.point("guard.grad", array)
        return array

    def tap_params(self, array):
        """Host-loop param-fingerprint tap (``guard.param`` site)."""
        from . import chaos as _chaos

        if _chaos.active:
            return _chaos.point("guard.param", array)
        return array

    def observe_grads(self, step: int, digest) -> None:
        """Append one step's gradient digest to the agreement window.
        ``digest`` may be a live device array — it is NOT synced here
        (the cadence check syncs the whole window in one pass)."""
        if not self.enabled:
            return
        with self._lock:
            self._window.append((int(step), digest))
            # bound the window: everything older than one cadence has
            # either been verified or already rolled back
            if len(self._window) > 2 * self.cadence:
                del self._window[:-2 * self.cadence]

    def param_digest(self, params) -> Any:
        """Compiled param fingerprint (one program, cached)."""
        import jax

        if self._pdigest_fn is None:
            self._pdigest_fn = jax.jit(device_digest)
        return self._pdigest_fn(params)

    # -- the cadence check ---------------------------------------------------

    def _spike_check(self, step: int, loss: float) -> bool:
        if not np.isfinite(loss) or self.spike <= 0:
            return False
        tripped = False
        if self._ema is not None and self._ema_n >= 3:
            floor = max(abs(self._ema), 1e-8)
            if abs(loss) > self.spike * floor:
                tripped = True
                _metrics.GUARD_TRIPS.labels("spike").inc()
                get_logger().warning(
                    "guard: loss spike at step %d — |%.4g| > %.1fx EMA "
                    "%.4g (advisory; digests decide corruption)",
                    step, loss, self.spike, self._ema)
        a = self.ema_alpha
        self._ema = loss if self._ema is None else a * self._ema + (
            1 - a) * loss
        self._ema_n += 1
        return tripped

    def check(self, step: int, *, loss: Optional[float] = None,
              finite: bool = True, param_digest=None,
              recompute: Optional[Callable[[int], Any]] = None
              ) -> Verdict:
        """Run the cadence check: detectors, then cross-rank agreement
        over the window gathered since the previous check.

        ``recompute(divergent_step)`` re-derives that step's gradient
        digest (the redundant-recompute vote on the sampled
        microbatch): deterministic trainers pass an exact recompute; a
        data-parallel trainer can reproduce only the current step's
        retained microbatch and passes None otherwise — mismatches then
        resolve by majority, or stay unattributed (rollback-only)."""
        step = int(step)
        _metrics.GUARD_CHECKS.labels("finite").inc()
        spike = False
        if loss is not None:
            _metrics.GUARD_CHECKS.labels("spike").inc()
            spike = self._spike_check(step, float(loss))
        nan = (not finite
               or (loss is not None and not np.isfinite(loss)))
        if nan:
            _metrics.GUARD_TRIPS.labels("finite").inc()
            get_logger().error(
                "guard: NaN/Inf detected at step %d — rolling back to "
                "the last verified checkpoint", step)

        with self._lock:
            entries = list(self._window)
            self._window.clear()
        if entries:
            # THE one bounded host sync per cadence: live device arrays
            # in the window come down in a single batched device_get,
            # not one blocking round-trip per stored digest
            import jax

            vals = jax.device_get([d for _, d in entries])
        else:
            vals = []
        window = [(s, _canon(v).hex())
                  for (s, _), v in zip(entries, vals)]
        if self.world <= 1 or self.exchange is None:
            if nan:
                return Verdict(step=step, ok=False, kind="nan",
                               spike=spike,
                               detail="non-finite loss/gradients")
            self._mark_verified(step)
            return Verdict(step=step, ok=True, kind="verified",
                           spike=spike)

        # a NaN-tripped rank must STILL join the exchange: peers are
        # already entering this step's gather, and a rank that bails
        # early leaves them blocked in a collective that never
        # completes (or stalling a full board timeout) — the nan flag
        # rides the payload instead, so every rank reaches the same
        # verdict in the same number of rounds
        _metrics.GUARD_CHECKS.labels("digest").inc()
        payload = json.dumps({
            "step": step,
            "window": window,
            "nan": nan,
            "param": None if param_digest is None
            else _canon(param_digest).hex(),
        }).encode()
        from . import trace

        with trace.span("guard.exchange", step=step, round="digest"):
            boards = self.exchange.gather(f"chk-{step}", payload,
                                          world=self.world, rank=self.rank)
        views: List[Optional[dict]] = []
        for b in boards:
            try:
                views.append(None if b is None else json.loads(b))
            except ValueError:
                views.append(None)
        if any(v is None for v in views):
            missing = [r for r, v in enumerate(views) if v is None]
            get_logger().warning(
                "guard: step-%d agreement check missing rank(s) %s "
                "(exchange timeout) — window unverified", step, missing)
            if nan:
                return Verdict(step=step, ok=False, kind="nan",
                               spike=spike,
                               detail="non-finite loss/gradients")
            return Verdict(step=step, ok=True, kind="partial",
                           spike=spike,
                           detail=f"missing ranks {missing}")
        nan_ranks = [r for r, v in enumerate(views) if v.get("nan")]
        if nan_ranks:
            # non-finite values anywhere poison the window for every
            # rank (the allreduce already mixed them in): rollback-all,
            # no attribution — a NaN names a value, not its producer
            return Verdict(step=step, ok=False, kind="nan", spike=spike,
                           detail=f"non-finite on rank(s) {nan_ranks}")
        verdict = self._judge(step, views, recompute)
        verdict.spike = spike
        if verdict.ok:
            self._mark_verified(step)
        return verdict

    def _mark_verified(self, step: int) -> None:
        self.last_verified_step = step
        os.environ[ENV_VERIFIED] = str(step)  # survives the execv
        _metrics.GUARD_LAST_VERIFIED.set(step)
        if 0 <= self._rollback_barrier < step:
            # progress got PAST the step that tripped the last
            # rollback: the fault was transient — disarm the loop fuse
            self._rollback_count = 0
            self._rollback_barrier = -1
            os.environ.pop(ENV_ROLLBACK_COUNT, None)
            os.environ.pop(ENV_ROLLBACK_STEP, None)

    def _judge(self, step: int, views: Sequence[dict],
               recompute) -> Verdict:
        """Compare the gathered windows/param digests; attribute."""
        params = [v.get("param") for v in views]
        tables = [dict(v.get("window") or ()) for v in views]
        all_steps = sorted({s for t in tables for s in t})
        divergent = None
        for s in all_steps:
            vals = {t.get(s) for t in tables if s in t}
            if len(vals) > 1:
                divergent = s
                break
        # a rank that fingerprinted no params (the hook is optional)
        # abstains — absence must never read as disagreement
        params_agree = len({p for p in params if p is not None}) <= 1
        if divergent is None and params_agree:
            return Verdict(step=step, ok=True, kind="verified")

        _metrics.GUARD_TRIPS.labels("digest").inc()
        # -- attribute: majority vote at the first divergent point ----------
        if divergent is not None:
            votes = [t.get(divergent) for t in tables]
        else:
            votes = list(params)
        # a rank with NO entry at the divergent step (e.g. it restarted
        # mid-window) casts no vote: it neither supports nor contradicts
        # the majority, and must never be attributed by absence
        cast = [v for v in votes if v is not None]
        counts: Dict[Any, int] = {}
        for v in cast:
            counts[v] = counts.get(v, 0) + 1
        modal, modal_n = max(counts.items(), key=lambda kv: kv[1])
        attributed: List[int] = []
        if modal_n * 2 > len(cast):
            attributed = [r for r, v in enumerate(votes)
                          if v is not None and v != modal]
            outcome = ("self" if self.rank in attributed
                       else "peer" if attributed else "unattributed")
        else:
            # pairwise tie: the redundant-recompute vote — my own
            # recompute of the divergent step disagreeing with what I
            # published means the corruption was MINE (a transient flip
            # in my compute); a second exchange round shares verdicts
            self_ok = True
            if divergent is not None and recompute is not None:
                try:
                    mine = tables[self.rank].get(divergent)
                    redone = _canon(recompute(divergent)).hex()
                    self_ok = (mine is None) or (redone == mine)
                except Exception as e:  # a failing recompute is no vote
                    get_logger().warning(
                        "guard: recompute vote failed (%s: %s)",
                        type(e).__name__, e)
            from . import trace

            with trace.span("guard.exchange", step=step, round="vote"):
                flags = self.exchange.gather(
                    f"vote-{step}", b"1" if self_ok else b"0",
                    world=self.world, rank=self.rank)
            attributed = [r for r, f in enumerate(flags) if f == b"0"]
            outcome = ("self" if self.rank in attributed
                       else "peer" if attributed else "unattributed")
        _metrics.GUARD_ATTRIBUTIONS.labels(outcome).inc()
        get_logger().error(
            "guard: CROSS-RANK DIGEST MISMATCH at step %d (first "
            "divergent step %s) — attributed rank(s) %s%s",
            step, divergent, attributed or "none (unattributed)",
            " [THIS RANK]" if self.rank in attributed else "")
        return Verdict(
            step=step, ok=False, kind="mismatch", attributed=attributed,
            self_attributed=self.rank in attributed,
            divergent_step=divergent,
            detail=f"votes={votes}")

    # -- response policy -----------------------------------------------------

    def respond(self, verdict: Verdict, state=None) -> None:
        """Drive the response: nothing on ok; quarantine when THIS rank
        was attributed; roll back to the last verified checkpoint
        otherwise (non-elastic contexts raise :class:`IntegrityError`
        instead of exec-restarting)."""
        if verdict.ok:
            return
        if verdict.self_attributed:
            self.quarantine(verdict)
            return  # only reachable with a test exit_fn
        self.rollback(state=state, reason=verdict.kind,
                      step=verdict.step)

    def quarantine(self, verdict: Verdict) -> None:
        """This rank computed a wrong value: report the integrity
        failure to the elastic driver (which blacklists this whole
        HOST — a lying chip taints its machine) and exit with
        :data:`QUARANTINE_EXIT`."""
        get_logger().error(
            "guard: this rank attributed as corrupt at step %d — "
            "reporting integrity failure and quarantining (exit %d)",
            verdict.step, QUARANTINE_EXIT)
        try:
            # flight recorder: the quarantined rank's final spans —
            # including the chaos.inject event that framed it — leave
            # with the bundle, not with the process image
            from .trace import flight as _flight

            _flight.maybe_dump("quarantine", extra={
                "step": verdict.step,
                "divergent_step": verdict.divergent_step})
        except Exception:
            pass
        try:
            from .elastic.worker import (
                elastic_enabled, notification_manager,
            )

            if elastic_enabled():
                notification_manager.report_integrity_failure(
                    f"silent corruption attributed at step "
                    f"{verdict.step} (divergent step "
                    f"{verdict.divergent_step})")
                time.sleep(0.2)  # let the report drain before exit
        except Exception:
            pass  # the exit itself still blacklists the slot
        self._exit(QUARANTINE_EXIT)

    def rollback(self, state=None, reason: str = "mismatch",
                 step: Optional[int] = None) -> None:
        """Survivor response: discard the poisoned window.  Checkpoints
        newer than the last VERIFIED step are deleted, the board
        generation is bumped (stale exchange entries read as absent),
        and in elastic mode the worker exec-restarts with NO live
        snapshot — post-boot auto-resume then restores the newest
        surviving (verified, checksummed) checkpoint and the skipped
        steps re-run.  Non-elastic callers get :class:`IntegrityError`
        and own their own reload.

        NOTE: the checkpoint ring's ``keep`` must exceed the guard
        cadence (keep >= cadence + 1; 2x is comfortable) — a shallower
        ring can have every entry inside the poisoned window, leaving
        nothing to roll back to (the discard logs loudly and resume
        then degrades to step 0).

        LOOP FUSE: ``HVD_TPU_GUARD_MAX_ROLLBACKS`` (default 3)
        consecutive rollbacks without a verified check ever getting
        PAST the tripping step mean the fault reproduces
        deterministically — a real training divergence (lr blowup, bad
        batch), not transient corruption.  The guard then REFUSES to
        restart and raises :class:`IntegrityError` naming the step, so
        the real error surfaces instead of an unbounded restart loop
        burning the fleet."""
        del state  # the live state is poisoned by definition; never kept
        if step is not None:
            self._rollback_barrier = max(self._rollback_barrier,
                                         int(step))
        self._rollback_count += 1
        if self._rollback_count > self.max_rollbacks:
            get_logger().error(
                "guard: %d consecutive rollbacks never got past step "
                "%s — this failure REPRODUCES deterministically "
                "(likely a real training divergence, not transient "
                "corruption); refusing to restart again",
                self._rollback_count - 1, self._rollback_barrier)
            raise IntegrityError(
                f"integrity trip at step {self._rollback_barrier} "
                f"reproduced across {self._rollback_count - 1} "
                f"rollbacks ({reason}); refusing another restart — "
                "inspect the training run (HVD_TPU_GUARD_MAX_ROLLBACKS "
                "raises the fuse)")
        os.environ[ENV_ROLLBACK_COUNT] = str(self._rollback_count)
        os.environ[ENV_ROLLBACK_STEP] = str(self._rollback_barrier)
        _metrics.GUARD_ROLLBACKS.inc()
        get_logger().error(
            "guard: rolling back to last verified step %d (%s)",
            self.last_verified_step, reason)
        if self.ckpt_dir:
            from . import checkpoint as _checkpoint

            removed = _checkpoint.discard_newer_than(
                self.ckpt_dir, self.last_verified_step)
            if removed:
                get_logger().warning(
                    "guard: discarded %d checkpoint(s) inside the "
                    "poisoned window: %s", len(removed),
                    [os.path.basename(p) for p in removed])
        # bump the board generation (inherited across the execv): the
        # re-run's exchanges must never read this era's entries —
        # deleting them instead would race peers still mid-gather
        os.environ[ENV_GEN] = str(env_int(ENV_GEN, 0) + 1)
        os.environ[ENV_ROLLBACK_T0] = f"{time.time():.4f}"
        try:
            from .trace import flight as _flight

            _flight.maybe_dump("rollback", extra={
                "reason": reason,
                "verified_step": self.last_verified_step})
        except Exception:
            pass
        try:
            from .elastic.worker import (
                _persist_and_exec, elastic_enabled,
            )

            if elastic_enabled():
                _persist_and_exec(None)  # does not return
        except ImportError:
            pass
        raise IntegrityError(
            f"silent corruption detected ({reason}); rolled the "
            f"checkpoint ring back to verified step "
            f"{self.last_verified_step} — reload it to continue")

    # -- compiled-step convenience -------------------------------------------

    def on_train_step(self, step: int, loss, diag: Dict[str, Any],
                      params=None,
                      recompute: Optional[Callable[[int], Any]] = None,
                      state=None) -> Optional[Verdict]:
        """One call per compiled step from a training loop
        (:func:`training.fit_epoch` wires this): records the step's
        device digest without syncing, and at cadence performs the ONE
        bounded host sync (window + loss + param fingerprint), the
        agreement check, and the response.  Returns the verdict on
        cadence steps (None between them)."""
        if not self.enabled:
            return None
        self.observe_grads(step, diag["digest"])
        if not self.due(step):
            return None
        finite = bool(np.asarray(diag["finite"]))
        pdig = self.param_digest(params) if params is not None else None
        verdict = self.check(
            step, loss=float(np.asarray(loss)), finite=finite,
            param_digest=pdig, recompute=recompute)
        self.respond(verdict, state=state)
        return verdict
