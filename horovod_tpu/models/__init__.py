"""Model zoo used by benchmarks and examples (reference analog: examples/
model definitions, e.g. pytorch_synthetic_benchmark's ResNet-50)."""

from .resnet import (  # noqa: F401
    ResNet, ResNet18, ResNet34, ResNet50, ResNet101, ResNet152, ResNetTiny,
)
from .simple import LeNet, MLP  # noqa: F401
from .transformer import (  # noqa: F401
    Transformer, TransformerConfig, gpt_small,
)
