"""Model zoo used by benchmarks and examples (reference analog: examples/
model definitions, e.g. pytorch_synthetic_benchmark's ResNet-50)."""
