"""ResNet family (flax linen), TPU-first.

Reference analog: the ResNet-50 used by the reference's headline benchmarks
(examples/pytorch/pytorch_synthetic_benchmark.py loads torchvision
resnet50; examples/tensorflow2/tensorflow2_synthetic_benchmark.py uses
Keras ResNet50 — BASELINE.md tracked configs).  Written natively for TPU:

  * bfloat16 activations by default (MXU-friendly), float32 params/BN stats;
  * NHWC layout (XLA:TPU's native conv layout);
  * ``bn_axis_name`` turns every BatchNorm into a cross-replica (sync) BN
    via flax's ``axis_name`` — the TPU-native form of
    horovod/torch/sync_batch_norm.py (one fused psum over the mesh axis
    instead of hand-written allgather of moments).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut."""

    features: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.features, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.features, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.features * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.features * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNetBlock(nn.Module):
    """Basic 3x3 -> 3x3 block (ResNet-18/34)."""

    features: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.features, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.features, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.features, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


def space_to_depth_stem(x, features, conv, name="conv_init"):
    """The MLPerf space-to-depth stem: mathematically identical to the
    7x7/stride-2 stem conv but MXU-friendly.

    A 7x7/s2 conv over 3 channels runs the MXU at 3/128 input-channel
    occupancy.  Re-expressing the SAME linear map as a 2x2
    space-to-depth (H,W,3 -> H/2,W/2,12) followed by a 4x4/s1 conv with
    asymmetric (2,1) padding quadruples the contraction depth and
    removes the strided gather.  Weight correspondence (proven by
    tests/test_models_and_ring.py::test_space_to_depth_stem_equivalence):
    w4[kp,kq,(a,b,c),o] = w7[2kp+a-1, 2kq+b-1, c, o] with out-of-range
    taps zero (the 'pad 7x7 to 8x8' trick).
    """
    n, h, w, c = x.shape
    xs = x.reshape(n, h // 2, 2, w // 2, 2, c)
    xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
    return conv(features, (4, 4), (1, 1),
                padding=[(2, 1), (2, 1)], name=name)(xs)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    bn_axis_name: Optional[str] = None  # set to mesh axis for sync-BN
    stem: str = "conv"  # "conv" (classic 7x7/s2) | "space_to_depth"
    # Per-block rematerialization (save-nothing policy): a MEMORY
    # lever, not a speed lever — backward recomputes each block's convs
    # from the block input, cutting stored activations to block
    # boundaries, but on v5e it measured 21% SLOWER with MORE total
    # HBM traffic than XLA's stored-activation schedule (PERF.md round
    # 4 lever sweep).  Use it to fit larger batches/models, expecting
    # that throughput cost.
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype,
            kernel_init=nn.initializers.variance_scaling(
                2.0, "fan_out", "normal"
            ),
        )
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, axis_name=self.bn_axis_name,
        )
        x = x.astype(self.dtype)
        if self.stem == "space_to_depth":
            x = space_to_depth_stem(x, self.num_filters, conv)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        block_cls = self.block_cls
        if self.remat:
            # prevent_cse=True (default) is load-bearing: with CSE
            # allowed, XLA eliminated the recomputation and restored the
            # stored-activation schedule — measured identical FLOPs/time
            # to remat=False (PERF.md round 4 lever sweep)
            block_cls = nn.remat(block_cls)
        block_index = 0
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                # explicit names pinned to the unwrapped auto-names so
                # toggling remat never renames params (nn.remat's wrapper
                # class would otherwise prefix them Checkpoint...)
                x = block_cls(
                    features=self.num_filters * 2 ** i,
                    strides=strides, conv=conv, norm=norm, act=nn.relu,
                    name=f"{self.block_cls.__name__}_{block_index}",
                )(x)
                block_index += 1
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     name="head")(x.astype(jnp.float32))
        return x


ResNet18 = functools.partial(
    ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock
)
ResNet34 = functools.partial(
    ResNet, stage_sizes=[3, 4, 6, 3], block_cls=ResNetBlock
)
ResNet50 = functools.partial(
    ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock
)
ResNet101 = functools.partial(
    ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock
)
ResNet152 = functools.partial(
    ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock
)
# Tiny variant for CPU-mesh tests / multichip dry runs.
ResNetTiny = functools.partial(
    ResNet, stage_sizes=[1, 1], block_cls=ResNetBlock, num_filters=8,
    num_classes=10,
)
