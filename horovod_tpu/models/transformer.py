"""GPT-style decoder-only transformer (flax linen), TPU-first.

Reference analog: the BERT-Large SQuAD fine-tune and Llama-7B pretrain
configs tracked in BASELINE.json — the reference trains these data-parallel
via DistributedOptimizer; this model is the framework's flagship for the
same role, designed so sequence parallelism can shard the context:

  * ``attention_impl='dot'`` — plain causal attention (default);
  * ``attention_impl='flash'`` — the pallas VMEM-resident flash kernel
    (ops/flash_attention.py; 2-3x over dense at S=4096 on v5e);
  * ``attention_impl='ring'`` — ring attention over a mesh axis
    (parallel/ring_attention.py): the sequence dimension is sharded and
    KV blocks rotate via ``ppermute``, enabling contexts far beyond one
    chip's HBM.  The reference has no analog (SURVEY.md §5.7) — it only
    ships the alltoall/allgather primitives such schemes build on;
  * ``attention_impl='ring_flash'`` — same ring schedule with each block
    computed by the pallas flash kernels (no (S/n)² logits in HBM even
    within a block).

bfloat16 activations, float32 params; RoPE positions; pre-norm blocks.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..ops import spmd_ops
from ..ops.reduce_ops import Sum


# Named activation-remat policies for the decoder blocks (Chen et al.,
# 2016 sublinear memory; jax.checkpoint / jax.checkpoint_policies).  What
# the backward pass may READ from the forward without recomputing:
#   none          — every intermediate saved (no remat; fastest, most HBM)
#   dots          — MXU (matmul) outputs saved, elementwise/norm/softmax
#                   recomputed (jax.checkpoint_policies.checkpoint_dots)
#   dots_no_batch — only batch-free matmul outputs saved; in a decoder
#                   block every dot carries the batch dim, so this
#                   recomputes the whole block from its input (the
#                   historical `remat=True` policy)
#   full          — save nothing but the block input (jax.checkpoint's
#                   default policy): minimum memory, ~1/3 extra FLOPs
REMAT_POLICIES = {
    "none": None,
    "dots": "checkpoint_dots",
    "dots_no_batch": "checkpoint_dots_with_no_batch_dims",
    "full": None,
}


def _checkpoint_policy(name: str):
    """The jax.checkpoint_policies member for a policy name (None = save
    nothing, i.e. jax.checkpoint's default)."""
    attr = REMAT_POLICIES[name]
    return getattr(jax.checkpoint_policies, attr) if attr else None


def resolve_remat_policies(policy, num_layers: int,
                           default: str = "none"):
    """Normalize a remat-policy selection to one name per block.

    ``policy`` may be None (→ ``default`` everywhere), a single policy
    name applied to every block, or a sequence of ``num_layers`` names
    selecting per block (e.g. remat only the deep half of the stack).
    """
    if policy is None:
        policy = default
    if isinstance(policy, str):
        policies = (policy,) * num_layers
    else:
        policies = tuple(policy)
        if len(policies) != num_layers:
            raise ValueError(
                f"per-block remat policy needs {num_layers} entries, "
                f"got {len(policies)}"
            )
    for p in policies:
        if p not in REMAT_POLICIES:
            raise ValueError(
                f"unknown remat policy {p!r}; expected one of "
                f"{sorted(REMAT_POLICIES)}"
            )
    return policies


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    num_layers: int = 12
    num_heads: int = 12
    # GQA (Ainslie et al., 2023; the Llama-2-70B/Llama-3 layout): K/V
    # projections produce this many heads, shared by num_heads/num_kv_heads
    # query heads each.  None (default) = MHA.  Every attention_impl
    # (dot, flash, ring, ring_flash) consumes the grouped K/V NATIVELY —
    # the dense paths group their einsums and the pallas kernels share
    # each K/V head across its query-head group in VMEM — so attention
    # K/V bytes/FLOPs, ring comms, the K/V projections and any KV cache
    # all shrink by num_heads/num_kv_heads; nothing is ever repeated.
    num_kv_heads: Optional[int] = None
    head_dim: int = 64
    mlp_ratio: int = 4
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    # 'dot' | 'ring'; 'ring' requires seq_axis_name and running inside
    # shard_map with the sequence sharded over that axis.
    attention_impl: str = "dot"
    seq_axis_name: Optional[str] = None
    # False = bidirectional (encoder / BERT-family) attention; supported
    # by every impl — dot, the pallas flash kernel, and both ring modes
    # (the causal block-skipping simply switches off)
    causal: bool = True
    # Mistral-style sliding-window attention: each token attends the last
    # `window` positions, itself included (q_pos - k_pos < window, the
    # Mistral/HF convention; symmetric reach when causal=False).  Exact on
    # every impl: mask-level on 'dot' and dense 'ring'; on 'flash' and
    # 'ring_flash' out-of-window blocks are SKIPPED in the kernels —
    # compute O(S·window), the real Mistral training path — and a causal
    # window additionally truncates the ring rotation itself
    # (parallel/ring_attention.py ring_window_steps), so out-of-window
    # ring steps cost neither compute nor comms.
    window: Optional[int] = None
    # rematerialize each decoder block in the backward pass: activation
    # memory drops from O(layers) to O(1) blocks at ~1/3 extra FLOPs —
    # the standard TPU memory/compute trade (jax.checkpoint) that lets
    # long-context and large-batch configs fit HBM.  Legacy boolean
    # switch: True ≡ remat_policy="dots_no_batch" (kept for callers
    # predating configurable policies).
    remat: bool = False
    # Configurable activation-remat policy (docs/OPTIM.md policy
    # matrix): None (derive from `remat`), a REMAT_POLICIES name applied
    # to every block, or a tuple of num_layers names selecting PER
    # BLOCK — e.g. ("none",)*6 + ("full",)*6 remats only the deep half.
    remat_policy: Any = None
    # Megatron-style tensor sharding (Shoeybi et al.; docs/SERVING.md
    # sharding section): name of a mesh axis the module is being traced
    # under (shard_map).  When set AND bound, every sublayer runs on its
    # 1/tp slice — q/k/v projections and attention per LOCAL head group
    # (kv heads shard too, so the paged KV pool shards with them), MLP
    # gate/up column-split — and the two row-parallel projections
    # (attention output, MLP down) finish with ONE psum each: the
    # classic 2-psums-per-block TP schedule.  Unbound or None degrades
    # to the unsharded program (identical params, identical math), so
    # the same config serves single- and multi-chip.  num_heads,
    # num_kv_heads and d_model*mlp_ratio must all divide by the axis
    # size (validated at trace).  Inference-first: the serving engine
    # is the consumer; training paths keep using parallel/sharded.py.
    shard_axis: Optional[str] = None

    def __post_init__(self):
        kv = self.num_kv_heads
        if kv is not None and (kv <= 0 or self.num_heads % kv):
            raise ValueError(
                f"num_heads ({self.num_heads}) must be a multiple of "
                f"num_kv_heads ({kv})"
            )
        if self.remat_policy is not None:
            # normalize early so invalid names fail at config build, and
            # store a hashable tuple (the dataclass is frozen/hashable)
            object.__setattr__(
                self, "remat_policy",
                self.remat_policy if isinstance(self.remat_policy, str)
                else tuple(self.remat_policy),
            )
            resolve_remat_policies(self.remat_policy, self.num_layers)

    def block_remat_policies(self):
        """Per-block policy names (``remat_policy`` resolved, with the
        legacy ``remat`` bool as the default)."""
        return resolve_remat_policies(
            self.remat_policy, self.num_layers,
            default="dots_no_batch" if self.remat else "none",
        )

    @property
    def d_model(self) -> int:
        return self.num_heads * self.head_dim


def rope(x: jax.Array, positions: jax.Array) -> jax.Array:
    """Rotary position embedding; x: (B, S, H, D), positions: (B, S)."""
    d = x.shape[-1]
    freqs = 1.0 / (10000.0 ** (np.arange(0, d, 2) / d))
    angles = positions[..., None].astype(jnp.float32) * freqs  # (B, S, D/2)
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], -1)
    return out.astype(x.dtype)


def sliding_mask(q_pos, k_pos, causal=True, window=None):
    """(Sq, Sk) bool attention mask shared by the dot oracle and the
    ring path (the two must stay exactly equivalent).  Causal:
    ``q_pos >= k_pos``; window (Mistral/HF convention): each query
    attends the last ``window`` positions, ITSELF INCLUDED
    (``q_pos - k_pos < window``; symmetric |Δ| < window when
    bidirectional).  ``window`` must be >= 1: a non-positive window
    would mask every entry and silently degrade to uniform attention
    (dot) or NaN (ring online-softmax)."""
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    delta = q_pos[:, None] - k_pos[None, :]
    mask = (delta >= 0) if causal else jnp.ones_like(delta, bool)
    if window is not None:
        reach = delta if causal else jnp.abs(delta)
        mask = mask & (reach < window)
    return mask


def causal_dot_attention(q, k, v, *, q_offset=0, k_offset=0, causal=True,
                         window=None):
    """Standard attention; offsets support sequence-sharded blocks.

    q: (B, S, H, D); k, v: (B, S, H_kv, D) with H_kv | H — under GQA
    (H_kv < H) the einsums GROUP the contraction (query head
    ``hk*g + j`` reads kv head ``hk``) instead of repeating K/V to full
    heads, so no inflated K/V tensor is ever materialized.  Softmax in
    float32 (TPU numerics), matmuls in the input dtype so they hit the
    MXU in bf16.  ``causal=False`` is the bidirectional (encoder /
    BERT-family) form — no mask at all.  ``window``: Mistral-style
    sliding window — each token attends the last ``window`` positions,
    itself included (see ``sliding_mask``).
    """
    b, s_q, h, d = q.shape
    s_k, h_kv = k.shape[1], k.shape[2]
    if h_kv <= 0 or h % h_kv:
        raise ValueError(
            f"query heads ({h}) must be a multiple of kv heads ({h_kv})"
        )
    if h_kv != h:
        qg = q.reshape(b, s_q, h_kv, h // h_kv, d)
        logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).reshape(
            b, h, s_q, s_k
        ) / jnp.sqrt(d).astype(q.dtype)
    else:
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(d).astype(
            q.dtype)
    logits = logits.astype(jnp.float32)
    if causal or window is not None:
        mask = sliding_mask(
            q_offset + jnp.arange(q.shape[1]),
            k_offset + jnp.arange(k.shape[1]),
            causal=causal, window=window,
        )
        logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if h_kv != h:
        return jnp.einsum(
            "bhgqk,bkhd->bqhgd",
            probs.reshape(b, h_kv, h // h_kv, s_q, s_k), v,
        ).reshape(b, s_q, h, d)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _shard_size(cfg: TransformerConfig) -> int:
    """Bound size of ``cfg.shard_axis`` (1 when unset/unbound), with the
    divisibility contract checked at trace: every per-chip slice —
    query heads, kv heads (the paged pool shards with them) and the MLP
    hidden — must be exact, or shards would disagree on shapes."""
    from ..parallel._mesh_utils import axis_size_or_1

    tp = axis_size_or_1(cfg.shard_axis)
    if tp > 1:
        kv = cfg.num_kv_heads or cfg.num_heads
        hidden = cfg.d_model * cfg.mlp_ratio
        if cfg.num_heads % tp or kv % tp or hidden % tp:
            raise ValueError(
                f"shard_axis {cfg.shard_axis!r} of size {tp} must divide "
                f"num_heads ({cfg.num_heads}), num_kv_heads ({kv}) and "
                f"d_model*mlp_ratio ({hidden})")
    return tp


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, paged=None, layer: int = 0):
        cfg = self.cfg
        dense = functools.partial(
            nn.DenseGeneral, dtype=cfg.dtype, use_bias=False
        )
        # divisibility/positivity validated in TransformerConfig.__post_init__
        kv_heads = (cfg.num_heads if cfg.num_kv_heads is None
                    else cfg.num_kv_heads)
        # Megatron head sharding: under a bound shard_axis this trace
        # sees the LOCAL head slice — q/k/v kernels are (D, H/tp, d)
        # column slices, attention runs on H/tp query heads over the
        # H_kv/tp kv heads this chip owns (the GQA group ratio is
        # shard-invariant), and the output projection below reassembles
        # with one psum (row-parallel).
        tp = _shard_size(cfg)
        heads = cfg.num_heads // tp
        kv_heads = kv_heads // tp
        q = dense(features=(heads, cfg.head_dim), name="q")(x)
        k = dense(features=(kv_heads, cfg.head_dim), name="k")(x)
        v = dense(features=(kv_heads, cfg.head_dim), name="v")(x)
        q = rope(q, positions)
        k = rope(k, positions)
        if paged is not None:
            # serving path (docs/SERVING.md): K/V live in the paged
            # cache's block pools, not in this activation.  Chunk (the
            # mixed chunked-prefill + decode step — whole-prompt
            # prefill is its offset-0 case) writes each row's chunk at
            # its own offset then attends the GATHERED pages — cached
            # prefix included — with per-row global offsets; decode
            # writes the one new token then attends the gathered pages
            # with the q_len=1 kernel.
            if cfg.attention_impl not in ("dot", "flash"):
                raise ValueError(
                    f"paged serving supports attention_impl 'dot'/'flash', "
                    f"not {cfg.attention_impl!r}")
            if not cfg.causal:
                raise ValueError("paged serving requires causal=True")
            if paged.mode == "chunk":
                from ..ops.flash_attention import flash_chunk_attention

                paged.write_chunk(layer, k, v)
                gk, gv, kv_start = paged.gather(
                    layer, window=cfg.window, q_span=k.shape[1])
                out = flash_chunk_attention(
                    q, gk, gv, paged.lens, window=cfg.window,
                    kv_start=kv_start,
                )
            else:
                from ..ops.flash_attention import flash_decode_attention

                paged.write_decode(layer, k, v)
                gk, gv, kv_start = paged.gather(layer, window=cfg.window)
                out = flash_decode_attention(
                    q, gk, gv, paged.lens + 1, window=cfg.window,
                    kv_start=kv_start,
                )
        # GQA needs no expansion: every impl consumes (B, S, H_kv, D)
        # K/V natively — the kernels/einsums share each kv head across
        # its query-head group, so the group factor is saved in
        # attention HBM bytes, FLOPs and ring comms, not just in the
        # projections.
        elif cfg.attention_impl in ("ring", "ring_flash"):
            from ..parallel.ring_attention import ring_attention

            out = ring_attention(
                q, k, v, axis_name=cfg.seq_axis_name,
                impl="flash" if cfg.attention_impl == "ring_flash"
                else "dense",
                causal=cfg.causal,
                window=cfg.window,
            )
        elif cfg.attention_impl == "flash":
            from ..ops.flash_attention import flash_attention

            out = flash_attention(q, k, v, causal=cfg.causal,
                                  window=cfg.window)
        else:
            out = causal_dot_attention(q, k, v, causal=cfg.causal,
                                       window=cfg.window)
        out = nn.DenseGeneral(
            features=cfg.d_model, axis=(-2, -1), dtype=cfg.dtype,
            use_bias=False, name="o",
        )(out)
        if tp > 1:
            # row-parallel output projection: each chip contracted its
            # local head slice (the kernel is an (H/tp, d, D) row slice
            # of the global one); ONE psum reassembles the sublayer —
            # the first of Megatron's two collectives per block
            out = spmd_ops.allreduce(out, op=Sum, axis=cfg.shard_axis)
        return out


class MlpBlock(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        # Megatron MLP under a bound shard_axis: gate/up are COLUMN
        # slices ((D, F/tp) kernels — no comms, the nonlinearity is
        # elementwise on the slice), down is the ROW slice ((F/tp, D))
        # whose partial products ONE psum reassembles — the second of
        # Megatron's two collectives per block.  tp == 1 is the
        # unsharded program verbatim.
        tp = _shard_size(cfg)
        hidden = cfg.d_model * cfg.mlp_ratio // tp
        gate = nn.Dense(hidden, dtype=cfg.dtype, use_bias=False, name="gate")(x)
        up = nn.Dense(hidden, dtype=cfg.dtype, use_bias=False, name="up")(x)
        out = nn.Dense(
            cfg.d_model, dtype=cfg.dtype, use_bias=False, name="down"
        )(nn.silu(gate) * up)
        if tp > 1:
            out = spmd_ops.allreduce(out, op=Sum, axis=cfg.shard_axis)
        return out


class Block(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, paged=None, layer: int = 0):
        cfg = self.cfg
        norm = functools.partial(
            nn.RMSNorm, dtype=cfg.dtype, epsilon=1e-5
        )
        x = x + Attention(cfg, name="attn")(
            norm(name="ln1")(x), positions, paged=paged, layer=layer)
        x = x + MlpBlock(cfg, name="mlp")(norm(name="ln2")(x))
        return x


class Transformer(nn.Module):
    """Decoder-only LM.  ``__call__(tokens, positions=None) -> logits``."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, positions=None, train: bool = True,
                 paged=None):
        cfg = self.cfg
        if positions is None:
            local = jnp.arange(tokens.shape[1])
            if cfg.attention_impl in ("ring", "ring_flash") and \
                    cfg.seq_axis_name:
                # sequence is sharded over the axis: global position =
                # shard_index * S_local + local offset (RoPE must match
                # the global causal offsets ring_attention masks with)
                local = (
                    jax.lax.axis_index(cfg.seq_axis_name) * tokens.shape[1]
                    + local
                )
            positions = jnp.broadcast_to(local, tokens.shape)
        emb = nn.Embed(
            cfg.vocab_size, cfg.d_model,
            dtype=cfg.dtype, name="embed",
        )
        x = emb(tokens)
        # per-block remat policy (flax-aware checkpoint transform); one
        # lifted class per distinct policy so identical policies share a
        # transform
        policies = cfg.block_remat_policies() if train else None
        block_cls_for = {"none": Block}
        for i in range(cfg.num_layers):
            pol = policies[i] if policies is not None else "none"
            block_cls = block_cls_for.get(pol)
            if block_cls is None:
                block_cls = nn.remat(
                    Block, policy=_checkpoint_policy(pol)
                )
                block_cls_for[pol] = block_cls
            if paged is not None:
                # serving (inference-only) path: the paged-cache state
                # threads through every block, each addressing its own
                # pool layer; never composes with remat (train=False)
                x = block_cls(cfg, name=f"layer_{i}")(
                    x, positions, paged, i)
            else:
                x = block_cls(cfg, name=f"layer_{i}")(x, positions)
        x = nn.RMSNorm(dtype=cfg.dtype, epsilon=1e-5, name="ln_f")(x)
        logits = emb.attend(x.astype(jnp.float32))
        if paged is not None:
            return logits, paged
        return logits


def overlap_segments(model: "Transformer", tokens, targets,
                     loss_fn=None):
    """Segment-chain view of :class:`Transformer` for the
    backward/collective overlap scheduler (``ops/overlap.py``,
    docs/tensor-fusion.md): one :class:`~horovod_tpu.ops.overlap.Segment`
    per decoder block plus the embed and head links, each applying the
    SAME flax submodules ``__call__`` composes (``Block``/``nn.Embed``/
    ``nn.RMSNorm`` applied standalone against their param subtrees), so
    the chain's math is identical op-for-op — only the backward gains
    bucket boundaries.  The tied embedding is read by both the first and
    last segment; its gradient therefore completes at the embed segment
    and rides the final bucket.

    Per-block remat policies compose: a non-``none`` policy wraps that
    block's segment in ``jax.checkpoint`` with the same policy the
    in-module ``nn.remat`` lift would use.

    The sequence-sharded ring impls position tokens off the mesh axis —
    segment them via the multi-axis chain
    (``parallel.sharded.overlap_segments``) instead.
    """
    from ..ops.overlap import Segment

    cfg = model.cfg
    if cfg.attention_impl in ("ring", "ring_flash"):
        raise ValueError(
            "overlap_segments does not support the sequence-sharded ring "
            "impls; use parallel.sharded.overlap_segments' chain or the "
            "plain (unoverlapped) step"
        )
    if loss_fn is None:
        import optax

        def loss_fn(logits, labels):
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()

    positions = jnp.broadcast_to(
        jnp.arange(tokens.shape[1]), tokens.shape
    )
    embed_mod = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype)

    def seg_embed(params, toks):
        return embed_mod.apply({"params": params["embed"]}, toks)

    def make_block(i, policy):
        def seg(params, x):
            return Block(cfg).apply(
                {"params": params[f"layer_{i}"]}, x, positions
            )

        if policy != "none":
            seg = jax.checkpoint(seg, policy=_checkpoint_policy(policy))
        return Segment(seg, keys=(f"layer_{i}",))

    def seg_head(params, x):
        x = nn.RMSNorm(dtype=cfg.dtype, epsilon=1e-5).apply(
            {"params": params["ln_f"]}, x
        )
        logits = embed_mod.apply(
            {"params": params["embed"]}, x.astype(jnp.float32),
            method=nn.Embed.attend,
        )
        return loss_fn(logits, targets)

    policies = cfg.block_remat_policies()
    return (
        [Segment(seg_embed, keys=("embed",))]
        + [make_block(i, policies[i]) for i in range(cfg.num_layers)]
        + [Segment(seg_head, keys=("ln_f", "embed"))]
    )


def modeled_activation_bytes(cfg: TransformerConfig, batch: int,
                             seq: Optional[int] = None) -> dict:
    """Modeled forward-to-backward activation bytes under the config's
    remat policies — the capacity arithmetic PERF.md round 6 reasons
    with (batch 1024 = "remat territory"), pinned by
    tests/test_remat_policies.py.

    Counts, per block, the tensors the backward READS without
    recomputation (matmul inputs/outputs and nonlinear intermediates in
    the activation dtype; attention-impl-agnostic — flash never
    materializes the S×S probabilities, so no quadratic term appears):

      none          — block input, ln1/ln2 outputs, q, k, v, attention
                      context, gate, up, silu(gate)*up
      dots          — block input + matmul outputs only (q, k, v,
                      context, o-proj, gate, up, down-proj)
      dots_no_batch — block input only (every decoder dot carries the
                      batch dim, so the policy saves none of them)
      full          — block input only

    Returns ``{"total_bytes", "per_block_bytes": {policy: bytes},
    "policies"}``; ``total_bytes`` sums the per-block figure over the
    resolved per-block policies.
    """
    s = int(seq if seq is not None else cfg.max_seq_len)
    act = jnp.dtype(cfg.dtype).itemsize
    kv_heads = cfg.num_kv_heads or cfg.num_heads
    bsd = batch * s * cfg.d_model * act          # one (B, S, D) tensor
    kv = 2 * batch * s * kv_heads * cfg.head_dim * act   # K and V
    f = batch * s * cfg.d_model * cfg.mlp_ratio * act    # one MLP hidden
    per_block = {
        "none": 5 * bsd + kv + 3 * f,   # input, ln1, q, ctx, ln2 + k,v
                                        # + gate, up, silu(gate)*up
        "dots": 5 * bsd + kv + 2 * f,   # input, q, ctx, o, down + k,v
                                        # + gate, up
        "dots_no_batch": bsd,           # block input only
        "full": bsd,                    # block input only
    }
    policies = cfg.block_remat_policies()
    return {
        "total_bytes": sum(per_block[p] for p in policies),
        "per_block_bytes": per_block,
        "policies": policies,
    }


# Named sizes (flagship family; Llama-ish shapes for the pretrain config).
def gpt_small(**kw) -> TransformerConfig:
    return TransformerConfig(num_layers=12, num_heads=12, head_dim=64, **kw)


def gpt_tiny(**kw) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=256, num_layers=2, num_heads=2, head_dim=16,
        max_seq_len=128, **kw,
    )


def llama_7b(**kw) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=32000, num_layers=32, num_heads=32, head_dim=128,
        max_seq_len=4096, **kw,
    )


def llama3_8b(**kw) -> TransformerConfig:
    """Llama-3-8B layout: GQA with 8 K/V heads over 32 query heads."""
    return TransformerConfig(
        vocab_size=128256, num_layers=32, num_heads=32, num_kv_heads=8,
        head_dim=128, max_seq_len=8192, **kw,
    )
