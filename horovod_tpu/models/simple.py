"""Small models: MLP and LeNet.

Reference analog: examples/pytorch/pytorch_mnist.py's LeNet-style Net (a
BASELINE.md tracked config) and the MNIST MLPs across the reference's
examples/ — used here for the minimum end-to-end slice (SURVEY.md §7.2
step 3) and CI-speed training tests.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    features: Sequence[int] = (128, 64)
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for f in self.features:
            x = nn.relu(nn.Dense(f, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


class LeNet(nn.Module):
    """LeNet-5-style conv net matching the reference's pytorch_mnist.py Net:
    two conv+pool stages then two dense layers."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        # expects NHWC (e.g. (B, 28, 28, 1))
        x = x.astype(self.dtype)
        x = nn.Conv(10, (5, 5), dtype=self.dtype)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = nn.Conv(20, (5, 5), dtype=self.dtype)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(50, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
