"""MXNet NDArray collectives over the XLA engine.

Reference parity: horovod/mxnet/mpi_ops.py + the C++ binding it fronts
(mxnet/mpi_ops.cc, adapter.cc, tensor_util.cc — SURVEY.md §2.3).  The
reference wraps ``mxnet.nd.NDArray`` into ``common::Tensor`` and pushes
the collective onto MXNet's dependency engine so it completes
asynchronously behind engine reads; here the NDArray round-trips through
numpy (``asnumpy()`` / ``t[:] = out``) into the same eager engine every
other adapter uses, and ops complete before returning.  The reference's
``priority`` argument orders work on the MXNet engine; our engine
negotiates readiness cross-rank instead, so ``priority`` is accepted for
signature parity and ignored (documented divergence).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

import mxnet as mx

from ..common.process_sets import ProcessSet
from ..ops import collective_ops as _ops
from ..ops.reduce_ops import ReduceOp


def _to_np(tensor) -> np.ndarray:
    if not isinstance(tensor, mx.nd.NDArray):
        raise ValueError(
            f"horovod_tpu.mxnet ops take mxnet.nd.NDArray, got "
            f"{type(tensor).__name__}"
        )
    return tensor.asnumpy()


def _from_np(a, like) -> "mx.nd.NDArray":
    return mx.nd.array(np.asarray(a), ctx=like.context, dtype=like.dtype)


def _write_back(tensor, a) -> None:
    tensor[:] = np.asarray(a, dtype=tensor.dtype).reshape(tensor.shape)


# -- allreduce ---------------------------------------------------------------


def allreduce(tensor, average: Optional[bool] = None,
              name: Optional[str] = None, priority: int = 0,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              op: Optional[ReduceOp] = None,
              process_set: Optional[ProcessSet] = None):
    """Reference: horovod/mxnet/mpi_ops.py allreduce — returns a new
    averaged NDArray."""
    out = _ops.allreduce(
        _to_np(tensor), average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set,
    )
    return _from_np(out, tensor)


def allreduce_(tensor, average: Optional[bool] = None,
               name: Optional[str] = None, priority: int = 0,
               prescale_factor: float = 1.0, postscale_factor: float = 1.0,
               op: Optional[ReduceOp] = None,
               process_set: Optional[ProcessSet] = None):
    """In-place allreduce (reference: allreduce_)."""
    out = _ops.allreduce(
        _to_np(tensor), average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set,
    )
    _write_back(tensor, out)
    return tensor


def grouped_allreduce(tensors: Sequence, average: Optional[bool] = None,
                      name: Optional[str] = None, priority: int = 0,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      op: Optional[ReduceOp] = None,
                      process_set: Optional[ProcessSet] = None) -> List:
    outs = _ops.grouped_allreduce(
        [_to_np(t) for t in tensors], average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set,
    )
    return [_from_np(o, t) for o, t in zip(outs, tensors)]


def grouped_allreduce_(tensors: Sequence, average: Optional[bool] = None,
                       name: Optional[str] = None, priority: int = 0,
                       prescale_factor: float = 1.0,
                       postscale_factor: float = 1.0,
                       op: Optional[ReduceOp] = None,
                       process_set: Optional[ProcessSet] = None) -> List:
    outs = _ops.grouped_allreduce(
        [_to_np(t) for t in tensors], average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set,
    )
    for t, o in zip(tensors, outs):
        _write_back(t, o)
    return list(tensors)


# -- allgather ---------------------------------------------------------------


def allgather(tensor, name: Optional[str] = None, priority: int = 0,
              process_set: Optional[ProcessSet] = None):
    """Reference: horovod/mxnet/mpi_ops.py allgather — concatenates along
    dim 0 (ranks may differ in dim 0)."""
    out = _ops.allgather(_to_np(tensor), name=name, process_set=process_set)
    return _from_np(out, tensor)


# -- broadcast ---------------------------------------------------------------


def broadcast(tensor, root_rank: int, name: Optional[str] = None,
              priority: int = 0,
              process_set: Optional[ProcessSet] = None):
    out = _ops.broadcast(_to_np(tensor), root_rank, name=name,
                         process_set=process_set)
    return _from_np(out, tensor)


def broadcast_(tensor, root_rank: int, name: Optional[str] = None,
               priority: int = 0,
               process_set: Optional[ProcessSet] = None):
    out = _ops.broadcast(_to_np(tensor), root_rank, name=name,
                         process_set=process_set)
    _write_back(tensor, out)
    return tensor


# -- alltoall / reducescatter ------------------------------------------------


def alltoall(tensor, splits=None, name: Optional[str] = None,
             priority: int = 0,
             process_set: Optional[ProcessSet] = None) -> Tuple:
    """Reference: horovod/mxnet/mpi_ops.py alltoall — returns
    (received, received_splits)."""
    np_splits = None if splits is None else _to_np(splits)
    received, recv_splits = _ops.alltoall(
        _to_np(tensor), splits=np_splits, name=name, process_set=process_set
    )
    return (_from_np(received, tensor),
            mx.nd.array(np.asarray(recv_splits), dtype="int32"))


def reducescatter(tensor, op: Optional[ReduceOp] = None,
                  name: Optional[str] = None, priority: int = 0,
                  process_set: Optional[ProcessSet] = None):
    out = _ops.reducescatter(_to_np(tensor), op=op, name=name,
                             process_set=process_set)
    return _from_np(out, tensor)


def grouped_reducescatter(tensors: Sequence, op: Optional[ReduceOp] = None,
                          name: Optional[str] = None, priority: int = 0,
                          process_set: Optional[ProcessSet] = None) -> List:
    outs = _ops.grouped_reducescatter(
        [_to_np(t) for t in tensors], op=op, name=name,
        process_set=process_set,
    )
    return [_from_np(o, t) for o, t in zip(outs, tensors)]


def barrier(process_set: Optional[ProcessSet] = None) -> None:
    _ops.barrier(process_set=process_set)


def join() -> int:
    return _ops.join()
