"""State broadcast helpers for MXNet models.

Reference parity: horovod/mxnet/__init__.py broadcast_parameters (the
reference keeps it beside the trainer; split out here to mirror the
torch adapter's layout) — SURVEY.md §2.3 MXNet binding row.
"""

from __future__ import annotations

from typing import Optional

import mxnet as mx

from ..common.process_sets import ProcessSet
from . import mpi_ops


def _deferred_init_error():
    """mx.gluon.parameter.DeferredInitializationError, reached via
    getattr so both real mxnet and the test fake resolve it."""
    param_ns = getattr(getattr(mx, "gluon", None), "parameter", None)
    return getattr(param_ns, "DeferredInitializationError", ())


def _hook_deferred_broadcast(p, root_rank: int, name: str,
                             process_set: Optional[ProcessSet]) -> None:
    """Broadcast a deferred-init gluon parameter as soon as its shape is
    resolved (reference: _append_broadcast_init wrapping _init_impl)."""
    orig_init_impl = p._init_impl

    def wrapped(*args, **kwargs):
        orig_init_impl(*args, **kwargs)
        for i, d in enumerate(p.list_data()):
            mpi_ops.broadcast_(d, root_rank,
                               name=f"parameter.{name}.{i}",
                               process_set=process_set)

    p._init_impl = wrapped


def broadcast_parameters(params, root_rank: int = 0,
                         prefix: Optional[str] = None,
                         process_set: Optional[ProcessSet] = None) -> None:
    """Broadcast parameters from ``root_rank`` in place.

    Accepts either a plain ``dict`` of name → NDArray (e.g. a module's
    ``get_params()`` arg/aux dicts) or a gluon parameter collection
    (name → ``gluon.Parameter``), matching the reference's two accepted
    shapes.  Gluon parameters whose shape is still unresolved
    (``DeferredInitializationError``) are broadcast lazily right after
    their deferred initialization runs, like the reference.
    """
    prefix = prefix or ""
    if params is None:
        return
    if not hasattr(params, "items"):
        raise ValueError(
            "broadcast_parameters expects a dict of name->NDArray or a "
            "gluon parameter collection"
        )
    tensors = []
    deferred_t = _deferred_init_error()
    for name, p in sorted(params.items(), key=lambda kv: kv[0]):
        if hasattr(p, "list_data"):  # gluon.Parameter
            try:
                data = p.list_data()
            except Exception as exc:
                if deferred_t and isinstance(exc, deferred_t):
                    _hook_deferred_broadcast(p, root_rank,
                                             f"{prefix}{name}", process_set)
                    continue
                raise
            tensors.extend((f"{prefix}{name}.{i}", d)
                           for i, d in enumerate(data))
        else:  # bare NDArray
            tensors.append((f"{prefix}{name}", p))
    for name, tensor in tensors:
        mpi_ops.broadcast_(tensor, root_rank,
                           name=f"parameter.{name}",
                           process_set=process_set)
