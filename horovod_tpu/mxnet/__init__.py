"""horovod_tpu.mxnet: the MXNet framework adapter.

Reference parity: the ``horovod.mxnet`` surface (horovod/mxnet/__init__.py,
mpi_ops.py + the C++ binding mxnet/mpi_ops.cc, adapter.cc,
tensor_util.cc — SURVEY.md §2.3).  A reference Gluon script needs only
its import changed::

    import horovod_tpu.mxnet as hvd
    hvd.init()
    trainer = hvd.DistributedTrainer(net.collect_params(), "sgd",
                                     {"learning_rate": 0.01})
    hvd.broadcast_parameters(net.collect_params(), root_rank=0)

Design: like the torch adapter, MXNet stays the model frontend and
collectives execute through the shared eager XLA engine via a numpy
bridge (``asnumpy()`` in, ``t[:] =`` out).  mxnet itself is not
installable in this image (archived upstream), so this adapter is
exercised by contract tests against a faked ``mxnet`` module
(tests/_fake_modules/mxnet) the same way the pyspark/ray launch paths
are — the adapter bodies below run for real; only NDArray storage is
faked.
"""

from __future__ import annotations

from typing import Optional

import mxnet as mx

# lifecycle + topology (shared with the JAX surface)
from ..common.basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, local_rank, size, local_size,
    cross_rank, cross_size, is_homogeneous, xla_built, nccl_built,
    mpi_enabled, mpi_built, mpi_threads_supported, gloo_built,
    gloo_enabled, ccl_built, cuda_built, rocm_built, ddl_built,
    native_built, start_timeline, stop_timeline,
)
from ..common.exceptions import (  # noqa: F401
    HorovodInternalError, HostsUpdatedInterrupt,
)
from ..common.process_sets import (  # noqa: F401
    ProcessSet, global_process_set,
)
from .. import add_process_set, remove_process_set  # noqa: F401
from ..ops.reduce_ops import (  # noqa: F401
    Adasum, Average, Max, Min, Product, ReduceOp, Sum,
)
from .functions import broadcast_parameters  # noqa: F401
from .mpi_ops import (  # noqa: F401
    allgather, allreduce, allreduce_, alltoall, barrier, broadcast,
    broadcast_, grouped_allreduce, grouped_allreduce_,
    grouped_reducescatter, join, reducescatter,
)
from . import mpi_ops  # noqa: F401


def _split_groups(items, num_groups: int):
    """Partition items into num_groups contiguous buckets (reference:
    horovod.mxnet num_groups grouped-allreduce batching); num_groups<=0
    means one bucket."""
    if num_groups <= 0 or num_groups >= len(items):
        return [items] if num_groups <= 0 else [[it] for it in items]
    size_, rem = divmod(len(items), num_groups)
    out, start = [], 0
    for g in range(num_groups):
        end = start + size_ + (1 if g < rem else 0)
        out.append(items[start:end])
        start = end
    return out


class DistributedOptimizer(mx.optimizer.Optimizer):
    """Wrap an ``mx.optimizer.Optimizer`` so every ``update()`` allreduces
    the gradient first (reference: horovod/mxnet/__init__.py
    DistributedOptimizer).

    Reference math, re-based on the engine's Average: the wire carries an
    AVERAGE allreduce of ``grad / gradient_predivide_factor`` and the
    wrapped optimizer's ``rescale_grad`` absorbs the remaining
    ``gradient_predivide_factor``.  (The reference ships SUM + a
    ``rescale_grad /= size`` fold; here the engine's Average supplies the
    1/N with the correct contributor count for any chips-per-process
    topology — the ADVICE-r3 cross_size()-vs-size() trap.)
    """

    def __init__(self, optimizer, gradient_predivide_factor: float = 1.0,
                 num_groups: int = 0,
                 process_set: Optional[ProcessSet] = None):
        if isinstance(optimizer, DistributedOptimizer):
            raise ValueError(
                "optimizer is already a horovod_tpu DistributedOptimizer"
            )
        self._optimizer = optimizer
        self._predivide = float(gradient_predivide_factor)
        self._num_groups = int(num_groups)
        self._process_set = process_set
        optimizer.rescale_grad *= gradient_predivide_factor

    # -- the hook -----------------------------------------------------------

    def _do_allreduce(self, index, grad):
        if isinstance(index, (tuple, list)):
            # num_groups splits a multi-index update into that many
            # atomic grouped allreduces (reference: num_groups batching)
            groups = _split_groups(list(zip(index, grad)), self._num_groups)
            for gi, bucket in enumerate(groups):
                mpi_ops.grouped_allreduce_(
                    [g for _, g in bucket], average=True,
                    name=f"allreduce.group.{bucket[0][0]}",
                    prescale_factor=1.0 / self._predivide,
                    process_set=self._process_set,
                )
        else:
            mpi_ops.allreduce_(
                grad, average=True, name=f"allreduce.{index}",
                prescale_factor=1.0 / self._predivide,
                process_set=self._process_set,
            )

    def update(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)

    def create_state(self, index, weight):
        return self._optimizer.create_state(index, weight)

    def create_state_multi_precision(self, index, weight):
        return self._optimizer.create_state_multi_precision(index, weight)

    # everything else (learning_rate, wd, schedulers…) delegates.
    # __dict__ lookup, not self._optimizer: __getattr__ fires for any
    # missing attribute, and a plain read here would recurse when
    # _optimizer itself is absent (e.g. during unpickling)
    def __getattr__(self, item):
        try:
            return getattr(self.__dict__["_optimizer"], item)
        except KeyError:
            raise AttributeError(item)


class DistributedTrainer(mx.gluon.Trainer):
    """Gluon trainer whose kvstore sync point is a cross-rank allreduce
    (reference: horovod/mxnet/__init__.py DistributedTrainer).

    The reference folds the world size into the trainer's ``_scale`` and
    SUM-allreduces at the ``_allreduce_grads`` hook; here the hook is an
    AVERAGE allreduce (the engine supplies the correct 1/N for any
    chips-per-process topology) and ``_scale`` only absorbs
    ``gradient_predivide_factor``.
    """

    def __init__(self, params, optimizer, optimizer_params=None,
                 gradient_predivide_factor: float = 1.0,
                 num_groups: int = 0,
                 prefix: Optional[str] = None,
                 process_set: Optional[ProcessSet] = None):
        if isinstance(optimizer, DistributedOptimizer):
            raise ValueError(
                "pass the bare optimizer to DistributedTrainer; it applies "
                "the distributed hook itself (reference raises here too)"
            )
        super().__init__(params, optimizer, optimizer_params, kvstore=None)
        self._scale *= gradient_predivide_factor
        self._hvd_predivide = float(gradient_predivide_factor)
        self._hvd_num_groups = int(num_groups)
        self._hvd_process_set = process_set
        self._hvd_prefix = prefix or ""

    def _allreduce_grads(self):
        live = [(i, j, g) for i, p in enumerate(self._params)
                if p.grad_req != "null"
                for j, g in enumerate(p.list_grad())]
        if not live:
            return
        if self._hvd_num_groups > 0:
            for bucket in _split_groups(live, self._hvd_num_groups):
                mpi_ops.grouped_allreduce_(
                    [g for _, _, g in bucket], average=True,
                    name=(f"{self._hvd_prefix}allreduce.group."
                          f"{bucket[0][0]}.{bucket[0][1]}"),
                    prescale_factor=1.0 / self._hvd_predivide,
                    process_set=self._hvd_process_set,
                )
        else:
            for i, j, grad in live:
                mpi_ops.allreduce_(
                    grad, average=True,
                    name=f"{self._hvd_prefix}allreduce.{i}.{j}",
                    prescale_factor=1.0 / self._hvd_predivide,
                    priority=-i,
                    process_set=self._hvd_process_set,
                )
