"""SyncBatchNorm for the JAX/flax path.

Reference parity: horovod/torch/sync_batch_norm.py (SURVEY.md §2.3) —
batch statistics reduced across all workers each training step.  On TPU
the idiomatic form is flax's ``BatchNorm(axis_name=...)`` inside a
``shard_map``/``pjit`` program: the mean/variance ``pmean`` lowers to an
ICI allreduce fused into the step.  This module packages that as a
drop-in module plus a converter mirroring
``torch.nn.SyncBatchNorm.convert_sync_batchnorm``.

(The torch adapter's eager-autograd version lives in
``horovod_tpu.torch.sync_batch_norm``.)
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn

from .common.topology import WORLD_AXIS


class SyncBatchNorm(nn.BatchNorm):
    """``nn.BatchNorm`` whose statistics sync over the world axis by
    default (reference: hvd.SyncBatchNorm).  Use inside a shard_map'ped
    training step where ``axis_name`` is bound."""

    axis_name: Optional[str] = WORLD_AXIS


def cross_replica(bn_cls=nn.BatchNorm, axis: str = WORLD_AXIS):
    """Partial-application helper: ``cross_replica()`` is BatchNorm with
    the world axis bound — handy for model definitions that take a norm
    constructor."""
    import functools

    return functools.partial(bn_cls, axis_name=axis)
