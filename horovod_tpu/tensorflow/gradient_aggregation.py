"""Graph-mode local gradient aggregation for TF training loops.

Reference parity: horovod/tensorflow/gradient_aggregation.py
(LocalGradientAggregationHelper) — accumulate gradients locally for
``backward_passes_per_step`` passes and allreduce once, halving (or
better) the communication frequency.  State lives in ``tf.Variable``s so
the whole schedule traces into a ``tf.function`` (the Keras-3 optimizer
wrapper's eager aggregation cannot); the apply itself is gated by
``tf.cond`` exactly like the reference.

Usage in a custom loop::

    agg = LocalGradientAggregationHelper(
        backward_passes_per_step=4,
        allreduce_func=lambda gs: [hvd.allreduce(g, op=hvd.Average)
                                   for g in gs],
    )

    @tf.function
    def train_step(x, y):
        with tf.GradientTape() as tape:
            loss = loss_fn(model(x), y)
        grads = tape.gradient(loss, model.trainable_variables)
        grads = agg.compute_gradients(grads)       # zeros on skip passes
        agg.apply_gradients(
            lambda: opt.apply_gradients(
                zip(grads, model.trainable_variables)
            )
        )
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import tensorflow as tf


class LocalGradientAggregationHelper:
    """Reference: LocalGradientAggregationHelper (SURVEY.md §2.3)."""

    def __init__(
        self,
        backward_passes_per_step: int,
        allreduce_func: Callable[[List[tf.Tensor]], List[tf.Tensor]],
        average_aggregated_gradients: bool = True,
    ):
        if backward_passes_per_step <= 0:
            raise ValueError("backward_passes_per_step must be > 0")
        self.backward_passes_per_step = backward_passes_per_step
        self.average_aggregated_gradients = average_aggregated_gradients
        self._allreduce = allreduce_func
        self._counter: Optional[tf.Variable] = None
        self._buffers: List[tf.Variable] = []

    def _build(self, grads: Sequence[tf.Tensor]) -> None:
        self._counter = tf.Variable(0, dtype=tf.int32, trainable=False,
                                    name="hvd_agg_counter")
        self._buffers = [
            tf.Variable(tf.zeros_like(g), trainable=False,
                        name=f"hvd_agg_buf_{i}")
            for i, g in enumerate(grads)
        ]

    def compute_gradients(self, grads: Sequence[tf.Tensor]):
        """Accumulate; on the Nth pass return the allreduced aggregate
        (and reset), otherwise return zeros (the paired
        ``apply_gradients`` no-ops on those passes)."""
        grads = list(grads)
        if any(g is None for g in grads):
            raise ValueError(
                "LocalGradientAggregationHelper requires materialized "
                "gradients (got None); filter variables without gradients"
            )
        if self._counter is None:
            self._build(grads)
        for buf, g in zip(self._buffers, grads):
            buf.assign_add(g)
        self._counter.assign_add(1)
        n = self.backward_passes_per_step

        def flush():
            aggregated = [tf.identity(b) for b in self._buffers]
            if self.average_aggregated_gradients:
                aggregated = [a / n for a in aggregated]
            reduced = self._allreduce(aggregated)
            for b in self._buffers:
                b.assign(tf.zeros_like(b))
            self._counter.assign(0)
            return list(reduced)

        def skip():
            return [tf.zeros_like(b) for b in self._buffers]

        return tf.cond(tf.equal(self._counter, n), flush, skip)

    def apply_gradients(self, apply_closure: Callable[[], None]) -> None:
        """Run ``apply_closure`` only on flush passes (reference:
        the helper's tf.cond-wrapped apply)."""
        if self._counter is None:
            raise RuntimeError("call compute_gradients first")

        def do():
            apply_closure()
            return tf.constant(0)

        # both branches must return the same structure under tf.cond
        tf.cond(tf.equal(self._counter, 0),  # flush just reset it
                do, lambda: tf.constant(0))
