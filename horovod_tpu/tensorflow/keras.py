"""Import-path parity for ``horovod.tensorflow.keras``.

The reference exposes the Keras surface both as ``horovod.keras`` and
``horovod.tensorflow.keras`` (the tf.keras flavor).  Keras 3 unified the
two, so this module simply re-exports ``horovod_tpu.keras``::

    import horovod_tpu.tensorflow.keras as hvd
    hvd.init()
    opt = hvd.DistributedOptimizer(opt)
"""

from ..keras import *  # noqa: F401,F403
from ..keras import callbacks, elastic  # noqa: F401
from ..keras import (  # noqa: F401
    init, shutdown, is_initialized, rank, local_rank, size, local_size,
    cross_rank, cross_size, allreduce, allgather, broadcast, alltoall,
    grouped_allreduce, reducescatter, barrier, join, broadcast_variables,
    broadcast_object, broadcast_object_fn, allgather_object,
    broadcast_model_weights, DistributedOptimizer, Compression,
    ProcessSet, global_process_set, Adasum, Average, Max, Min, Product,
    ReduceOp, Sum,
)
