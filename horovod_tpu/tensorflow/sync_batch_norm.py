"""Cross-worker synchronized batch normalization for Keras 3.

Reference parity: horovod/tensorflow/sync_batch_norm.py
(SyncBatchNormalization overriding _calculate_mean_and_var to allreduce
the batch statistics) — SURVEY.md §2.3.  Keras 3 funnels the statistics
through ``BatchNormalization._moments``, so that single override point
serves every backend.

Global moments from per-worker sums (the reference's formulation, robust
to ragged per-rank batch sizes): allreduce [Σx, Σx², n] per channel, then
mean = Σx/n and var = Σx²/n − mean².
"""

from __future__ import annotations

import functools

import jax
import numpy as np
import keras
from keras import ops

from ..common import basics
from ..ops import collective_ops as _ops
from ..ops.reduce_ops import Sum
from .optimizer import _grad_kind


def _allreduce_sum(x, name, process_set):
    """Backend-dispatching, DIFFERENTIABLE Sum allreduce of one tensor.

    The batch statistics feed the normalization output, so autodiff must
    flow through this op.  The numpy bridge (py_function / pure_callback)
    records nothing on either framework's tape, so the gradient is
    attached explicitly: d(sum-allreduce)/dx = sum-allreduce of the
    cotangent — the same gradient the reference registers for its
    HorovodAllreduceOp (every rank backprops its local loss; summing the
    cotangents yields the global-loss gradient)."""
    kind = _grad_kind(x)
    if type(x).__module__.startswith("torch"):
        # no registered gradient on the numpy fallback: np.asarray on a
        # grad-requiring torch tensor raises, and a detached constant
        # would silently zero d(loss)/d(stats)
        raise NotImplementedError(
            "SyncBatchNormalization supports the tensorflow and jax Keras "
            "backends; the torch backend's stats allreduce has no "
            "gradient path (use horovod_tpu.torch.SyncBatchNorm for "
            "torch models)"
        )
    if kind == "tf":
        import tensorflow as tf

        from . import mpi_ops

        @tf.custom_gradient
        def ar(t):
            out = mpi_ops.allreduce(t, op=Sum, name=name,
                                    process_set=process_set)

            def grad(dy):
                return mpi_ops.allreduce(dy, op=Sum, name=f"{name}.grad",
                                         process_set=process_set)

            return out, grad

        return ar(x)
    if kind == "jax":
        return _jax_allreduce_sum(x, name=name, process_set=process_set)
    return ops.convert_to_tensor(np.asarray(_ops.allreduce(
        np.asarray(x), op=Sum, name=name, process_set=process_set,
    )))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _jax_allreduce_sum(x, name, process_set):
    return _jax_ar_callback(x, name, process_set)


def _jax_ar_callback(x, name, process_set):
    import jax as _jax

    if isinstance(x, _jax.core.Tracer):
        return _jax.pure_callback(
            lambda a: np.asarray(_ops.allreduce(
                np.asarray(a), op=Sum, name=name, process_set=process_set,
            )),
            _jax.ShapeDtypeStruct(x.shape, x.dtype), x,
        )
    return _ops.allreduce(x, op=Sum, name=name, process_set=process_set)


def _jax_ar_fwd(x, name, process_set):
    return _jax_ar_callback(x, name, process_set), None


def _jax_ar_bwd(name, process_set, _res, g):
    return (_jax_ar_callback(g, f"{name}.grad", process_set),)


_jax_allreduce_sum.defvjp(_jax_ar_fwd, _jax_ar_bwd)


class SyncBatchNormalization(keras.layers.BatchNormalization):
    """Drop-in BatchNormalization whose batch statistics are computed over
    ALL workers (reference: hvd.SyncBatchNormalization) — needed when the
    per-worker batch is too small for stable statistics."""

    def __init__(self, *args, process_set=None, **kwargs):
        kwargs.pop("synchronized", None)  # we ARE the synchronized variant
        super().__init__(*args, **kwargs)
        self._hvd_process_set = process_set

    def _moments(self, inputs, mask):
        multi = basics.is_initialized() and \
            basics._require_init().engine.multi_process
        if mask is not None and multi:
            # local moments here would silently desynchronize the ranks —
            # the exact defect this layer exists to prevent
            raise NotImplementedError(
                "SyncBatchNormalization does not support masked inputs in "
                "a multi-process run (the masked weighted sums are not "
                "allreduced)"
            )
        if mask is not None or not multi:
            return super()._moments(inputs, mask)

        x = ops.cast(inputs, "float32")
        reduction_axes = [a for a in range(len(x.shape))
                          if a != self.axis % len(x.shape)]
        local_sum = ops.sum(x, axis=reduction_axes)          # (C,)
        local_sqsum = ops.sum(x * x, axis=reduction_axes)    # (C,)
        n_channels = x.shape[self.axis]
        local_count = ops.cast(ops.size(x), "float32") / float(n_channels)
        packed = ops.concatenate(
            [local_sum, local_sqsum, ops.reshape(local_count, (1,))]
        )
        # one deterministic name per layer: every rank's training step
        # runs the same layers in the same order
        packed = _allreduce_sum(
            packed, f"sync_bn.{self.name}", self._hvd_process_set
        )
        packed = ops.cast(packed, "float32")
        total_sum = packed[:n_channels]
        total_sqsum = packed[n_channels:2 * n_channels]
        total_count = packed[2 * n_channels]
        mean = total_sum / total_count
        variance = total_sqsum / total_count - mean * mean
        return mean, variance
