"""horovod_tpu.tensorflow: the TensorFlow 2 framework adapter.

Reference parity: the ``horovod.tensorflow`` surface
(horovod/tensorflow/__init__.py, mpi_ops.py + the mpi_ops.cc /
xla_mpi_ops.cc custom-op bindings, functions.py, compression.py,
elastic.py — SURVEY.md §2.3).  A reference training script needs only its
import changed::

    import horovod_tpu.tensorflow as hvd
    hvd.init()
    tape = hvd.DistributedGradientTape(tape)
    hvd.broadcast_variables(model.variables, root_rank=0)
    hvd.broadcast_variables(opt.variables, root_rank=0)

Design: TF stays the model/autograd frontend; collectives execute through
the shared negotiated eager engine (CPU tensors bridge via numpy; traced
``tf.function`` graphs reach it through ``tf.py_function``).  The
reference's ``xla_mpi_ops.cc`` solved "collectives inside a compiled
graph" with XLA custom calls — here the whole data plane already *is*
XLA; compiled TPU training is the JAX surface (``horovod_tpu.training``),
and this adapter exists for reference-script parity and CPU-hosted TF.
"""

from __future__ import annotations

# lifecycle + topology (shared with the JAX surface)
from ..common.basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, local_rank, size, local_size,
    cross_rank, cross_size, is_homogeneous, xla_built, nccl_built,
    mpi_enabled, mpi_built, mpi_threads_supported, gloo_built,
    gloo_enabled, ccl_built, cuda_built, rocm_built, ddl_built,
    native_built, start_timeline, stop_timeline,
)
from ..common.exceptions import (  # noqa: F401
    HorovodInternalError, HostsUpdatedInterrupt,
)
from ..common.process_sets import ProcessSet, global_process_set  # noqa: F401
from .. import add_process_set, remove_process_set  # noqa: F401
from ..ops.reduce_ops import (  # noqa: F401
    Adasum, Average, Max, Min, Product, ReduceOp, Sum,
)
from .compression import Compression  # noqa: F401
from .functions import (  # noqa: F401
    allgather_object, broadcast_object, broadcast_object_fn,
    broadcast_model_weights, broadcast_variables,
)
from .mpi_ops import (  # noqa: F401
    allgather, allreduce, alltoall, barrier, broadcast, grouped_allgather,
    grouped_allreduce, grouped_reducescatter, join, reducescatter,
)
from .gradient_aggregation import LocalGradientAggregationHelper  # noqa: F401
from .optimizer import (  # noqa: F401
    DistributedGradientTape, DistributedOptimizer,
)
from .sync_batch_norm import SyncBatchNormalization  # noqa: F401
from . import elastic  # noqa: F401


def broadcast_global_variables(root_rank: int = 0) -> None:
    """Reference: horovod/tensorflow broadcast_global_variables — a TF1
    global-collection API.  TF2 has no global variable collection (the
    reference itself raises in eager mode pointing at
    broadcast_variables); same contract here."""
    raise RuntimeError(
        "hvd.broadcast_global_variables() requires the TF1 global "
        "variable collection, which does not exist under TF2 eager "
        "semantics.  Use hvd.broadcast_variables(model.variables, "
        f"root_rank={root_rank}) or broadcast_model_weights(model) "
        "instead (the reference raises the same way in eager mode)."
    )


def __getattr__(name):
    # lazy: importing horovod_tpu.tensorflow must not pull keras in.
    # importlib directly — `from . import keras` would probe this very
    # __getattr__ before importing (infinite recursion).
    if name == "keras":
        import importlib

        return importlib.import_module(__name__ + ".keras")
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )
