"""DistributedGradientTape / DistributedOptimizer for TensorFlow + Keras 3.

Reference parity: horovod/tensorflow/__init__.py (DistributedGradientTape,
_make_allreduce_grads_fn) and horovod/_keras/__init__.py
(create_distributed_optimizer) — SURVEY.md §2.3.  The TF2 training idioms
both reference paths serve:

  tape = hvd.DistributedGradientTape(tape)          # custom loops
  opt  = hvd.DistributedOptimizer(keras_optimizer)  # model.fit / Keras 3

Keras 3 note: the reference predates Keras 3; its keras wrapper overrode
``get_gradients``/``apply_gradients`` of the TF-internal optimizer.  Keras
3 funnels every backend's update through ``Optimizer.apply``, so the
dynamic subclass here overrides that single point — the same
subclass-the-instance trick the reference uses (upstream
create_distributed_optimizer builds ``cls = type(opt.__class__.__name__,
(opt.__class__,), ...)``).  With KERAS_BACKEND=jax the update runs inside
``jax.jit``, where the negotiated eager engine is reached through
``jax.pure_callback`` (experimental; the TPU-native training path remains
``horovod_tpu.training``/optax).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..common import basics
from ..common.process_sets import ProcessSet
from ..ops import collective_ops as _ops
from ..ops.reduce_ops import Average, ReduceOp, Sum
from .compression import Compression


def _scale_factors(op: Optional[ReduceOp], gradient_predivide_factor: float,
                   process_set: Optional[ProcessSet]):
    """Map (op, predivide) onto engine (op, prescale, postscale) the way
    the reference's _make_allreduce_grads_fn does: dividing by the factor
    before the sum and by size/factor after is numerically safer than one
    post-division for fp16 gradients.

    The divisor is the number of summed *contributions* — one per member
    process (the eager engine reduces per-process host tensors), NOT
    ``hvd.size()``, which counts chips and over-divides whenever a process
    drives more than one chip."""
    if gradient_predivide_factor == 1.0:
        return op or Average, 1.0, 1.0
    engine = basics._require_init().engine
    n = engine._ctx(process_set).n if process_set is not None \
        else engine.num_contributors
    return Sum, 1.0 / gradient_predivide_factor, \
        gradient_predivide_factor / n


def _allreduce_np_grads(grads, compression, op, prescale, postscale,
                        process_set, name_prefix):
    """Allreduce a list of numpy gradients (None entries pass through)."""
    outs = []
    for i, g in enumerate(grads):
        if g is None:
            outs.append(None)
            continue
        arr = np.asarray(g)
        # fp16-on-the-wire compression happens in numpy here (the torch/tf
        # Compressors operate on framework tensors; this path is shared)
        ctx = None
        if compression is Compression.fp16 and arr.dtype in (
                np.float32, np.float64):
            ctx = arr.dtype
            arr = arr.astype(np.float16)
        out = np.asarray(_ops.allreduce(
            arr, op=op, prescale_factor=prescale,
            postscale_factor=postscale, process_set=process_set,
            name=f"{name_prefix}.{i}",
        ))
        outs.append(out.astype(ctx) if ctx is not None else out)
    return outs


class _DistributedGradientTape:
    """Wraps tf.GradientTape; ``gradient()`` returns allreduced grads
    (reference: horovod/tensorflow/__init__.py _DistributedGradientTape)."""

    def __init__(self, tape, compression, op, gradient_predivide_factor,
                 process_set, num_groups):
        self._tape = tape
        self._compression = compression
        self._op = op
        self._predivide = gradient_predivide_factor
        self._process_set = process_set
        self._num_groups = num_groups

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def __getattr__(self, name):
        return getattr(self._tape, name)

    def gradient(self, target, sources, output_gradients=None):
        from . import mpi_ops

        grads = self._tape.gradient(target, sources, output_gradients)
        op, prescale, postscale = _scale_factors(
            self._op, self._predivide, self._process_set
        )
        flat = list(grads) if isinstance(grads, (list, tuple)) else [grads]
        live = [(i, g) for i, g in enumerate(flat) if g is not None]
        if self._num_groups > 0 and len(live) > 1:
            # split into num_groups chunks, each an atomic grouped op
            # (reference: num_groups arg of DistributedGradientTape)
            n = min(self._num_groups, len(live))
            out_live = []
            for c in range(n):
                chunk = live[c::n]
                tensors = [self._compression.compress(g) for _, g in chunk]
                reduced = mpi_ops.grouped_allreduce(
                    [t for t, _ in tensors], op=op, prescale_factor=prescale,
                    postscale_factor=postscale, process_set=self._process_set,
                    name=f"DistributedGradientTape.group{c}",
                )
                out_live.extend(
                    (i, self._compression.decompress(r, ctx))
                    for (i, _), r, (_, ctx) in zip(chunk, reduced, tensors)
                )
            for i, g in out_live:
                flat[i] = g
        else:
            for i, g in live:
                t, ctx = self._compression.compress(g)
                t = mpi_ops.allreduce(
                    t, op=op, prescale_factor=prescale,
                    postscale_factor=postscale, process_set=self._process_set,
                    name=f"DistributedGradientTape.{i}",
                )
                flat[i] = self._compression.decompress(t, ctx)
        if isinstance(grads, (list, tuple)):
            return type(grads)(flat)
        return flat[0]


def DistributedGradientTape(gradtape, device_dense: str = "",
                            device_sparse: str = "",
                            compression=Compression.none,
                            op: Optional[ReduceOp] = None,
                            gradient_predivide_factor: float = 1.0,
                            num_groups: int = 0,
                            process_set: Optional[ProcessSet] = None):
    """Reference: hvd.DistributedGradientTape.  ``device_dense``/
    ``device_sparse`` are accepted for signature parity; placement is the
    engine's concern here (the reference used them to pin GPU copies).
    Sparse gradients (tf.IndexedSlices, e.g. from embedding lookups)
    densify on the wire — the reference's ``sparse_as_dense=True``
    behavior, which is the right default on TPU (tested:
    test_distributed_gradient_tape_indexed_slices)."""
    return _DistributedGradientTape(
        gradtape, compression, op, gradient_predivide_factor, process_set,
        num_groups,
    )


def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         device_dense: str = "", device_sparse: str = "",
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op: Optional[ReduceOp] = None,
                         gradient_predivide_factor: float = 1.0,
                         average_aggregated_gradients: bool = True,
                         process_set: Optional[ProcessSet] = None):
    """Wrap a Keras 3 optimizer so ``apply`` allreduces gradients first
    (reference: horovod/_keras/__init__.py create_distributed_optimizer).

    Works with any Keras 3 backend: TF tensors bridge through
    ``tensorflow.mpi_ops`` (eager or tf.function); JAX tracers reach the
    engine via ``jax.pure_callback``; anything numpy-convertible takes the
    direct path.  ``backward_passes_per_step > 1`` aggregates locally for
    N applies and allreduces once (eager-mode python state; matches the
    reference's LocalGradientAggregationHelper semantics)."""
    # Re-wrap guard (ADVICE round 3): wrapping twice would make
    # ``super(self.__class__, self)`` resolve to the same frame in both
    # dynamic subclasses — infinite recursion instead of a clear error.
    # Matches the reference, which raises ValueError on an already-wrapped
    # optimizer (easy to hit re-running user setup after an exec-restart).
    if optimizer.__class__.__dict__.get("apply") is _distributed_apply:
        raise ValueError(
            "optimizer is already a horovod_tpu DistributedOptimizer; "
            "wrapping it twice is not supported"
        )
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,), {
        "apply": _distributed_apply,
    })
    optimizer.__class__ = cls
    optimizer._hvd_compression = compression
    optimizer._hvd_op = op
    optimizer._hvd_predivide = gradient_predivide_factor
    optimizer._hvd_process_set = process_set
    optimizer._hvd_passes_per_step = int(backward_passes_per_step)
    optimizer._hvd_average_aggregated = average_aggregated_gradients
    optimizer._hvd_agg = None
    optimizer._hvd_agg_count = 0
    return optimizer


def _grad_kind(g):
    mod = type(g).__module__
    if mod.startswith("tensorflow"):
        return "tf"
    if mod.startswith("torch"):
        return "torch"
    try:
        import jax

        if isinstance(g, (jax.Array, jax.core.Tracer)):
            return "jax"
    except ImportError:
        pass
    return "np"


def _distributed_apply(self, grads, trainable_variables=None):
    op, prescale, postscale = _scale_factors(
        self._hvd_op, self._hvd_predivide, self._hvd_process_set
    )
    # classify on the INCOMING grads: local aggregation converts to numpy
    # below, and the framework bridge (e.g. torch apply rejecting numpy)
    # must still engage on the flush pass
    kinds = {_grad_kind(g) for g in grads if g is not None}
    n = self._hvd_passes_per_step
    if n > 1:
        def _is_traced(g):
            if g is None:
                return False
            if _grad_kind(g) == "tf":
                return not hasattr(g, "numpy")  # symbolic tf.function value
            import jax

            return isinstance(g, jax.core.Tracer)

        if any(_is_traced(g) for g in grads):
            raise RuntimeError(
                "backward_passes_per_step > 1 aggregates in eager python "
                "state; compile-free execution is required (e.g. "
                "model.compile(..., run_eagerly=True))"
            )
        grads = [
            None if g is None
            else (g.detach().cpu().numpy()
                  if _grad_kind(g) == "torch" else np.asarray(g))
            for g in grads
        ]
        if self._hvd_agg is None:
            self._hvd_agg = [None if g is None else g.copy() for g in grads]
        else:
            for a, g in zip(self._hvd_agg, grads):
                if a is not None and g is not None:
                    a += g
        self._hvd_agg_count += 1
        if self._hvd_agg_count < n:
            return  # aggregate only; no variable update this pass
        grads = self._hvd_agg
        if self._hvd_average_aggregated:
            grads = [None if g is None else g / n for g in grads]
        self._hvd_agg = None
        self._hvd_agg_count = 0
        if kinds == {"tf"}:
            # eager-only path (guard above); the aggregated numpy arrays
            # route through the numpy engine and return fine to TF
            kinds = {"np"}

    if kinds == {"tf"}:
        from . import mpi_ops

        reduced = []
        for i, g in enumerate(grads):
            if g is None:
                reduced.append(None)
                continue
            t, ctx = self._hvd_compression.compress(g)
            t = mpi_ops.allreduce(
                t, op=op, prescale_factor=prescale,
                postscale_factor=postscale,
                process_set=self._hvd_process_set,
                name=f"DistributedOptimizer.{i}",
            )
            reduced.append(self._hvd_compression.decompress(t, ctx))
    elif kinds == {"jax"}:
        reduced = _allreduce_jax_grads(
            grads, self._hvd_compression, op, prescale, postscale,
            self._hvd_process_set,
        )
    elif kinds == {"torch"}:
        # Keras torch backend: bridge through numpy (grads arrive
        # detached from keras's backward) and hand torch tensors back —
        # keras's torch apply rejects numpy
        import torch

        np_grads = _allreduce_np_grads(
            [None if g is None
             else (g.detach().cpu().numpy() if hasattr(g, "detach")
                   else np.asarray(g))  # already numpy after aggregation
             for g in grads],
            self._hvd_compression, op, prescale, postscale,
            self._hvd_process_set, "DistributedOptimizer",
        )
        # copy: the engine may hand back a read-only buffer view, which
        # torch.as_tensor would wrap with a non-writable warning
        reduced = [None if g is None else torch.as_tensor(np.array(g))
                   for g in np_grads]
    else:
        reduced = _allreduce_np_grads(
            grads, self._hvd_compression, op, prescale, postscale,
            self._hvd_process_set, "DistributedOptimizer",
        )
    return super(self.__class__, self).apply(reduced, trainable_variables)


def _allreduce_jax_grads(grads, compression, op, prescale, postscale,
                         process_set):
    """JAX-backend Keras: the update runs under jit, so reach the eager
    negotiated engine through a host callback.  Concrete (eager) arrays
    take the direct path.  Compression happens numpy-side inside the
    callback (fp16 on the wire, original dtype back out), so the traced
    result shape/dtype is unchanged."""
    import jax
    import jax.numpy as jnp
    from jax.core import Tracer

    def host(i, a):
        arr = np.asarray(a)
        ctx = None
        if compression is Compression.fp16 and arr.dtype in (
                np.float32, np.float64):
            ctx = arr.dtype
            arr = arr.astype(np.float16)
        out = np.asarray(_ops.allreduce(
            arr, op=op, prescale_factor=prescale,
            postscale_factor=postscale, process_set=process_set,
            name=f"DistributedOptimizer.{i}",
        ))
        return out.astype(ctx) if ctx is not None else out

    reduced = []
    for i, g in enumerate(grads):
        if g is None:
            reduced.append(None)
        elif isinstance(g, Tracer):
            reduced.append(jax.pure_callback(
                lambda a, i=i: host(i, a),
                jax.ShapeDtypeStruct(g.shape, g.dtype), g,
            ))
        else:
            reduced.append(jnp.asarray(host(i, g)))
    return reduced
