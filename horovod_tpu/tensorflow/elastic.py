"""Elastic state for TensorFlow / Keras models.

Reference parity: horovod/tensorflow/elastic.py (TensorFlowKerasState,
TensorFlowState) — capture model + optimizer variables at ``commit()``,
roll back on peer failure, rank-0-broadcast on ``sync()``.  Works with
any Keras 3 backend because capture goes through ``get_weights()`` /
``Variable.assign`` numpy values.
"""

from __future__ import annotations

import copy
from typing import Any

import numpy as np

from ..elastic import ObjectState, run  # noqa: F401 (re-export)
from ..elastic.sampler import ElasticSampler  # noqa: F401 (re-export)


def _is_keras_model(v: Any) -> bool:
    return hasattr(v, "get_weights") and hasattr(v, "set_weights")


def _is_optimizer(v: Any) -> bool:
    return hasattr(v, "variables") and hasattr(v, "apply_gradients")


class TensorFlowKerasState(ObjectState):
    """Elastic state holding a Keras model and/or optimizer (reference:
    TensorFlowKerasState(model=..., optimizer=..., epoch=0, batch=0)).

    The base ObjectState snapshots plain fields; model/optimizer fields
    are recognized structurally and captured as numpy weight lists."""

    def _snapshot(self):
        snap = {}
        for k, v in self._attrs.items():
            if _is_keras_model(v):
                snap[k] = ("__keras_model__",
                           [np.array(w) for w in v.get_weights()])
            elif _is_optimizer(v):
                snap[k] = ("__keras_optimizer__",
                           [np.array(var) for var in v.variables])
            elif hasattr(v, "state_dict") and hasattr(v, "load_state_dict"):
                snap[k] = ("__state_dict__", copy.deepcopy(v.state_dict()))
            else:
                snap[k] = ("__value__", copy.deepcopy(v))
        return snap

    def _apply_snapshot(self, snap) -> None:
        for k, (kind, payload) in snap.items():
            if k not in self._attrs:
                self._attrs[k] = payload if kind == "__value__" else None
                continue
            v = self._attrs[k]
            if kind == "__keras_model__":
                v.set_weights([np.array(w) for w in payload])
            elif kind == "__keras_optimizer__":
                # an unbuilt optimizer has no variables yet; only restore
                # when the shapes line up (same contract as the reference,
                # which pre-builds the optimizer before restoring)
                if len(v.variables) == len(payload):
                    for var, w in zip(v.variables, payload):
                        var.assign(np.array(w))
            elif kind == "__state_dict__":
                v.load_state_dict(copy.deepcopy(payload))
            else:
                self._attrs[k] = copy.deepcopy(payload)


# Alias matching the reference's plain-TF variant: the structural capture
# above covers ``tf.Module``-style objects exposing get_weights or
# variables just the same.
TensorFlowState = TensorFlowKerasState


__all__ = ["TensorFlowKerasState", "TensorFlowState", "ObjectState",
           "ElasticSampler", "run"]
