"""State broadcast helpers for TensorFlow/Keras models.

Reference parity: horovod/tensorflow/functions.py — broadcast_variables,
broadcast_object, broadcast_object_fn, allgather_object (SURVEY.md §2.3),
used at train start so every worker leaves rank 0's initialization
identically.
"""

from __future__ import annotations

from typing import Any, Iterable

import numpy as np
import tensorflow as tf

from .. import functions as _jax_functions
from . import mpi_ops


def broadcast_variables(variables: Iterable[tf.Variable],
                        root_rank: int = 0, process_set=None) -> None:
    """Assign every variable rank ``root_rank``'s value (reference:
    horovod/tensorflow/functions.py broadcast_variables).  Works on any
    iterable of ``tf.Variable``/Keras variables."""
    for i, v in enumerate(variables):
        name = getattr(v, "name", None) or f"broadcast_var.{i}"
        value = mpi_ops.broadcast(
            tf.convert_to_tensor(v), root_rank,
            name=f"broadcast.{name}", process_set=process_set,
        )
        v.assign(value)


def broadcast_object(obj: Any, root_rank: int = 0, name: str = None,
                     process_set=None) -> Any:
    """Reference: horovod/tensorflow/functions.py broadcast_object (pickle
    + size/payload broadcast); delegates to the shared implementation."""
    return _jax_functions.broadcast_object(obj, root_rank=root_rank,
                                           process_set=process_set)


def broadcast_object_fn(root_rank: int = 0, name: str = None,
                        process_set=None):
    """Reference: broadcast_object_fn — returns a callable so the object
    need only exist on the root."""
    return lambda obj=None: broadcast_object(
        obj, root_rank=root_rank, name=name, process_set=process_set
    )


def allgather_object(obj: Any, name: str = None, process_set=None) -> list:
    """Gather one picklable object per rank into a list ordered by rank
    (reference: horovod/tensorflow/functions.py allgather_object)."""
    return _jax_functions.allgather_object(obj, process_set=process_set)


def broadcast_model_weights(model, root_rank: int = 0,
                            process_set=None) -> None:
    """Broadcast a Keras model's weights (multi-backend: goes through
    ``get_weights()`` numpy, so it also serves KERAS_BACKEND=jax)."""
    from ..ops import collective_ops as _ops

    synced = [
        np.asarray(_ops.broadcast(
            w, root_rank, name=f"broadcast_model_weight.{i}",
            process_set=process_set,
        ))
        for i, w in enumerate(model.get_weights())
    ]
    model.set_weights(synced)
