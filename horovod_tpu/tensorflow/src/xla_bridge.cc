// XLA CPU custom-call bridge for the TensorFlow adapter.
//
// Reference parity: horovod/tensorflow/xla_mpi_ops.cc (SURVEY.md §2.3,
// §5.8) — the reference registers HVD collectives as XLA custom calls so
// they can live inside tf.function(jit_compile=True).  The TPU-native
// redesign keeps the C side to pure pointer plumbing: one custom-call
// target that forwards buffers to a Python callback, which runs the SAME
// negotiated eager engine every other adapter surface uses.  All shape,
// dtype, and op metadata travels in a meta operand built at trace time,
// so this file needs no TF op machinery — only XLA's target registry,
// whose live instance is shared with the interpreter through
// libtensorflow_cc.so.2 (verified: _pywrap_tensorflow_internal links it).
//
// Built lazily by horovod_tpu/tensorflow/xla_ops.py with the system g++
// against the pip-shipped TF headers; no Python headers needed (the
// callback crosses via a ctypes CFUNCTYPE pointer, which acquires the
// GIL on entry).

#include <cstdint>

#include "xla/service/custom_call_target_registry.h"

namespace {

// Python-side callback: (meta_json, meta_len, data_in_ptrs, out_ptrs).
typedef void (*HvdTfCallback)(const void* meta, uint32_t meta_len,
                              const void** ins, void** outs);

HvdTfCallback g_callback = nullptr;

}  // namespace

extern "C" void hvd_tpu_tf_set_callback(HvdTfCallback cb) { g_callback = cb; }

// Custom-call entry point.  Operand 0 is the meta buffer:
//   [u32 meta_len][u32 n_results][meta_len bytes of JSON]
// operands 1..N are tensor data.  XLA hands a direct buffer pointer for a
// single result and a tuple (void**) for several; n_results from the
// header disambiguates, so Python always sees a flat out-pointer array.
extern "C" void hvd_tpu_tf_collective(void* out, const void** ins) {
  const uint8_t* hdr = static_cast<const uint8_t*>(ins[0]);
  uint32_t meta_len, n_results;
  __builtin_memcpy(&meta_len, hdr, 4);
  __builtin_memcpy(&n_results, hdr + 4, 4);
  void* single[1];
  void** outs;
  if (n_results == 1) {
    single[0] = out;
    outs = single;
  } else {
    outs = static_cast<void**>(out);
  }
  g_callback(hdr + 8, meta_len, ins + 1, outs);
}

namespace {
bool registered = [] {
  xla::CustomCallTargetRegistry::Global()->Register(
      "hvd_tpu_tf_collective",
      reinterpret_cast<void*>(&hvd_tpu_tf_collective), "Host");
  return true;
}();
}  // namespace
