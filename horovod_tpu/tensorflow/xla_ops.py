"""XLA-compilable TF collectives (reference: tensorflow/xla_mpi_ops.cc).

The reference's XLA ops let ``hvd.allreduce`` live inside
``tf.function(jit_compile=True)``; the ``tf.py_function`` route cannot
(py_function has no XLA lowering).  This module provides the TPU-native
equivalent:

- a tiny C++ custom-call target (``src/xla_bridge.cc``) registered into
  the process-wide ``xla::CustomCallTargetRegistry`` that TF's own
  compiled programs consult (shared via libtensorflow_cc.so.2);
- ops emitted from Python as ``XlaCustomCallV2`` — registered in TF's op
  registry (its C++ wrapper ships in libtensorflow_cc) though absent from
  ``tf.raw_ops``, so it is applied through ``op_def_library``;
- a ctypes callback that dispatches each custom call back into the SAME
  negotiated eager engine every adapter surface uses, so a jit-compiled
  step's allreduce coordinates with eager peers rank-for-rank.

Shape-preserving collectives only (allreduce, grouped allreduce,
broadcast): XLA requires static result shapes, and allgather/alltoall
results are data-dependent — exactly the reference's scoping, whose XLA
op set is allreduce-only.

Engine errors inside a compiled program cannot raise through XLA; the
callback records them, returns identity data, and the error re-raises at
the next collective call (see ``maybe_reraise``).
"""

from __future__ import annotations

import ctypes
import json
import os
import struct
import sys
import threading
from typing import Optional

import numpy as np
import tensorflow as tf

from ..ops import collective_ops as _engine_ops
from ..ops.reduce_ops import ReduceOp
from ..utils.logging import get_logger

# Everything here runs at trace time from inside tf.function bodies, where
# AutoGraph rewrites called functions; an AutoGraph-converted ctypes
# callback raises inside the C callback ("Exception ignored while creating
# argument"), so the whole module opts out.
_no_autograph = tf.autograph.experimental.do_not_convert

_LIB_NAME = "libhvd_tf_xla.so"
_TARGET = "hvd_tpu_tf_collective"

_lock = threading.Lock()
_lib = None
_load_attempted = False
_last_error: Optional[BaseException] = None


# -- build + load ------------------------------------------------------------


@_no_autograph
def _build_and_load():
    """Compile (if stale) and dlopen the bridge; returns the CDLL or None.

    Mirrors native/_maybe_build: the system g++ against the pip TF
    headers, linking libtensorflow_cc.so.2 so the registry singleton is
    the live one.  Any failure degrades to unavailable (py_function path
    keeps working); the failure is logged once.
    """
    global _lib, _load_attempted
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        try:
            import shutil
            import subprocess

            here = os.path.dirname(os.path.abspath(__file__))
            src = os.path.join(here, "src", "xla_bridge.cc")
            out = os.path.join(here, _LIB_NAME)
            tf_dir = tf.sysconfig.get_lib()
            if not os.path.exists(
                    os.path.join(tf_dir, "libtensorflow_cc.so.2")):
                raise RuntimeError("libtensorflow_cc.so.2 not shipped")
            if (not os.path.exists(out)
                    or os.path.getmtime(out) < os.path.getmtime(src)):
                if shutil.which("g++") is None:
                    raise RuntimeError("no g++")
                # per-pid temp + atomic rename: concurrent workers (e.g.
                # tpurun -np N on a fresh checkout) all build; without
                # this one dlopens a half-written ELF
                tmp = f"{out}.{os.getpid()}.tmp"
                cmd = (["g++", "-O2", "-fPIC", "-shared"]
                       + tf.sysconfig.get_compile_flags()
                       + ["-o", tmp, src, f"-L{tf_dir}",
                          "-l:libtensorflow_cc.so.2",
                          f"-Wl,-rpath,{tf_dir}"])
                try:
                    subprocess.run(cmd, check=True, capture_output=True,
                                   timeout=300)
                    os.replace(tmp, out)
                finally:
                    if os.path.exists(tmp):
                        os.remove(tmp)
            _lib = ctypes.CDLL(out)  # static registrar fires at load
            _lib.hvd_tpu_tf_set_callback(_CB_REF)
        except Exception as e:
            _lib = None
            get_logger().warning(
                "TF XLA collective bridge unavailable (%s); "
                "jit_compile=True steps will not work — plain graph/eager "
                "paths are unaffected", e)
        return _lib


@_no_autograph
def available() -> bool:
    if os.environ.get("HOROVOD_ENABLE_XLA_OPS", "").lower() in ("0", "false"):
        return False
    return _build_and_load() is not None


@_no_autograph
def in_jit_trace(consider_env: bool = True) -> bool:
    """True when the current trace belongs to a jit_compile=True
    tf.function.  TF exposes no public trace-time signal, so walk the
    stack for the polymorphic Function driving the trace and read its
    jit_compile (innermost non-None wins, matching must-compile
    clustering).

    With ``consider_env`` (the lowering decision), HOROVOD_ENABLE_XLA_OPS
    =1/true forces the XLA lowering for every graph-mode collective (the
    reference's env contract — meaningful when the graph compiles, e.g.
    under TF auto-clustering).  Callers asking "is this REALLY a
    must-compile trace?" (e.g. the allgather rejection) pass
    consider_env=False so the force flag cannot break plain-graph ops
    that work fine through py_function."""
    if consider_env and os.environ.get(
            "HOROVOD_ENABLE_XLA_OPS", "").lower() in ("1", "true"):
        return True
    # raw frame walk, NOT inspect.stack(): this runs once per symbolic
    # collective during tracing (hundreds of times for a big tape), and
    # inspect.stack materializes source lines for every frame
    fr = sys._getframe(1)
    while fr is not None:
        slf = fr.f_locals.get("self")
        if slf is not None:
            jc = getattr(slf, "_jit_compile", None)
            if jc is None:
                ft = getattr(slf, "function_type", None)
                jc = getattr(ft, "jit_compile", None) if ft is not None \
                    else None
            if jc is not None:
                return bool(jc)
        fr = fr.f_back
    return False


def maybe_reraise() -> None:
    """Re-raise an engine error captured inside a compiled program (the
    custom call cannot raise through XLA — identity data was returned)."""
    global _last_error
    err, _last_error = _last_error, None
    if err is not None:
        raise err


# -- the callback ------------------------------------------------------------


def _np_dtype(name: str):
    if name in ("bfloat16",):
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


@_no_autograph
def _callback(meta_p, meta_len, ins, outs):
    global _last_error
    meta = json.loads(ctypes.string_at(meta_p, meta_len))
    specs = meta["tensors"]
    arrays = []
    for i, spec in enumerate(specs):
        dt = _np_dtype(spec["dtype"])
        shape = tuple(spec["shape"])
        nbytes = int(np.prod(shape, dtype=np.int64)) * dt.itemsize if shape \
            else dt.itemsize
        buf = ctypes.string_at(ins[i], nbytes)
        arrays.append(np.frombuffer(buf, dtype=dt).reshape(shape))
    try:
        results = _dispatch(meta, arrays)
    except BaseException as e:  # noqa: BLE001 — must not unwind into XLA
        get_logger().error(
            "collective failed inside a jit-compiled step: %s: %s "
            "(identity data returned; the error re-raises on the driving "
            "thread at the step boundary)", type(e).__name__, e)
        _last_error = e
        _async_raise_on_main(e)
        results = arrays
    for i, (res, spec) in enumerate(zip(results, specs)):
        dt = _np_dtype(spec["dtype"])
        res = np.ascontiguousarray(np.asarray(res, dtype=dt))
        if res.shape != tuple(spec["shape"]):
            # never overrun XLA's statically-sized output buffer: a
            # shape-deviating engine result becomes a recorded error +
            # identity data, not heap corruption deep in the TF runtime
            get_logger().error(
                "collective result shape %s != declared %s; identity "
                "data returned", res.shape, tuple(spec["shape"]))
            _last_error = _last_error or ValueError(
                f"collective result shape {res.shape} != declared "
                f"{tuple(spec['shape'])}")
            res = arrays[i]
        ctypes.memmove(outs[i], res.ctypes.data, res.nbytes)


def _async_raise_on_main(err: BaseException) -> None:
    """Surface an in-compiled-step engine error on the main thread.

    A cached jit_compile=True train loop may never re-enter trace-time
    code (where ``maybe_reraise`` runs) nor any eager collective — the
    error would otherwise be swallowed forever and training would
    continue on identity (un-reduced) data.  A custom call cannot raise
    through XLA, so inject the exception CLASS asynchronously into the
    main thread (fires at the next bytecode boundary — i.e. when the
    compiled step returns); the instance detail stays in ``_last_error``
    for ``maybe_reraise``.  HorovodInternalError reaches the elastic run
    wrapper's recovery exactly as on the eager path.  Disable with
    HVD_TPU_TF_XLA_ASYNC_RAISE=0 (then only logging + deferred re-raise
    remain)."""
    if os.environ.get("HVD_TPU_TF_XLA_ASYNC_RAISE", "1") in ("0", "false"):
        return
    try:
        cls = type(err) if isinstance(err, Exception) else RuntimeError
        tid = threading.main_thread().ident
        if tid is None or tid == threading.get_ident():
            return
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(tid), ctypes.py_object(cls))
    except Exception:  # pragma: no cover — raising must never recurse
        pass


def _resolve_process_set(set_id: int):
    if set_id < 0:
        return None
    from ..common.basics import _require_init

    return _require_init().process_set_registry.get(set_id)


def _dispatch(meta, arrays):
    kind = meta["kind"]
    ps = _resolve_process_set(meta.get("process_set", -1))
    if kind == "allreduce":
        return [_engine_ops.allreduce(
            arrays[0], average=meta["average"],
            op=None if meta["op"] is None else ReduceOp(meta["op"]),
            prescale_factor=meta["prescale"],
            postscale_factor=meta["postscale"],
            name=meta["name"], process_set=ps)]
    if kind == "grouped_allreduce":
        return _engine_ops.grouped_allreduce(
            arrays, average=meta["average"],
            op=None if meta["op"] is None else ReduceOp(meta["op"]),
            prescale_factor=meta["prescale"],
            postscale_factor=meta["postscale"],
            name=meta["name"], process_set=ps)
    if kind == "broadcast":
        return [_engine_ops.broadcast(
            arrays[0], meta["root_rank"], name=meta["name"],
            process_set=ps)]
    raise ValueError(f"unknown collective kind {kind!r}")


# The CFUNCTYPE object must be created OUTSIDE any tf.function trace
# (AutoGraph would convert _callback) and stay referenced for the process
# lifetime (ctypes callbacks die with their wrapper object).
_CB_REF = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.c_uint32,
    ctypes.POINTER(ctypes.c_void_p),
    ctypes.POINTER(ctypes.c_void_p))(_callback)


# -- op emission -------------------------------------------------------------


@_no_autograph
def _emit(kind: str, tensors, **meta_fields):
    """Build one XlaCustomCallV2 over ``tensors`` (+ the meta operand)."""
    from tensorflow.python.framework import op_def_library

    maybe_reraise()
    tensors = [tf.convert_to_tensor(t) for t in tensors]
    for t in tensors:
        if not t.shape.is_fully_defined():
            raise ValueError(
                "XLA collectives need static shapes; got "
                f"{t.shape} for a {kind} inside jit_compile")
    meta = json.dumps({
        "kind": kind,
        "tensors": [{"dtype": t.dtype.name, "shape": t.shape.as_list()}
                    for t in tensors],
        **meta_fields,
    }).encode()
    hdr = struct.pack("<II", len(meta), len(tensors)) + meta
    meta_t = tf.constant(np.frombuffer(hdr, np.uint8))
    out = op_def_library.apply_op(
        "XlaCustomCallV2",
        operands=[meta_t] + tensors,
        call_target_name=_TARGET,
        backend_config="",
        has_side_effect=True,
        result_dtypes=[t.dtype for t in tensors],
        result_shapes=[t.shape for t in tensors],
    )
    return list(out) if isinstance(out, (list, tuple)) else [out]


def xla_allreduce(tensor, average=None, name=None, op=None,
                  prescale_factor=1.0, postscale_factor=1.0,
                  process_set=None):
    return _emit(
        "allreduce", [tensor], average=average, name=name,
        op=None if op is None else int(op), prescale=prescale_factor,
        postscale=postscale_factor,
        process_set=-1 if process_set is None
        else process_set.process_set_id)[0]


def xla_grouped_allreduce(tensors, average=None, name=None, op=None,
                          prescale_factor=1.0, postscale_factor=1.0,
                          process_set=None):
    return _emit(
        "grouped_allreduce", tensors, average=average, name=name,
        op=None if op is None else int(op), prescale=prescale_factor,
        postscale=postscale_factor,
        process_set=-1 if process_set is None
        else process_set.process_set_id)


def xla_broadcast(tensor, root_rank, name=None, process_set=None):
    return _emit(
        "broadcast", [tensor], root_rank=int(root_rank), name=name,
        process_set=-1 if process_set is None
        else process_set.process_set_id)[0]
