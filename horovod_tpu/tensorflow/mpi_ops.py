"""TensorFlow tensor collectives over the XLA engine.

Reference parity: horovod/tensorflow/mpi_ops.py + the C++ custom ops it
fronts (tensorflow/mpi_ops.cc — SURVEY.md §2.3).  The reference registers
``HorovodAllreduce``-style TF kernels; here a CPU ``tf.Tensor`` bridges to
numpy (zero-copy in eager mode) and routes through the same eager engine
the JAX and torch surfaces use, so every rank's TF collective negotiates
in the one shared background controller.

Graph mode (``tf.function``): the reference's custom ops trace natively;
this adapter wraps the engine call in ``tf.py_function`` so traced
programs (e.g. Keras ``model.fit``'s compiled ``train_step``) execute the
same negotiated collective at run time.  Output shapes are re-asserted
where statically known (allreduce/broadcast preserve shape).

``tf.function(jit_compile=True)``: py_function has no XLA lowering, so
shape-preserving collectives switch to the XLA custom-call bridge
(``xla_ops`` — reference: tensorflow/xla_mpi_ops.cc) when tracing for a
must-compile function (auto-detected; HOROVOD_ENABLE_XLA_OPS=1 forces it
for all graph mode, =0 disables).  Shape-dynamic collectives (allgather,
alltoall, reducescatter) cannot be XLA-compiled — same scoping as the
reference's allreduce-only XLA op set — and raise with a migration hint.

The TPU compute path for new code remains the JAX API; this adapter
exists for reference-script parity and CPU-hosted TF training.
"""

from __future__ import annotations

import os
import sys
from typing import Optional

import numpy as np
import tensorflow as tf

from ..common.process_sets import ProcessSet
from ..ops import collective_ops as _ops
from ..ops.reduce_ops import ReduceOp, Sum


def _is_symbolic(t) -> bool:
    return isinstance(t, tf.Tensor) and not hasattr(t, "numpy")


def _xla_path() -> bool:
    """True when collectives should lower through the XLA custom-call
    bridge: tracing for jit_compile=True (or forced via env) and the
    bridge built.  Trace-time only — never on the eager fast path."""
    if os.environ.get("HOROVOD_ENABLE_XLA_OPS", "").lower() in ("0", "false"):
        return False
    from . import xla_ops

    return xla_ops.in_jit_trace() and xla_ops.available()


def _reject_in_jit(op_name: str) -> None:
    from . import xla_ops

    # consider_env=False: the HOROVOD_ENABLE_XLA_OPS force flag must not
    # reject shape-dynamic ops in PLAIN graphs, where py_function works
    if xla_ops.in_jit_trace(consider_env=False):
        raise NotImplementedError(
            f"hvd.{op_name} has a data-dependent output shape and cannot "
            "run inside tf.function(jit_compile=True) (XLA needs static "
            "shapes; the reference's XLA op set is likewise "
            "allreduce-only).  Call it outside the jit-compiled function, "
            "or use the JAX surface (horovod_tpu.ops.spmd_ops) where "
            "uneven collectives are compiled natively."
        )


def _check_xla_error() -> None:
    """Surface an engine error captured inside a compiled program (the
    XLA bridge cannot raise through XLA) from the next eager/graph entry.
    sys.modules guard: never pays the bridge import on sessions that
    never used jit_compile."""
    m = sys.modules.get(__package__ + ".xla_ops")
    if m is not None:
        m.maybe_reraise()


def _run(engine_fn, tensor, out_dtype=None, preserve_shape=True):
    """Execute ``engine_fn(np_array) -> np_array`` on a TF tensor, in
    eager or graph mode."""
    _check_xla_error()
    tensor = tf.convert_to_tensor(tensor)
    out_dtype = out_dtype or tensor.dtype
    if not _is_symbolic(tensor):
        return tf.convert_to_tensor(
            np.asarray(engine_fn(tensor.numpy())), dtype=out_dtype
        )
    out = tf.py_function(
        lambda a: np.asarray(engine_fn(a.numpy())), [tensor], Tout=out_dtype
    )
    if preserve_shape:
        out.set_shape(tensor.shape)
    else:
        out.set_shape([None] + list(tensor.shape)[1:])
    return out


# -- allreduce ---------------------------------------------------------------


def allreduce(tensor, average: Optional[bool] = None,
              name: Optional[str] = None, op: Optional[ReduceOp] = None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              process_set: Optional[ProcessSet] = None):
    """Reference: horovod/tensorflow/mpi_ops.py allreduce (op defaults to
    Average, as upstream's ``hvd.allreduce``)."""
    tensor = tf.convert_to_tensor(tensor)  # once; _run's convert is a no-op
    if _is_symbolic(tensor) and _xla_path():
        from . import xla_ops

        return xla_ops.xla_allreduce(
            tensor, average=average, name=name, op=op,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, process_set=process_set)
    return _run(
        lambda a: _ops.allreduce(
            a, average=average, name=name, op=op,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, process_set=process_set,
        ),
        tensor,
    )


def grouped_allreduce(tensors, average: Optional[bool] = None,
                      name: Optional[str] = None,
                      op: Optional[ReduceOp] = None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      process_set: Optional[ProcessSet] = None):
    """Reference: horovod/tensorflow/mpi_ops.py grouped_allreduce — the
    group executes atomically (all fuse together or none)."""
    _check_xla_error()
    tensors = [tf.convert_to_tensor(t) for t in tensors]
    kwargs = dict(
        average=average, name=name, op=op, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor, process_set=process_set,
    )
    if not any(_is_symbolic(t) for t in tensors):
        outs = _ops.grouped_allreduce([t.numpy() for t in tensors], **kwargs)
        return [tf.convert_to_tensor(np.asarray(o), dtype=t.dtype)
                for o, t in zip(outs, tensors)]
    if _xla_path():
        from . import xla_ops

        return xla_ops.xla_grouped_allreduce(
            tensors, average=average, name=name, op=op,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, process_set=process_set)
    douts = [t.dtype for t in tensors]

    def run(*arrays):
        outs = _ops.grouped_allreduce([a.numpy() for a in arrays], **kwargs)
        return [np.asarray(o) for o in outs]

    outs = tf.py_function(run, tensors, Tout=douts)
    for o, t in zip(outs, tensors):
        o.set_shape(t.shape)
    return list(outs)


def _run_grouped(engine_fn, tensors, op_name: str):
    """Shared scaffold for grouped shape-dynamic collectives: eager →
    engine directly; plain graph → py_function; jit_compile → clean
    rejection (dim0 may differ per rank, so output dim0 is unknown)."""
    _check_xla_error()
    tensors = [tf.convert_to_tensor(t) for t in tensors]
    if any(_is_symbolic(t) for t in tensors):
        _reject_in_jit(op_name)
        douts = [t.dtype for t in tensors]

        def run(*arrays):
            return [np.asarray(o)
                    for o in engine_fn([a.numpy() for a in arrays])]

        outs = tf.py_function(run, tensors, Tout=douts)
        for o, t in zip(outs, tensors):
            if t.shape.rank is not None:  # unknown rank stays unknown
                o.set_shape([None] + list(t.shape)[1:])
        return list(outs)
    outs = engine_fn([t.numpy() for t in tensors])
    return [tf.convert_to_tensor(np.asarray(o), dtype=t.dtype)
            for o, t in zip(outs, tensors)]


def grouped_allgather(tensors, name: Optional[str] = None,
                      process_set: Optional[ProcessSet] = None):
    """Reference: tf grouped_allgather — atomic fused group (one dim0
    exchange + per-dtype-bucket gather on the shared implementation)."""
    return _run_grouped(
        lambda arrays: _ops.grouped_allgather(
            arrays, name=name, process_set=process_set),
        tensors, "grouped_allgather",
    )


def grouped_reducescatter(tensors, op: Optional[ReduceOp] = None,
                          name: Optional[str] = None,
                          process_set: Optional[ProcessSet] = None):
    """Reference: tf grouped_reducescatter — atomic group release."""
    return _run_grouped(
        lambda arrays: _ops.grouped_reducescatter(
            arrays, op=op if op is not None else Sum, name=name,
            process_set=process_set),
        tensors, "grouped_reducescatter",
    )


# -- allgather / broadcast ---------------------------------------------------


def allgather(tensor, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None):
    """Concatenate each rank's tensor along axis 0; first dims may differ
    per rank (reference: HorovodAllgather's uneven recvcounts)."""
    tensor = tf.convert_to_tensor(tensor)
    if _is_symbolic(tensor):
        _reject_in_jit("allgather")
    return _run(
        lambda a: _ops.allgather(a, name=name, process_set=process_set),
        tensor, preserve_shape=False,
    )


def broadcast(tensor, root_rank: int, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None):
    tensor = tf.convert_to_tensor(tensor)
    if _is_symbolic(tensor) and _xla_path():
        from . import xla_ops

        return xla_ops.xla_broadcast(tensor, root_rank, name=name,
                                     process_set=process_set)
    return _run(
        lambda a: _ops.broadcast(a, root_rank, name=name,
                                 process_set=process_set),
        tensor,
    )


# -- alltoall / reducescatter ------------------------------------------------


def alltoall(tensor, splits=None, name: Optional[str] = None,
             process_set: Optional[ProcessSet] = None):
    """Returns (received, received_splits) like the reference's
    HorovodAlltoall."""
    _check_xla_error()
    tensor = tf.convert_to_tensor(tensor)
    have_splits = splits is not None
    if have_splits:
        splits = tf.convert_to_tensor(splits)

    def run(a, s=None):
        received, recv_splits = _ops.alltoall(
            a.numpy(), splits=None if s is None else np.asarray(s.numpy()),
            name=name, process_set=process_set,
        )
        return np.asarray(received), np.asarray(recv_splits, np.int32)

    symbolic = _is_symbolic(tensor) or (have_splits and _is_symbolic(splits))
    if symbolic:
        _reject_in_jit("alltoall")
    if not symbolic:
        received, recv_splits = run(tensor, splits if have_splits else None)
        return (tf.convert_to_tensor(received, dtype=tensor.dtype),
                tf.convert_to_tensor(recv_splits, tf.int32))

    inputs = [tensor, splits] if have_splits else [tensor]
    received, recv_splits = tf.py_function(
        run, inputs, Tout=[tensor.dtype, tf.int32]
    )
    received.set_shape([None] + list(tensor.shape)[1:])
    recv_splits.set_shape([None])
    return received, recv_splits


def reducescatter(tensor, op: Optional[ReduceOp] = None,
                  name: Optional[str] = None,
                  process_set: Optional[ProcessSet] = None):
    tensor = tf.convert_to_tensor(tensor)
    if _is_symbolic(tensor):
        _reject_in_jit("reducescatter")
    return _run(
        lambda a: _ops.reducescatter(a, op=op, name=name,
                                     process_set=process_set),
        tensor, preserve_shape=False,
    )


# -- control -----------------------------------------------------------------


def barrier(process_set: Optional[ProcessSet] = None) -> None:
    _check_xla_error()
    _ops.barrier(process_set=process_set)


def join() -> int:
    """Reference: HorovodJoin — returns the last joining rank."""
    return _ops.join()
