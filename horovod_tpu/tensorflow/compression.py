"""Gradient compression for the TensorFlow adapter.

Reference parity: horovod/tensorflow/compression.py —
``Compression.none`` and ``Compression.fp16``, applied to gradients
before the wire and undone after.
"""

from __future__ import annotations

import tensorflow as tf


class Compressor:
    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference: NoneCompressor)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast fp32/fp64 to fp16 on the wire (reference: FP16Compressor)."""

    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating:
            return tf.cast(tensor, tf.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tf.cast(tensor, ctx) if ctx is not None else tensor


class Compression:
    """Namespace matching the reference's ``hvd.Compression`` surface."""

    none = NoneCompressor
    fp16 = FP16Compressor
