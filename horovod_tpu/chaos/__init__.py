"""Deterministic fault injection (chaos) for horovod_tpu.

The subsystem that PROVES the recovery machinery works: named injection
points throughout the framework evaluate a seed-driven plan and, when a
rule fires, inject one of eight faults::

    drop     the caller discards the unit of work (frame, batch)
    delay    sleep ``delay`` seconds, then continue
    corrupt  flip one bit of the payload handed to :func:`point`
    raise    raise :class:`ChaosInjected` at the call site
    kill     SIGKILL this process (the classic elastic fault)
    hang     sleep forever — a live-but-silent worker, the fault only
             heartbeats (not process-exit watching) can see
    flipbit  flip ONE high-order bit of a numeric payload (ndarray,
             float, int; bytes get one mid-buffer bit) — the silent-
             data-corruption model ("Cores that don't count"): a
             materially wrong VALUE inside a structurally valid
             container, visible only to integrity checks (guard.*)
    scale    multiply a numeric payload by ``factor`` (default 1024) —
             the runaway-gradient model the guard's loss-spike EMA sees

Configured entirely from the environment so any launcher can inject::

    HVD_TPU_CHAOS="elastic.commit:kill,at=8,rank=1;transport.frame.send:corrupt,at=400,rank=1,fuse=/tmp/f1"
    HVD_TPU_CHAOS_SEED=42

Per-rank derived streams (spec.Rule.stream_seed) make runs replay
exactly: same seed + same rank + same call sequence = same injection
trace.  Sites under ``transport.`` live in the native C++ core; their
rules are exported through the ``hvdtpu_chaos_*`` C API at controller
load (native/src/chaos.h mirrors the evaluation semantics).

When ``HVD_TPU_CHAOS`` is unset the whole subsystem is a single module
bool check per call site — free in steady state.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, List, Optional

from ..metrics import instruments as _metrics
from ..utils.logging import get_logger
from .spec import (
    ACTION_ENUM, ACTIONS, NATIVE_ACTIONS, ChaosSpecError, Rule, parse_spec,
)

__all__ = [
    "ChaosInjected", "DROP", "SITES", "active", "clear", "configure",
    "configure_native_lib", "injection_trace", "install_from_env", "point",
]

ENV_SPEC = "HVD_TPU_CHAOS"
ENV_SEED = "HVD_TPU_CHAOS_SEED"
#: Optional JSONL file every Python-side fire is appended to (replay
#: assertions in tools/chaos_soak.py read it back).
ENV_LOG = "HVD_TPU_CHAOS_LOG"

#: Sites evaluated in the native C++ core, exported via hvdtpu_chaos_*.
NATIVE_PREFIX = "transport."

#: Injection-point catalogue (docs/FAULT_TOLERANCE.md mirrors this).
SITES = (
    "transport.frame.send",    # native: outgoing negotiation frame
    "transport.frame.recv",    # native: incoming negotiation frame
    "controller.enqueue",      # collective submission (ctypes layer)
    "controller.resolve",      # fused-response execution callback
    "data.batch",              # input-pipeline worker collate
    "data.prefetch",           # device staging in the prefetcher
    "elastic.commit",          # elastic state commit (per training step)
    "training.step",           # fit_epoch loop body
    "fleet.preempt",           # preemption-notice poll (fleet/preemption.py)
    "guard.grad",              # per-step gradient tap (guard.py tap_grads)
    "guard.param",             # cadence param-fingerprint tap (guard.py)
    "checkpoint.payload",      # checkpoint bytes about to be published
    "serve.dispatch",          # router->replica request hand-off
    "serve.replica_step",      # one fleet replica's engine step
    "serve.migrate",           # KV snapshot wire on the warm recovery path
    "serve.snapshot",          # periodic in-flight KV export (replica)
    "serve.handoff",           # kvsnap wire at the prefill->decode boundary
)


class ChaosInjected(RuntimeError):
    """Raised at a chaos point by an ``action=raise`` rule."""


class _Drop:
    def __repr__(self):  # pragma: no cover - repr cosmetics
        return "<chaos.DROP>"


#: Sentinel returned by :func:`point` when a ``drop`` rule fired — the
#: caller discards the unit of work it was about to process.
DROP = _Drop()

#: Fast-path flag: False means every point() returns immediately.
active = False

_lock = threading.Lock()
_plan: dict = {}          # site -> List[_Armed]
_seed: int = 0
_rank: int = 0
_trace: List[dict] = []
_log_path: Optional[str] = None


class _Armed:
    """One installed rule + its deterministic draw stream."""

    __slots__ = ("rule", "state")

    def __init__(self, rule: Rule, stream_seed: int):
        self.rule = rule
        self.state = stream_seed  # xorshift64 state (matches chaos.h)

    def draw(self) -> float:
        x = self.state
        x ^= (x << 13) & 0xFFFFFFFFFFFFFFFF
        x ^= x >> 7
        x ^= (x << 17) & 0xFFFFFFFFFFFFFFFF
        self.state = x
        return (x >> 11) / float(1 << 53)


def configure(spec: str, seed: int = 0, rank: int = 0) -> List[Rule]:
    """Install a chaos plan (replacing any previous one).  Rules whose
    ``rank`` param names a different process are filtered out here —
    per-rank plans never reach the hot path."""
    global active, _seed, _rank
    rules = parse_spec(spec) if spec else []
    with _lock:
        _plan.clear()
        _trace.clear()
        _seed, _rank = int(seed), int(rank)
        for i, rule in enumerate(rules):
            if rule.rank is not None and rule.rank != rank:
                continue
            _plan.setdefault(rule.site, []).append(
                _Armed(rule, rule.stream_seed(_seed, rank, i))
            )
        active = bool(_plan)
    if active:
        get_logger().warning(
            "chaos: fault injection ACTIVE (%d rule(s), seed=%d, rank=%d)",
            sum(len(v) for v in _plan.values()), _seed, rank,
        )
    return rules


def install_from_env(rank: int = 0) -> bool:
    """Read ``HVD_TPU_CHAOS`` / ``HVD_TPU_CHAOS_SEED`` and install the
    plan for this process (called from ``hvd.init()``).  Returns whether
    any rule is active here."""
    global _log_path
    from ..common.retry import env_int

    spec = os.environ.get(ENV_SPEC, "")
    seed = env_int(ENV_SEED, 0)
    _log_path = os.environ.get(ENV_LOG) or None
    configure(spec, seed=seed, rank=rank)
    return active


def clear() -> None:
    """Disarm every rule (tests)."""
    global active
    with _lock:
        _plan.clear()
        _trace.clear()
        active = False


def injection_trace() -> List[dict]:
    """Python-side fires so far, in order (replay assertions)."""
    with _lock:
        return list(_trace)


def _burn_fuse(path: str) -> bool:
    """True when this process wins the fuse (O_EXCL create); False when
    the fuse was already burnt — by this boot or a previous one."""
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
        os.close(fd)
        return True
    except FileExistsError:
        return False
    except OSError:
        # an unwritable fuse path must not turn a one-shot rule into a
        # repeating one: treat it as burnt and warn
        get_logger().warning("chaos: fuse path %r unusable; skipping rule",
                             path)
        return False


def _record_fire(site: str, action: str, eval_idx: int) -> None:
    _metrics.CHAOS_INJECTIONS.labels(site, action).inc()
    event = {"site": site, "action": action, "eval": eval_idx,
             "rank": _rank}
    _trace.append(event)
    # chaos fires are first-class timeline events: a crash bundle or a
    # /trace export shows the injection in sequence with the spans it
    # broke (docs/TRACING.md)
    from .. import trace as _span_trace

    _span_trace.event("chaos.inject", site=site, action=action,
                      eval=eval_idx)
    get_logger().warning("chaos: injecting %s at %s (eval %d)",
                         action, site, eval_idx)
    if _log_path:
        try:
            with open(_log_path, "a") as f:
                f.write(json.dumps(event) + "\n")
        except OSError:
            pass


def _corrupt(payload: Any) -> Any:
    """Flip one bit of a bytes-like payload; other types pass through a
    best-effort mangling (numeric negate-and-offset)."""
    if isinstance(payload, (bytes, bytearray)):
        buf = bytearray(payload)
        if buf:
            buf[len(buf) // 2] ^= 0x01
        return bytes(buf)
    if isinstance(payload, (int, float)):
        return -payload - 1
    return payload


def _flipbit(payload: Any) -> Any:
    """Flip ONE bit of a numeric payload, placed high in the element's
    representation so the value change is material (for little-endian
    floats bit 6 of the top byte is an exponent bit): the silent-data-
    corruption model — wrong VALUE, valid container.  Returns None when
    the payload type carries no flippable value (caller raises)."""
    import numpy as np

    if isinstance(payload, np.ndarray):
        out = np.array(payload, copy=True)
        if out.size == 0 or out.dtype.hasobject:
            return None
        flat = out.reshape(-1).view(np.uint8)
        # middle element's most-significant byte (little-endian
        # layout), bit 4: a mid-exponent bit for floats — a 2^±32
        # value change that stays FINITE (flipping the top exponent
        # bits of a ~1.0 float would make Inf, which the cheap NaN/Inf
        # sentinel catches; SDC's interesting case is the wrong value
        # only a digest can see)
        i = (out.size // 2) * out.itemsize + (out.itemsize - 1)
        flat[i] ^= 0x10
        return out
    if isinstance(payload, (bytes, bytearray)):
        buf = bytearray(payload)
        if not buf:
            return None
        buf[len(buf) // 2] ^= 0x10
        return bytes(buf)
    if isinstance(payload, bool):
        return not payload
    if isinstance(payload, int):
        return payload ^ (1 << 30)
    if isinstance(payload, float):
        bits = np.array([payload], np.float64).view(np.uint64)
        bits[0] ^= np.uint64(1 << 52)  # exponent LSB: a large change
        return float(bits.view(np.float64)[0])
    return None


def _scale(payload: Any, factor: float) -> Any:
    """Multiply a numeric payload by ``factor`` (dtype preserved for
    ndarrays) — the runaway-value model.  None = not scalable."""
    import numpy as np

    if isinstance(payload, np.ndarray):
        if payload.dtype.hasobject or payload.dtype.kind in "SUV":
            return None
        return np.asarray(payload * factor).astype(payload.dtype)
    if isinstance(payload, bool):
        return None  # a scaled bool is a no-op, not a fault
    if isinstance(payload, (int, float)):
        return type(payload)(payload * factor)
    return None


def point(site: str, payload: Any = None) -> Any:
    """Evaluate the chaos plan at ``site``.

    Returns ``payload`` (possibly corrupted), or :data:`DROP` when the
    caller should discard the unit of work.  ``delay`` sleeps in place;
    ``raise`` raises :class:`ChaosInjected`; ``kill``/``hang`` never
    return.  One module-bool check when chaos is off.
    """
    if not active:
        return payload
    with _lock:
        armed = _plan.get(site)
        if not armed:
            return payload
        fire: Optional[Rule] = None
        eval_idx = 0
        for a in armed:
            r = a.rule
            eval_idx = r.evals
            r.evals += 1
            if fire is not None:
                continue  # counters still advance for later rules
            if r.times is not None and r.fired >= r.times:
                continue
            if eval_idx < r.after:
                continue
            if r.at is not None:
                if eval_idx != r.at:
                    continue
            elif r.prob < 1.0 and a.draw() >= r.prob:
                continue
            if r.fuse and not _burn_fuse(r.fuse):
                # burnt in a prior boot: retire the rule so the hot path
                # never re-probes the filesystem for it
                r.times = r.fired
                continue
            r.fired += 1
            fire = r
            _record_fire(site, r.action, eval_idx)
    if fire is None:
        return payload
    action = fire.action
    if action == "drop":
        return DROP
    if action == "delay":
        time.sleep(fire.delay)
        return payload
    if action == "corrupt":
        if payload is None:
            # no payload to corrupt at this site: inject as a failure so
            # a fault counted in the trace is a fault that happened
            raise ChaosInjected(
                f"chaos: corrupt at {site} (no payload; injected as "
                "failure)"
            )
        return _corrupt(payload)
    if action in ("flipbit", "scale"):
        out = None if payload is None else (
            _flipbit(payload) if action == "flipbit"
            else _scale(payload, fire.factor))
        if out is None:
            # nothing numeric to mangle: same inject-as-failure contract
            # as payload-less corrupt — a counted fault must be a fault
            raise ChaosInjected(
                f"chaos: {action} at {site} (no numeric payload; "
                "injected as failure)"
            )
        return out
    if action == "raise":
        raise ChaosInjected(
            f"chaos: injected failure at {site} (eval {fire.evals - 1})"
        )
    if action == "kill":
        if fire.code < 0:
            # code=-N delivers signal N to this process instead of
            # exiting — the preemption-notice drill (a SIGTERM the
            # fleet.preemption guard's grace path then handles); the
            # point returns and the handler runs asynchronously
            get_logger().error("chaos: delivering signal %d to self at %s",
                               -fire.code, site)
            os.kill(os.getpid(), -fire.code)
            return payload
        get_logger().error("chaos: self-kill at %s", site)
        try:
            # the black box goes out BEFORE the lights: the bundle
            # carries this process's final spans incl. the kill event
            # (HVD_TPU_TRACE_BUNDLE_DIR opts in; never raises)
            from ..trace import flight as _flight

            _flight.maybe_dump("chaos_kill", extra={"site": site})
        except Exception:
            pass
        os._exit(fire.code)
    if action == "hang":
        get_logger().error("chaos: self-hang at %s", site)
        while True:  # a live-but-silent process: only liveness probes see it
            time.sleep(3600)
    return payload  # pragma: no cover - exhaustive actions above


def raise_point(site: str) -> None:
    """:func:`point` for sites with NO droppable unit of work (commit,
    resolve, staging): a ``drop`` rule raises :class:`ChaosInjected`
    instead — the fault is actually injected, never merely recorded in
    the metrics/trace while the code path sails on."""
    if point(site) is DROP:
        raise ChaosInjected(
            f"chaos: drop at {site} (no droppable unit; injected as "
            "failure)"
        )


def configure_native_lib(lib, rank: Optional[int] = None) -> int:
    """Export the ``transport.*`` rules of the installed plan into the
    native core through the ``hvdtpu_chaos_*`` C API (called by the
    ctypes controller after dlopen, before ``hvdtpu_init``).  Returns the
    number of rules exported; 0 when chaos is off or the loaded binary
    predates the chaos API."""
    import ctypes

    if not hasattr(lib, "hvdtpu_chaos_set"):
        if active and any(s.startswith(NATIVE_PREFIX) for s in _plan):
            get_logger().warning(
                "chaos: native core predates hvdtpu_chaos_*; transport.* "
                "rules will not fire (rebuild with tools/rebuild_native.sh)"
            )
        return 0
    lib.hvdtpu_chaos_clear()
    if not active:
        return 0
    n = 0
    with _lock:
        use_rank = _rank if rank is None else rank
        for site, armed in _plan.items():
            if not site.startswith(NATIVE_PREFIX):
                continue
            for a in armed:
                r = a.rule
                if r.action not in NATIVE_ACTIONS:
                    get_logger().warning(
                        "chaos: action %r is Python-only; %s rule not "
                        "exported to the native engine", r.action, site)
                    continue
                lib.hvdtpu_chaos_set(
                    site.encode(), ACTION_ENUM[r.action],
                    ctypes.c_double(r.prob),
                    ctypes.c_longlong(-1 if r.at is None else r.at),
                    ctypes.c_longlong(r.after),
                    ctypes.c_longlong(-1 if r.times is None else r.times),
                    ctypes.c_double(r.delay),
                    ctypes.c_int(r.code),
                    (r.fuse or "").encode(),
                    ctypes.c_ulonglong(a.state),
                )
                n += 1
    if n:
        get_logger().warning(
            "chaos: %d native transport rule(s) exported (rank=%d)",
            n, use_rank,
        )
    return n
