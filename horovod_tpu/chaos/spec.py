"""Chaos spec grammar: parse ``HVD_TPU_CHAOS`` into injection rules.

Grammar (documented for users in docs/FAULT_TOLERANCE.md)::

    spec   := rule (";" rule)*
    rule   := site ":" action ("," param)*
    param  := key "=" value

``site`` is the dotted name of an injection point (the catalogue lives in
docs/FAULT_TOLERANCE.md; ``horovod_tpu.chaos.SITES`` mirrors it).
``action`` is one of ``drop | delay | corrupt | raise | kill | hang |
flipbit | scale``.  ``flipbit`` flips ONE high-order bit of a numeric
payload (ndarray/float/int; bytes get one mid-buffer bit) — the
Hochschild-style silent-corruption model: the value changes materially,
the container stays structurally valid.  ``scale`` multiplies a numeric
payload by ``factor`` — the runaway-gradient / loss-spike model.
Params:

    prob=F    fire probability per evaluation (default 1.0)
    at=N      fire exactly on the Nth evaluation of the site (0-based);
              implies times=1 unless overridden
    after=N   eligible only from the Nth evaluation on (default 0)
    times=N   maximum number of fires (default unlimited; 1 for at=)
    rank=R    only on the process with cross-rank R at install time
              (default: every rank)
    delay=F   seconds to sleep for action=delay (default 0.05)
    code=N    exit code for action=kill (default 137).  A NEGATIVE N
              delivers signal -N to the process instead of exiting
              (Python sites only) — the preemption drill:
              ``fleet.preempt:kill,code=-15`` is a SIGTERM notice the
              fleet.preemption guard's grace path handles
    factor=F  multiplier for action=scale (default 1024.0)
    fuse=PATH fire at most once ACROSS process generations: the first
              fire creates PATH (O_EXCL) and any process that finds it
              existing skips the rule.  This is how a kill/corrupt
              injection is kept from re-arming after the elastic
              exec-restart it provoked.

Determinism: probability draws come from a per-(rank, site, rule) stream
derived from ``HVD_TPU_CHAOS_SEED`` via SHA-256 — the same seed, rank and
call sequence replay the exact same injection trace (the acceptance bar
of tools/chaos_soak.py).  Evaluation counters are per process boot.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional

ACTIONS = ("drop", "delay", "corrupt", "raise", "kill", "hang",
           "flipbit", "scale")

#: Action enum values shared with the native side (native/src/chaos.h).
#: The native core implements only the first six; flipbit/scale are
#: Python-site actions (chaos.configure_native_lib skips them with a
#: warning when a transport.* rule names one).
ACTION_ENUM = {name: i + 1 for i, name in enumerate(ACTIONS)}

#: Actions the native engine (chaos.h Action enum) implements.
NATIVE_ACTIONS = frozenset(ACTIONS[:6])


class ChaosSpecError(ValueError):
    """Malformed HVD_TPU_CHAOS spec (bad grammar, unknown action/param)."""


@dataclass
class Rule:
    site: str
    action: str
    prob: float = 1.0
    at: Optional[int] = None
    after: int = 0
    times: Optional[int] = None
    rank: Optional[int] = None
    delay: float = 0.05
    code: int = 137
    factor: float = 1024.0
    fuse: Optional[str] = None
    # runtime state (per process boot)
    evals: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)

    def stream_seed(self, seed: int, rank: int, index: int) -> int:
        """64-bit per-(seed, rank, site, rule-index) stream seed — the
        derivation both the Python and the native engine use, so a rule
        moved between the two fires on the same draws."""
        material = f"{seed}:{rank}:{self.site}:{index}".encode()
        return int.from_bytes(
            hashlib.sha256(material).digest()[:8], "little"
        ) or 1  # xorshift64 state must be nonzero


def _parse_rule(text: str) -> Rule:
    head, *params = [p.strip() for p in text.split(",")]
    if ":" not in head:
        raise ChaosSpecError(
            f"chaos rule {text!r} lacks ':' (want site:action[,k=v...])"
        )
    site, action = (s.strip() for s in head.split(":", 1))
    if not site:
        raise ChaosSpecError(f"chaos rule {text!r} has an empty site")
    if action not in ACTIONS:
        raise ChaosSpecError(
            f"chaos rule {text!r}: unknown action {action!r} "
            f"(want one of {', '.join(ACTIONS)})"
        )
    rule = Rule(site=site, action=action)
    for param in params:
        if not param:
            continue
        if "=" not in param:
            raise ChaosSpecError(
                f"chaos rule {text!r}: param {param!r} lacks '='"
            )
        key, value = (s.strip() for s in param.split("=", 1))
        try:
            if key == "prob":
                rule.prob = float(value)
                if not 0.0 <= rule.prob <= 1.0:
                    raise ChaosSpecError(
                        f"chaos rule {text!r}: prob must be in [0, 1]"
                    )
            elif key == "at":
                rule.at = int(value)
            elif key == "after":
                rule.after = int(value)
            elif key == "times":
                rule.times = int(value)
            elif key == "rank":
                rule.rank = int(value)
            elif key == "delay":
                rule.delay = float(value)
            elif key == "code":
                rule.code = int(value)
            elif key == "factor":
                rule.factor = float(value)
            elif key == "fuse":
                rule.fuse = value
            else:
                raise ChaosSpecError(
                    f"chaos rule {text!r}: unknown param {key!r}"
                )
        except ChaosSpecError:
            raise
        except ValueError as e:
            raise ChaosSpecError(
                f"chaos rule {text!r}: bad value for {key!r}: {e}"
            ) from None
    if rule.at is not None and rule.times is None:
        rule.times = 1
    return rule


def parse_spec(spec: str) -> List[Rule]:
    """Parse a full ``HVD_TPU_CHAOS`` value into rules (may be empty)."""
    rules = []
    for part in spec.split(";"):
        part = part.strip()
        if part:
            rules.append(_parse_rule(part))
    return rules
