"""Trace-site parity lint (pass #5).

The span recorder names its instrumentation points twice — the
``SITES`` catalogue in ``trace/__init__.py`` and the site table in
``docs/TRACING.md`` — and the package's ``trace.span("...")`` /
``trace.event("...")`` / ``trace.add_span("...")`` literals must agree
with both.  A span site present in one layer but not the others is
either a timeline name no dashboard can look up, or a documented
signal that never records — the same silent-drift class the chaos and
metrics passes exist for.

Checked equivalences:

* every ``span``/``event``/``add_span`` literal in the package names a
  catalogued site;
* every catalogued site has at least one call site in the package (a
  catalogue entry nothing records is dead);
* the docs/TRACING.md site table is exactly the catalogue (both
  directions).
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Set, Tuple

from ._common import Finding, iter_py_files, read_text

CHECK = "trace"

TRACE_INIT_PY = "horovod_tpu/trace/__init__.py"
TRACING_MD = "docs/TRACING.md"

_SITES_RE = re.compile(r"^SITES\s*=\s*\(", re.MULTILINE)
_STR_RE = re.compile(r"\"([a-z0-9_.]+)\"")
# matches trace.span("x") / _trace.event("x") / trace.add_span("x") —
# any alias ending in `trace.`; the method set keeps collective_ops'
# unrelated _span(name, ...) helper out
_CALL_RE = re.compile(
    r"\w*trace\.(?:span|event|add_span)\(\s*[\"']([a-z0-9_.]+)[\"']")
_DOC_ROW_RE = re.compile(
    r"^\|\s*`([a-z0-9_]+(?:\.[a-z0-9_]+)+)`\s*\|", re.MULTILINE)


def catalogue(root: str) -> Dict[str, int]:
    """site -> line of the SITES tuple in trace/__init__.py."""
    text = read_text(os.path.join(root, TRACE_INIT_PY))
    if text is None:
        return {}
    m = _SITES_RE.search(text)
    if not m:
        return {}
    i = text.index("(", m.start())
    depth, j = 0, i
    while j < len(text):
        if text[j] == "(":
            depth += 1
        elif text[j] == ")":
            depth -= 1
            if depth == 0:
                break
        j += 1
    out: Dict[str, int] = {}
    for sm in _STR_RE.finditer(text, i, j):
        out[sm.group(1)] = text.count("\n", 0, sm.start()) + 1
    return out


def run(root: str) -> List[Finding]:
    findings: List[Finding] = []
    sites = catalogue(root)
    if not sites:
        findings.append(Finding(
            CHECK, TRACE_INIT_PY, 0, "missing",
            "trace/__init__.py SITES catalogue not found/empty — the "
            "span-site registry is gone"))
        return findings

    # -- call sites ----------------------------------------------------------
    used: Set[str] = set()
    for rel in iter_py_files(root,
                             exclude_dirs=("analysis", "trace",
                                           "__pycache__")):
        text = read_text(os.path.join(root, rel))
        if text is None:
            continue
        for m in _CALL_RE.finditer(text):
            site = m.group(1)
            used.add(site)
            if site not in sites:
                lineno = text.count("\n", 0, m.start()) + 1
                findings.append(Finding(
                    CHECK, rel, lineno, site,
                    f"trace site {site!r} is recorded here but not in "
                    "the trace SITES catalogue — the timeline carries a "
                    "name no site table explains",
                ))

    for site, lineno in sorted(sites.items()):
        if site not in used:
            findings.append(Finding(
                CHECK, TRACE_INIT_PY, lineno, site,
                f"catalogued trace site {site!r} has no span()/event()/"
                "add_span() call site in the package (dead catalogue "
                "entry)",
            ))

    # -- documented table ----------------------------------------------------
    doc_text = read_text(os.path.join(root, TRACING_MD))
    if doc_text is None:
        findings.append(Finding(CHECK, TRACING_MD, 0, "missing",
                                "docs/TRACING.md not found"))
        return findings
    doc_sites: Dict[str, int] = {}
    for m in _DOC_ROW_RE.finditer(doc_text):
        doc_sites[m.group(1)] = doc_text.count("\n", 0, m.start()) + 1
    for site, lineno in sorted(sites.items()):
        if site not in doc_sites:
            findings.append(Finding(
                CHECK, TRACE_INIT_PY, lineno, site,
                f"trace site {site!r} is catalogued but missing from "
                "the docs/TRACING.md site table",
            ))
    for site, lineno in sorted(doc_sites.items()):
        if site not in sites:
            findings.append(Finding(
                CHECK, TRACING_MD, lineno, site,
                f"docs/TRACING.md documents trace site {site!r} but the "
                "SITES catalogue does not contain it",
            ))
    return findings
