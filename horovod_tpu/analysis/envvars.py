"""Env-var registry lint.

Every ``HVD_TPU_*`` variable the package reads must have a row in
``docs/running.md``; every documented row must correspond to a live
read (doc rot is drift too); and numeric parses must go through the
validated ``env_*`` helpers — a raw ``int(os.environ[...])`` turns a
typo'd knob into a process-killing ValueError at boot instead of a
warning + default.

Read detection covers the package's actual spellings:

* direct reads: ``os.environ.get/pop/[...]``, ``os.getenv``;
* the validated helpers: ``env_int(...)``, ``env_float(...)``;
* name constants: a module-level ``SOME_ENV = "HVD_TPU_X"`` (the
  constant exists to be read through);
* ``utils/env_parser.py``'s prefixing ``_get*("NAME")`` calls
  (resolved to ``HVD_TPU_NAME``);
* native reads: ``getenv("HVD_TPU_...")`` in ``native/src``.

Launcher *writes* (``env["HVD_TPU_X"] = ...``) are not reads and are
not required to be documented individually; docs/running.md's
worker-side list covers them, including the documented
``HVD_TPU_ELASTIC_*`` wildcard family.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Set, Tuple

from ._common import (
    Finding, RUNNING_MD, iter_native_files, iter_py_files, read_text,
    strip_comment,
)

CHECK = "env"
ENV_PARSER_PY = "horovod_tpu/utils/env_parser.py"

_READ_RES = (
    re.compile(r"os\.environ\.get\(\s*\"(HVD_TPU_\w+)\""),
    re.compile(r"os\.environ\.pop\(\s*\"(HVD_TPU_\w+)\""),
    re.compile(r"os\.getenv\(\s*\"(HVD_TPU_\w+)\""),
    re.compile(r"os\.environ\[\s*\"(HVD_TPU_\w+)\"\s*\](?!\s*=[^=])"),
    re.compile(r"env_(?:int|float|str|bool)\(\s*\"(HVD_TPU_\w+)\""),
    # keyword hand-off to a validated reader (metrics exposition)
    re.compile(r"env_var\s*=\s*\"(HVD_TPU_\w+)\""),
    # a name constant holding the variable (read through elsewhere)
    re.compile(r"^\s*[A-Za-z_]\w*\s*=\s*\"(HVD_TPU_\w+)\"\s*$",
               re.MULTILINE),
)
# std::getenv plus the validated native helpers (EnvSeconds & friends)
_NATIVE_READ_RE = re.compile(r"(?:getenv|Env\w*)\(\s*\"(HVD_TPU_\w+)\"")
_ENV_PARSER_GET_RE = re.compile(
    r"_get(?:_int|_float|_bool|_int_validated)?\(\s*[\r\n]*\s*\"(\w+)\""
)
_RAW_PARSE_RE = re.compile(r"\b(?:int|float)\s*\(\s*os\.(?:environ|getenv)")
_CONST_DEF_RE = re.compile(r"^\s*([A-Za-z_]\w*)\s*=\s*\"(HVD_TPU_\w+)\"\s*$")
_DOC_TOKEN_RE = re.compile(r"(HVD_TPU_[A-Z0-9_]+)(\*)?")


def _strip_comments(text: str, kind: str) -> str:
    """Comment-stripped text with line numbers preserved, so reads that
    wrap across lines (black-style call breaks) still match."""
    return "\n".join(strip_comment(ln, kind) for ln in text.splitlines())


def _lineno(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


def _scan_python(relfile: str, text: str,
                 reads: Dict[str, List[Tuple[str, int]]],
                 findings: List[Finding]) -> None:
    # normalize quote style so single-quoted reads match the patterns
    clean = _strip_comments(text, "py").replace("'", '"')
    consts: Dict[str, str] = {
        m.group(1): m.group(2)
        for m in re.finditer(_CONST_DEF_RE.pattern, clean, re.MULTILINE)
    }
    for rx in _READ_RES:
        for m in rx.finditer(clean):
            reads.setdefault(m.group(1), []).append(
                (relfile, _lineno(clean, m.start())))
    if relfile.replace(os.sep, "/") == ENV_PARSER_PY:
        for m in _ENV_PARSER_GET_RE.finditer(clean):
            reads.setdefault("HVD_TPU_" + m.group(1), []).append(
                (relfile, _lineno(clean, m.start())))
    for m in _RAW_PARSE_RE.finditer(clean):
        lineno = _lineno(clean, m.start())
        # name the variable when the call shows it (literal or a known
        # constant) so the allowlist key is stable
        context = clean[m.start():m.start() + 200]
        key = "raw"
        lit = re.search(r"\"(HVD_TPU_\w+)\"", context)
        if lit:
            key = lit.group(1)
        else:
            for name, value in consts.items():
                if re.search(rf"\b{re.escape(name)}\b", context):
                    key = value
                    break
        findings.append(Finding(
            CHECK, relfile, lineno, key,
            "raw numeric parse of an environment variable "
            f"({context.splitlines()[0].strip()[:60]}…) — use the "
            "validated env_int/env_float helpers "
            "(horovod_tpu.common.retry) so a garbled value warns and "
            "defaults instead of killing the process",
        ))


def _documented(root: str) -> Tuple[Set[str], List[str], str]:
    """(exact tokens, wildcard prefixes) mentioned in docs/running.md."""
    text = read_text(os.path.join(root, RUNNING_MD))
    if text is None:
        return set(), [], ""
    exact: Set[str] = set()
    wild: List[str] = []
    for m in _DOC_TOKEN_RE.finditer(text):
        if m.group(2):  # HVD_TPU_FOO_* family
            wild.append(m.group(1))
        else:
            exact.add(m.group(1))
    return exact, wild, text


def run(root: str) -> List[Finding]:
    findings: List[Finding] = []
    reads: Dict[str, List[Tuple[str, int]]] = {}
    for rel in iter_py_files(root):
        text = read_text(os.path.join(root, rel))
        if text is not None:
            _scan_python(rel, text, reads, findings)
    # tools/ scripts (benches, soaks) legitimize docs/running.md rows —
    # their reads are collected SEPARATELY so the package-hygiene
    # findings (undocumented read, raw parse) stay scoped to horovod_tpu/
    tool_reads: Dict[str, List[Tuple[str, int]]] = {}
    for rel in iter_py_files(root, subdir="tools"):
        text = read_text(os.path.join(root, rel))
        if text is not None:
            _scan_python(rel, text, tool_reads, [])
    for rel in iter_native_files(root):
        text = read_text(os.path.join(root, rel))
        if text is None:
            continue
        clean = _strip_comments(text, "c")
        for m in _NATIVE_READ_RE.finditer(clean):
            reads.setdefault(m.group(1), []).append(
                (rel, _lineno(clean, m.start())))

    exact, wild, doc_text = _documented(root)
    if not doc_text:
        findings.append(Finding(CHECK, RUNNING_MD, 0, "missing",
                                "docs/running.md not found — the env-var "
                                "registry has no documentation side"))
        return findings

    for var, sites in sorted(reads.items()):
        if var in exact or any(var.startswith(w) for w in wild):
            continue
        relfile, lineno = sites[0]
        findings.append(Finding(
            CHECK, relfile, lineno, var,
            f"{var} is read here but has no row in docs/running.md "
            "(every knob must be documented)",
        ))

    doc_lines = doc_text.splitlines()
    for var in sorted(exact):
        if var in reads or var in tool_reads:
            continue
        lineno = next((i for i, ln in enumerate(doc_lines, 1)
                       if var in ln), 0)
        findings.append(Finding(
            CHECK, RUNNING_MD, lineno, var,
            f"docs/running.md documents {var} but nothing in the "
            "package reads it (stale row, or the read uses an "
            "unrecognized spelling)",
        ))
    return findings
