"""C-API contract checker.

Parses the ``extern "C"`` function definitions in
``native/src/c_api.cc`` (name, arity, argument/return C types) and
cross-checks every ctypes ``restype``/``argtypes`` declaration in the
production binding (``native/controller.py``) and the ctypes test
harnesses.  Drift here is the silent-crash class this suite exists for:
a wrong ``argtypes`` list does not fail at import — ctypes happily
marshals garbage and corrupts the native stack at call time.

Rules:

* a binding to a symbol c_api.cc does not declare is an error (the
  load would AttributeError — or worse, hit a stale committed binary);
* ``argtypes`` arity must equal the C declaration's arity, and each
  position must map to the C parameter type;
* ``restype`` must map to the C return type;
* setting ``restype`` without ``argtypes`` is an error even for
  zero-argument functions — a bare binding accepts (and silently
  discards) arbitrary arguments, so arity drift goes unnoticed;
* in ``native/controller.py`` additionally: every declared C function
  must be bound (completeness — an unbound export is dead API).

``tools/rebuild_native.sh`` reuses :func:`declared_symbols` for its nm
export check, so the symbol list lives in exactly one parser.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, NamedTuple, Tuple

from ._common import (
    C_API_CC, CONTROLLER_PY, CTYPES_HARNESSES, Finding, read_text,
)

CHECK = "c-api"


class CFunc(NamedTuple):
    name: str
    ret: str            # normalized C return type
    args: Tuple[str, ...]  # normalized C parameter types
    line: int


_DEF_RE = re.compile(
    r"^(int|void|long long|double|unsigned long long|const char\s*\*)\s+"
    r"(hvdtpu_[a-z0-9_]+)\s*\(",
    re.MULTILINE,
)


def _normalize_ctype(raw: str) -> str:
    """``const  char *coord_host`` -> ``const char*`` (drop the
    parameter name, collapse whitespace, glue ``*`` to the type)."""
    s = raw.strip()
    if "(*" in s:
        return "funcptr"
    # drop a trailing identifier (the parameter name) if present
    m = re.match(r"^(.*?[\s*])([A-Za-z_]\w*)\s*$", s)
    if m and not m.group(1).strip() == "":
        s = m.group(1)
    s = re.sub(r"\s+", " ", s).strip()
    s = re.sub(r"\s*\*", "*", s)
    return s


def _split_top_level(argstr: str) -> List[str]:
    parts, depth, cur = [], 0, []
    for ch in argstr:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if "".join(cur).strip():
        parts.append("".join(cur))
    return parts


def parse_c_api(text: str) -> Dict[str, CFunc]:
    """Every ``hvdtpu_*`` function defined at column 0 in c_api.cc."""
    funcs: Dict[str, CFunc] = {}
    for m in _DEF_RE.finditer(text):
        ret = re.sub(r"\s*\*", "*", re.sub(r"\s+", " ", m.group(1))).strip()
        name = m.group(2)
        # scan the balanced parameter list starting at the open paren
        i = m.end() - 1
        depth, j = 0, i
        while j < len(text):
            if text[j] == "(":
                depth += 1
            elif text[j] == ")":
                depth -= 1
                if depth == 0:
                    break
            j += 1
        argstr = text[i + 1:j]
        parts = _split_top_level(argstr)
        if len(parts) == 1 and parts[0].strip() in ("", "void"):
            parts = []
        args = tuple(_normalize_ctype(p) for p in parts)
        line = text.count("\n", 0, m.start()) + 1
        funcs[name] = CFunc(name, ret, args, line)
    return funcs


def declared_symbols(root: str) -> List[str]:
    """Sorted hvdtpu_* symbol names declared in c_api.cc — the one
    source of truth rebuild_native.sh and the .so export checks use."""
    text = read_text(os.path.join(root, C_API_CC))
    if text is None:
        raise FileNotFoundError(os.path.join(root, C_API_CC))
    return sorted(parse_c_api(text))


# -- C type -> acceptable ctypes spellings ------------------------------------

ARG_ACCEPT: Dict[str, Tuple[str, ...]] = {
    "int": ("c_int",),
    "long long": ("c_longlong",),
    "unsigned long long": ("c_ulonglong",),
    "double": ("c_double",),
    "const char*": ("c_char_p",),
    # writable byte buffer: c_void_p is the established binding (numpy
    # .ctypes.data pointers), c_char_p would be immutable-leaning
    "char*": ("c_void_p", "c_char_p"),
    "void*": ("c_void_p",),
    "const void**": ("POINTER(c_void_p)",),
    "const int*": ("POINTER(c_int)",),
    "const long long*": ("POINTER(c_longlong)",),
    "const int64_t*": ("POINTER(c_int64)", "POINTER(c_longlong)"),
    "const char* const*": ("POINTER(c_char_p)",),
}

RET_ACCEPT: Dict[str, Tuple[str, ...]] = {
    "int": ("c_int",),
    "void": ("None",),
    "long long": ("c_longlong",),
    "double": ("c_double",),
    "unsigned long long": ("c_ulonglong",),
    "const char*": ("c_char_p",),
}

_IDENT_RE = re.compile(r"^[A-Za-z_]\w*$")


def _norm_py(token: str) -> str:
    return token.replace("ctypes.", "").replace(" ", "").replace("\n", "")


def _arg_ok(ctype: str, py: str) -> bool:
    # a function-pointer parameter is bound through a module-level
    # CFUNCTYPE object whose name we cannot resolve textually — accept
    # any plain identifier that is not a primitive ctypes spelling
    if ctype == "funcptr":
        return bool(_IDENT_RE.match(py)) and not py.startswith("c_")
    accept = ARG_ACCEPT.get(ctype)
    if accept is None:
        return False  # unknown C type: surfaced by the caller
    return py in accept


class Binding(NamedTuple):
    symbol: str
    # EVERY occurrence is kept and checked: the harnesses declare the
    # same symbol once per embedded ``python -c`` blob, and a
    # last-occurrence-wins scan would let drift in all but the final
    # blob ship silently
    restypes: List[Tuple[str, int]]        # (normalized value, line)
    argtypes: List[Tuple[List[str], int]]  # (normalized items, line)


_RESTYPE_RE = re.compile(r"\.(hvdtpu_[a-z0-9_]+)\.restype\s*=\s*([^\n#]+)")
_ARGTYPES_RE = re.compile(
    r"\.(hvdtpu_[a-z0-9_]+)\.argtypes\s*=\s*(\[[^\]]*\])", re.DOTALL
)


def scan_bindings(text: str) -> Dict[str, Binding]:
    """All ``<x>.hvdtpu_*.restype/argtypes`` assignments in one Python
    source file — including ones inside string-literal child programs
    (the ctypes harnesses embed their declarations in ``python -c``
    blobs), which is exactly why this is a textual scan, not an AST
    walk."""
    res: Dict[str, List[Tuple[str, int]]] = {}
    args: Dict[str, List[Tuple[List[str], int]]] = {}
    for m in _RESTYPE_RE.finditer(text):
        line = text.count("\n", 0, m.start()) + 1
        res.setdefault(m.group(1), []).append(
            (_norm_py(m.group(2).strip()), line))
    for m in _ARGTYPES_RE.finditer(text):
        line = text.count("\n", 0, m.start()) + 1
        inner = m.group(2)[1:-1]
        items = [_norm_py(p) for p in _split_top_level(inner)
                 if p.strip()]
        args.setdefault(m.group(1), []).append((items, line))
    return {
        sym: Binding(sym, res.get(sym, []), args.get(sym, []))
        for sym in sorted(set(res) | set(args))
    }


def _check_file(relfile: str, text: str, funcs: Dict[str, CFunc],
                require_complete: bool) -> List[Finding]:
    findings: List[Finding] = []
    bindings = scan_bindings(text)
    for sym, b in bindings.items():
        decl = funcs.get(sym)
        first_line = min(
            [ln for _, ln in b.restypes] + [ln for _, ln in b.argtypes])
        if decl is None:
            findings.append(Finding(
                CHECK, relfile, first_line, sym,
                f"ctypes binding to {sym} but c_api.cc declares no such "
                "function (stale binding or missing export)",
            ))
            continue
        for restype, line in b.restypes:
            accept = RET_ACCEPT.get(decl.ret, ())
            if restype not in accept:
                findings.append(Finding(
                    CHECK, relfile, line, sym,
                    f"{sym}.restype is {restype} but c_api.cc returns "
                    f"'{decl.ret}' (want one of {list(accept)})",
                ))
        if len(b.argtypes) < max(len(b.restypes), 1):
            findings.append(Finding(
                CHECK, relfile, b.restypes[0][1] if b.restypes
                else first_line, sym,
                f"{sym} is declared {max(len(b.restypes), 1)} time(s) "
                f"but carries only {len(b.argtypes)} argtypes "
                "declaration(s) — a bare binding accepts arbitrary "
                f"arguments; declare argtypes = "
                f"{'[]' if not decl.args else '[...]'} matching "
                f"c_api.cc:{decl.line} at every declaration site",
            ))
        if not b.restypes and b.argtypes and decl.ret != "int":
            # ctypes defaults a missing restype to c_int: fine for int
            # returns, silent truncation/garbage for anything else
            findings.append(Finding(
                CHECK, relfile, b.argtypes[0][1], sym,
                f"{sym} has argtypes but no restype; c_api.cc:"
                f"{decl.line} returns '{decl.ret}' and ctypes would "
                "default to c_int (truncated/garbage values)",
            ))
        for argtypes, line in b.argtypes:
            if len(argtypes) != len(decl.args):
                findings.append(Finding(
                    CHECK, relfile, line, sym,
                    f"{sym}.argtypes has {len(argtypes)} entries but "
                    f"c_api.cc:{decl.line} declares {len(decl.args)} "
                    "parameters (arity drift corrupts the call stack)",
                ))
                continue
            for i, (ctype, py) in enumerate(zip(decl.args, argtypes)):
                if ctype not in ARG_ACCEPT and ctype != "funcptr":
                    findings.append(Finding(
                        CHECK, relfile, line, sym,
                        f"{sym} parameter {i}: C type '{ctype}' is not "
                        "in the checker's type map (extend ARG_ACCEPT "
                        "in horovod_tpu/analysis/c_api.py)",
                    ))
                elif not _arg_ok(ctype, py):
                    findings.append(Finding(
                        CHECK, relfile, line, sym,
                        f"{sym}.argtypes[{i}] is {py} but c_api.cc:"
                        f"{decl.line} declares '{ctype}'",
                    ))
    if require_complete:
        for sym, decl in sorted(funcs.items()):
            if sym not in bindings:
                findings.append(Finding(
                    CHECK, relfile, 0, sym,
                    f"c_api.cc:{decl.line} exports {sym} but "
                    f"{relfile} never declares restype/argtypes for it",
                ))
    return findings


def run(root: str) -> List[Finding]:
    c_text = read_text(os.path.join(root, C_API_CC))
    if c_text is None:
        return [Finding(CHECK, C_API_CC, 0, "missing",
                        "c_api.cc not found — cannot check the contract")]
    funcs = parse_c_api(c_text)
    if not funcs:
        return [Finding(CHECK, C_API_CC, 0, "empty",
                        "no extern \"C\" hvdtpu_* definitions parsed from "
                        "c_api.cc (parser/style drift?)")]
    findings: List[Finding] = []
    ctrl = read_text(os.path.join(root, CONTROLLER_PY))
    if ctrl is None:
        findings.append(Finding(CHECK, CONTROLLER_PY, 0, "missing",
                                "native/controller.py not found"))
    else:
        findings += _check_file(CONTROLLER_PY, ctrl, funcs,
                                require_complete=True)
    for rel in CTYPES_HARNESSES:
        text = read_text(os.path.join(root, rel))
        if text is not None:
            findings += _check_file(rel, text, funcs,
                                    require_complete=False)
    return findings
