"""Metric catalogue lint.

Invariant (PR-1's stated contract, now machine-checked):

    metric names constructed in code  ⊆  instruments.py catalogue
                                       ⊆  docs/METRICS.md

* every ``counter("...")/gauge("...")/histogram("...")`` call with a
  literal name outside ``metrics/instruments.py`` is an undeclared
  metric — declare it in the catalogue so the name/labels/buckets live
  in one place;
* every catalogue name must appear in docs/METRICS.md;
* every ``hvd_tpu_*`` name METRICS.md mentions must exist in the
  catalogue (doc rot).

METRICS.md brace shorthand is understood:
``hvd_tpu_native_response_cache_{hits,misses}`` expands, a label set
``...seconds{phase}`` is stripped, and a trailing ``*`` makes a prefix
wildcard (``hvd_tpu_native_*``).
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Set, Tuple

from ._common import (
    Finding, INSTRUMENTS_PY, METRICS_MD, iter_py_files, read_text,
)

CHECK = "metrics"

_CTOR_RE = re.compile(
    r"\b(?:counter|gauge|histogram)\(\s*[\r\n]*\s*\"([a-z_][a-z0-9_]*)\""
)
_DOC_TOKEN_RE = re.compile(r"\bhvd_tpu_[a-z0-9_{},]*[a-z0-9_}]|\bhvd_tpu_[a-z0-9_]*_(?=\*)")

#: files whose constructor calls are the catalogue itself or harmless
#: (registry machinery, the package docstring example)
_EXEMPT = (
    "horovod_tpu/metrics/registry.py",
    "horovod_tpu/metrics/__init__.py",
    "horovod_tpu/metrics/instruments.py",
)


def catalogue(root: str) -> Tuple[Dict[str, int], str]:
    """name -> line of every instrument declared in instruments.py."""
    text = read_text(os.path.join(root, INSTRUMENTS_PY))
    if text is None:
        return {}, ""
    out: Dict[str, int] = {}
    for m in _CTOR_RE.finditer(text):
        out[m.group(1)] = text.count("\n", 0, m.start()) + 1
    return out, text


def _expand_doc_token(token: str) -> List[str]:
    m = re.search(r"\{([^}]*)\}", token)
    if not m:
        return [token]
    inner = m.group(1)
    if "," in inner:
        return [token[:m.start()] + alt + token[m.end():]
                for alt in inner.split(",")]
    return [token[:m.start()] + token[m.end():]]  # {label} annotation


def run(root: str) -> List[Finding]:
    findings: List[Finding] = []
    names, _ = catalogue(root)
    if not names:
        findings.append(Finding(
            CHECK, INSTRUMENTS_PY, 0, "missing",
            "metrics/instruments.py declares no instruments (or is "
            "missing) — the catalogue side of the contract is gone"))
        return findings

    # -- code ⊆ catalogue ----------------------------------------------------
    for rel in iter_py_files(root):
        norm = rel.replace(os.sep, "/")
        if norm in _EXEMPT:
            continue
        text = read_text(os.path.join(root, rel))
        if text is None:
            continue
        for m in _CTOR_RE.finditer(text):
            name = m.group(1)
            lineno = text.count("\n", 0, m.start()) + 1
            if name not in names:
                findings.append(Finding(
                    CHECK, rel, lineno, name,
                    f"metric {name!r} is constructed here but not "
                    "declared in metrics/instruments.py — move the "
                    "declaration into the catalogue",
                ))

    # -- catalogue ⊆ docs (and docs ⊆ catalogue) -----------------------------
    doc_text = read_text(os.path.join(root, METRICS_MD))
    if doc_text is None:
        findings.append(Finding(CHECK, METRICS_MD, 0, "missing",
                                "docs/METRICS.md not found"))
        return findings
    doc_exact: Set[str] = set()
    doc_prefixes: List[str] = []
    for m in _DOC_TOKEN_RE.finditer(doc_text):
        token = m.group(0)
        if doc_text[m.end():m.end() + 1] == "*":
            doc_prefixes.append(token)
            continue
        for expanded in _expand_doc_token(token):
            doc_exact.add(expanded)

    for name, lineno in sorted(names.items()):
        if name in doc_exact or any(name.startswith(p)
                                    for p in doc_prefixes):
            continue
        findings.append(Finding(
            CHECK, INSTRUMENTS_PY, lineno, name,
            f"metric {name!r} is in the catalogue but docs/METRICS.md "
            "never mentions it — add a catalogue row",
        ))

    doc_lines = doc_text.splitlines()
    for name in sorted(doc_exact):
        if name in names:
            continue
        # tolerate documented sub-series of declared histograms/counters
        if any(name.startswith(base) and name[len(base):] in
               ("_sum", "_count", "_bucket", "_total")
               for base in names):
            continue
        lineno = next((i for i, ln in enumerate(doc_lines, 1)
                       if name in ln), 0)
        findings.append(Finding(
            CHECK, METRICS_MD, lineno, name,
            f"docs/METRICS.md mentions {name!r} but the catalogue "
            "(metrics/instruments.py) does not declare it (stale doc "
            "or renamed metric)",
        ))
    return findings
