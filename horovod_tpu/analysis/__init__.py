"""Cross-layer contract checker (static analysis) for horovod_tpu.

The framework spans four hand-synchronized layers — the C exports in
``native/src/c_api.cc``, the ctypes bindings in
``native/controller.py``, the metric catalogue in
``metrics/instruments.py``, and the env-var / chaos-site / doc
registries.  Drift between them is a *silent-crash* class: a wrong
``argtypes`` corrupts the native stack at call time, an uncatalogued
chaos site is a fault rule that never fires, an undocumented knob is a
knob nobody finds.  This package checks all of it in milliseconds with
stdlib-only passes — seven bare-box AST/regex passes plus one
jax-gated program verifier:

=========== =====================================================
pass        contract
=========== =====================================================
c-api       c_api.cc declarations == every ctypes restype/argtypes
env         HVD_TPU_* reads == docs/running.md rows; no raw parses
metrics     code-built names ⊆ instruments.py ⊆ docs/METRICS.md
chaos       point() sites == native Decide sites == doc site table
trace       span/event sites == trace SITES == docs/TRACING.md
locks       lock-order acyclic; no mixed guarded/unguarded writes
collectives no rank-gated collectives; raw lax.p* only in ops//parallel/
programs    lowered-program invariants (jax; HVD_TPU_VERIFY_PROGRAMS=1)
=========== =====================================================

Run it::

    python -m horovod_tpu.analysis          # from an installed tree
    python tools/check.py                   # bare box, no jax needed

Never imports the framework — safe (and fast) on a box with nothing
but a Python interpreter.  See docs/ANALYSIS.md for the suppression
syntax and the sanitizer build modes that ship alongside this suite.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence

from . import (c_api, chaos_sites, collectives, envvars, locks,
               metrics_catalogue, programs, trace_sites)
from ._common import Finding, Suppressions

__all__ = ["Finding", "PASSES", "run_all", "main"]

PASSES: Dict[str, Callable[[str], List[Finding]]] = {
    "c-api": c_api.run,
    "env": envvars.run,
    "metrics": metrics_catalogue.run,
    "chaos": chaos_sites.run,
    "trace": trace_sites.run,
    "locks": locks.run,
    "collectives": collectives.run,
    "programs": programs.run,
}


def run_all(root: str, checks: Optional[Sequence[str]] = None,
            suppress: bool = True) -> List[Finding]:
    """Run the selected passes (default: all) against ``root`` and
    return the surviving findings, allowlists applied."""
    selected = list(checks) if checks else list(PASSES)
    unknown = [c for c in selected if c not in PASSES]
    if unknown:
        raise ValueError(f"unknown pass(es) {unknown}; have {list(PASSES)}")
    findings: List[Finding] = []
    for name in selected:
        findings.extend(PASSES[name](root))
    if not suppress:
        return findings
    sup = Suppressions(root)
    out = sup.filter(findings)
    out.extend(sup.extra_findings)
    if not checks:  # stale-entry audit only makes sense on a full run
        out.extend(sup.stale_entries())
    return sorted(out, key=lambda f: (f.file, f.line, f.check, f.key))


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis",
        description="horovod_tpu cross-layer contract checker",
    )
    parser.add_argument("checks", nargs="*",
                        help=f"passes to run (default all): {list(PASSES)}")
    parser.add_argument("--root", default=None,
                        help="repo root (default: derived from this file)")
    parser.add_argument("--list-c-symbols", action="store_true",
                        help="print the hvdtpu_* symbols declared in "
                        "c_api.cc, one per line, and exit (consumed by "
                        "tools/rebuild_native.sh)")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="findings only, no summary line")
    args = parser.parse_args(argv)

    root = args.root
    if root is None:
        import os
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))

    if args.list_c_symbols:
        for sym in c_api.declared_symbols(root):
            print(sym)
        return 0

    t0 = time.perf_counter()
    try:
        findings = run_all(root, args.checks or None)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    for f in findings:
        print(f.render())
    if not args.quiet:
        n = len(args.checks or PASSES)
        dt = time.perf_counter() - t0
        verdict = (f"{len(findings)} finding(s)" if findings
                   else "all contracts hold")
        print(f"horovod_tpu.analysis: {n} pass(es), {verdict} "
              f"({dt * 1000:.0f} ms)", file=sys.stderr)
    return 1 if findings else 0
