"""Host-side lock-discipline lint (pass #6, ``locks``).

The host side of the framework runs ~a dozen concurrent threads — the
fleet router's replica steppers, the autoscaler, heartbeats, device
prefetchers, the preemption guard, the metrics registry — and their
lock discipline was, until this pass, enforced only by review.  The
two failure classes this pass machine-checks are the classic ones:

* **lock-order inversion** — thread 1 acquires A then B, thread 2
  acquires B then A: a deadlock that only fires under contention.  The
  pass builds a lock-acquisition graph per module (``with self._lock:``
  scopes, plus nested acquisitions reached through one level of
  same-class method calls) and reports every cycle.
* **unguarded shared state** — in a class that spawns threads, an
  attribute written both under and outside a lock (inconsistent
  discipline: the unguarded write races the guarded readers), and a
  ``threading.Thread`` target mutating attributes no lock protects
  while other methods also write them (write/write race).

Everything is stdlib-``ast``; ``__init__`` writes are construction-time
and never counted.  The analysis is intentionally per-class /
per-module — cross-object inversions (A's lock held across a call into
B) are out of static reach here and belong to the TSan CI leg, which
this pass complements, not replaces.  Suppress a justified finding
with ``contract-ok: locks -- <why>`` (single-threaded-use invariants
must be named in the justification; docs/ANALYSIS.md).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from ._common import Finding, iter_py_files, read_text

CHECK = "locks"

#: threading factories whose instances define a guard scope.
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
#: call names that mark a class as spawning concurrency.
_THREAD_FACTORIES = {"Thread", "Timer", "ThreadPoolExecutor",
                     "start_new_thread"}


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of a call target / attribute chain."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif isinstance(cur, ast.Call):
        parts.append(_dotted(cur.func) + "()")
    return ".".join(reversed(parts))


def _self_attr(node: ast.AST) -> Optional[str]:
    """``attr`` when the node is ``self.attr``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _write_target_attr(target: ast.AST) -> Optional[str]:
    """The ``self`` attribute a store target mutates: ``self.x = ...``,
    ``self.x[k] = ...``, ``self.x += ...`` all write ``x``."""
    a = _self_attr(target)
    if a is not None:
        return a
    if isinstance(target, ast.Subscript):
        return _self_attr(target.value)
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            a = _write_target_attr(elt)
            if a is not None:
                return a
    return None


class _MethodScan(ast.NodeVisitor):
    """One method's lock-relevant events.

    ``acquires``: (lock, line, frozenset(held-before)) per ``with``
    item that takes a known lock.  ``writes``: (attr, line,
    held-nonempty) per ``self``-attribute store.  ``calls``: (method,
    line, frozenset(held)) per ``self.m(...)`` call.  ``spawns``:
    thread-target method names passed to a thread factory.
    """

    def __init__(self, lock_names: Set[str], module_locks: Set[str]):
        self.lock_names = lock_names
        self.module_locks = module_locks
        self.acquires: List[Tuple[str, int, frozenset]] = []
        self.writes: List[Tuple[str, int, bool]] = []
        self.calls: List[Tuple[str, int, frozenset]] = []
        self.spawns: List[str] = []
        self._held: Tuple[str, ...] = ()

    # -- lock identification -------------------------------------------------

    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        a = _self_attr(expr)
        if a is not None and a in self.lock_names:
            return a
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return expr.id
        return None

    # -- visitors ------------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        entered: List[str] = []
        for item in node.items:
            lock = self._lock_of(item.context_expr)
            if lock is not None:
                self.acquires.append(
                    (lock, item.context_expr.lineno,
                     frozenset(self._held + tuple(entered))))
                entered.append(lock)
        self._held = self._held + tuple(entered)
        for stmt in node.body:
            self.visit(stmt)
        if entered:
            self._held = self._held[: len(self._held) - len(entered)]

    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        terminal = name.rsplit(".", 1)[-1]
        # explicit .acquire() counts as an acquisition event (no scope)
        if terminal == "acquire":
            lock = self._lock_of(getattr(node.func, "value", None))
            if lock is not None:
                self.acquires.append(
                    (lock, node.lineno, frozenset(self._held)))
        if terminal in _THREAD_FACTORIES:
            for kw in node.keywords:
                if kw.arg == "target":
                    tgt = _self_attr(kw.value)
                    if tgt is not None:
                        self.spawns.append(tgt)
            # submit(self.m) style targets ride the positional args too
            for arg in node.args:
                tgt = _self_attr(arg)
                if tgt is not None:
                    self.spawns.append(tgt)
        method = _self_attr(node.func)
        if method is not None:
            self.calls.append((method, node.lineno, frozenset(self._held)))
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            attr = _write_target_attr(t)
            if attr is not None:
                self.writes.append((attr, node.lineno, bool(self._held)))
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = _write_target_attr(node.target)
        if attr is not None:
            self.writes.append((attr, node.lineno, bool(self._held)))
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            attr = _write_target_attr(node.target)
            if attr is not None:
                self.writes.append((attr, node.lineno, bool(self._held)))
        self.generic_visit(node)

    # nested defs/lambdas run later (often on another thread); their
    # bodies are scanned as separate contexts by the class walker, so
    # don't double-visit them under the current held set
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        pass


def _class_lock_names(cls: ast.ClassDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        if _dotted(node.value.func).rsplit(".", 1)[-1] in _LOCK_FACTORIES:
            for t in node.targets:
                attr = _write_target_attr(t)
                if attr is not None:
                    out.add(attr)
    return out


def _module_lock_names(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _dotted(node.value.func).rsplit(".", 1)[-1]
                in _LOCK_FACTORIES):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _methods(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _cycles(edges: Dict[str, Dict[str, int]]) -> List[Tuple[Tuple[str, ...],
                                                            int]]:
    """Elementary cycles of the acquisition digraph (DFS; the graphs
    here are a handful of nodes).  Returns (canonical node tuple, line
    of one participating edge) per distinct cycle."""
    seen: Set[Tuple[str, ...]] = set()
    out: List[Tuple[Tuple[str, ...], int]] = []

    def dfs(start: str, node: str, path: List[str]) -> None:
        for nxt, line in sorted(edges.get(node, {}).items()):
            if nxt == start:
                cyc = path + [node]
                rot = min(range(len(cyc)),
                          key=lambda i: tuple(cyc[i:] + cyc[:i]))
                canon = tuple(cyc[rot:] + cyc[:rot])
                if canon not in seen:
                    seen.add(canon)
                    out.append((canon, line))
            elif nxt not in path and nxt != node and nxt > start:
                # only walk nodes > start so each cycle is found from
                # its smallest node exactly once
                dfs(start, nxt, path + [node])

    for start in sorted(edges):
        dfs(start, start, [])
    return out


def _scan_class(rel: str, cls: ast.ClassDef, module_locks: Set[str],
                findings: List[Finding],
                edge_out: Dict[str, Dict[str, int]]) -> None:
    locks = _class_lock_names(cls)
    methods = _methods(cls)
    scans: Dict[str, _MethodScan] = {}
    for name, fn in methods.items():
        scan = _MethodScan(locks, module_locks)
        for stmt in fn.body:
            scan.visit(stmt)
        scans[name] = scan

    def qual(lock: str) -> str:
        return f"{cls.name}.{lock}" if lock in locks else lock

    # -- acquisition graph (order-inversion edges) ---------------------------
    for name, scan in scans.items():
        for lock, line, held in scan.acquires:
            for h in held:
                if h != lock:
                    edge_out.setdefault(qual(h), {}).setdefault(
                        qual(lock), line)
        for callee, line, held in scan.calls:
            if not held or callee not in scans:
                continue
            for lock, _line, _h in scans[callee].acquires:
                for h in held:
                    if h != lock:
                        edge_out.setdefault(qual(h), {}).setdefault(
                            qual(lock), line)

    # -- shared-state discipline (threaded classes only) ---------------------
    spawns: List[str] = []
    for scan in scans.values():
        spawns.extend(scan.spawns)
    if not spawns:
        return
    # writes per attr, construction (__init__) excluded
    guarded: Dict[str, int] = {}
    unguarded: Dict[str, int] = {}
    writers: Dict[str, Set[str]] = {}
    for name, scan in scans.items():
        if name == "__init__":
            continue
        for attr, line, held in scan.writes:
            if attr in locks:
                continue
            writers.setdefault(attr, set()).add(name)
            if held:
                guarded.setdefault(attr, line)
            else:
                unguarded.setdefault(attr, line)
    flagged: Set[str] = set()
    for attr in sorted(set(guarded) & set(unguarded)):
        flagged.add(attr)
        findings.append(Finding(
            CHECK, rel, unguarded[attr], f"{cls.name}.{attr}",
            f"{cls.name}.{attr} is written both under a lock (line "
            f"{guarded[attr]}) and outside one (here) in a class that "
            "spawns threads — the unguarded write races every guarded "
            "reader; take the lock or name the single-threaded-use "
            "invariant in a contract-ok justification",
        ))
    if not locks:
        return
    # thread targets mutating attrs other methods also write, no lock
    thread_methods = {m for m in spawns if m in scans}
    for m in sorted(thread_methods):
        for attr, line, held in scans[m].writes:
            if held or attr in locks or attr in flagged:
                continue
            others = writers.get(attr, set()) - {m}
            if not others:
                continue
            flagged.add(attr)
            findings.append(Finding(
                CHECK, rel, line, f"{cls.name}.{attr}",
                f"thread target {cls.name}.{m} writes {attr!r} with no "
                f"lock held while {sorted(others)[0]} also writes it — "
                "a write/write race across threads; guard both sides "
                "with one of the class's locks",
            ))


def run(root: str) -> List[Finding]:
    findings: List[Finding] = []
    for rel in iter_py_files(root):
        text = read_text(os.path.join(root, rel))
        if text is None:
            continue
        try:
            tree = ast.parse(text)
        except SyntaxError as e:
            findings.append(Finding(
                CHECK, rel, e.lineno or 0, "syntax",
                f"unparseable module: {e.msg}"))
            continue
        module_locks = _module_lock_names(tree)
        edges: Dict[str, Dict[str, int]] = {}
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                _scan_class(rel, node, module_locks, findings, edges)
        # module-level functions can nest module locks too
        mod_scan = _MethodScan(set(), module_locks)
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for stmt in node.body:
                    mod_scan.visit(stmt)
        for lock, line, held in mod_scan.acquires:
            for h in held:
                if h != lock:
                    edges.setdefault(h, {}).setdefault(lock, line)
        for cyc, line in _cycles(edges):
            key = "->".join(cyc + (cyc[0],))
            findings.append(Finding(
                CHECK, rel, line, key,
                f"lock-order inversion: acquisition cycle {key} — two "
                "threads taking these locks in opposite order deadlock "
                "under contention; pick one global order",
            ))
    return findings
