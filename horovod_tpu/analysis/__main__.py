"""``python -m horovod_tpu.analysis`` — run the contract checker.

Note: the ``-m`` spelling imports the full ``horovod_tpu`` package (and
therefore jax) before this module runs; on a bare box or in the CI lint
job use ``python tools/check.py``, which loads the analysis package
standalone in milliseconds.
"""

import sys

from . import main

sys.exit(main())
