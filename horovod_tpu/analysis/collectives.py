"""Collective-discipline lint (pass #7, ``collectives``).

Two classes of drift that deadlock or silently mis-account a
distributed program, both checkable from the AST:

* **rank-gated collective** — a collective call (``allreduce*``,
  ``allgather*``, ``psum``, ``ppermute``, ``all_to_all``, ...) inside
  control flow conditioned on the caller's rank / process identity.
  Collectives are rendezvous points: if rank 0 takes the branch and
  rank 1 does not, the fleet hangs at the next matched call — the
  classic mismatched-collective deadlock, invisible until the branch
  actually diverges.  Branching on *world size* is fine (every rank
  agrees on it); branching on *rank* is not.
* **raw lax collective outside ops//parallel/** — ``jax.lax.psum`` and
  friends called directly from other layers bypass the public API's
  reduction-op semantics, hierarchical routing, and byte accounting
  (``ops/comm_model``'s modeled == measured discipline assumes the
  ``ops``/``parallel`` entry points are the only collective authors).

Suppress a justified exception with ``contract-ok: collectives --
<why>`` (docs/ANALYSIS.md); a legitimate rank branch must explain why
every rank still reaches a matched call.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Set

from ._common import Finding, iter_py_files, read_text

CHECK = "collectives"

#: directories whose modules ARE the public collective layer.
_COLLECTIVE_LAYERS = ("horovod_tpu/ops/", "horovod_tpu/parallel/")

#: terminal call names that are collective rendezvous points.
_COLLECTIVE_PREFIXES = (
    "allreduce", "allgather", "alltoall", "all_to_all", "reducescatter",
    "reduce_scatter", "hierarchical_allreduce", "grouped_allreduce",
)
_COLLECTIVE_NAMES = {
    "psum", "psum_scatter", "pmean", "pmax", "pmin", "ppermute",
    "pbroadcast", "all_gather", "broadcast", "barrier",
}
#: non-collective lookalikes the prefix match must not trip on.
_FALSE_FRIENDS = {
    "broadcast_to", "broadcast_arrays", "broadcast_shapes",
    "broadcast_in_dim", "barrier_wait",
}

#: lax primitives only ops//parallel/ may author.
_LAX_COLLECTIVES = {
    "psum", "psum_scatter", "pmean", "pmax", "pmin", "ppermute",
    "all_gather", "all_to_all", "pbroadcast",
}

#: identifiers whose value diverges per rank — branching on them gates
#: the branch body per rank.
_RANK_TOKENS = {
    "rank", "local_rank", "node_rank", "cross_rank", "cross_size_rank",
    "process_index", "process_id", "rank_id", "my_rank", "worker_index",
    "task_index",
}


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    return ".".join(reversed(parts))


def _is_collective_call(name: str) -> bool:
    terminal = name.rsplit(".", 1)[-1]
    if terminal in _FALSE_FRIENDS:
        return False
    return (terminal in _COLLECTIVE_NAMES
            or terminal.startswith(_COLLECTIVE_PREFIXES))


def _rank_token_in(test: ast.AST) -> Optional[str]:
    """The first rank-valued identifier the branch condition reads."""
    for node in ast.walk(test):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name is not None and name.lstrip("_") in _RANK_TOKENS:
            return name
    return None


class _Scan(ast.NodeVisitor):
    def __init__(self, rel: str, findings: List[Finding]):
        self.rel = rel
        self.findings = findings
        self.in_layer = rel.startswith(_COLLECTIVE_LAYERS)
        self._rank_gate: List[str] = []

    def _visit_gated(self, node: ast.stmt, bodies) -> None:
        token = _rank_token_in(node.test)
        if token is None:
            self.visit(node.test)
            for body in bodies:
                for stmt in body:
                    self.visit(stmt)
            return
        self.visit(node.test)
        self._rank_gate.append(token)
        for body in bodies:
            for stmt in body:
                self.visit(stmt)
        self._rank_gate.pop()

    def visit_If(self, node: ast.If) -> None:
        # both arms diverge per rank: the else of `if rank() == 0` is
        # exactly as rank-conditional as the body
        self._visit_gated(node, (node.body, node.orelse))

    def visit_While(self, node: ast.While) -> None:
        self._visit_gated(node, (node.body, node.orelse))

    def visit_IfExp(self, node: ast.IfExp) -> None:
        token = _rank_token_in(node.test)
        if token is None:
            self.generic_visit(node)
            return
        self.visit(node.test)
        self._rank_gate.append(token)
        self.visit(node.body)
        self.visit(node.orelse)
        self._rank_gate.pop()

    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        terminal = name.rsplit(".", 1)[-1]
        if self._rank_gate and _is_collective_call(name):
            self.findings.append(Finding(
                CHECK, self.rel, node.lineno, terminal,
                f"collective {terminal!r} under rank-conditional control "
                f"flow (branch tests {self._rank_gate[-1]!r}): ranks that "
                "skip the branch never reach the rendezvous — the "
                "mismatched-collective deadlock; hoist the call out of "
                "the branch or mask its inputs instead",
            ))
        parent = name.rsplit(".", 2)
        if (not self.in_layer
                and terminal in _LAX_COLLECTIVES
                and len(parent) >= 2 and parent[-2] == "lax"):
            self.findings.append(Finding(
                CHECK, self.rel, node.lineno, f"lax.{terminal}",
                f"raw lax.{terminal} outside ops//parallel/ bypasses the "
                "public collective API (reduce-op semantics, hierarchical "
                "routing, comm_model byte accounting) — call the "
                "horovod_tpu.ops spelling instead",
            ))
        self.generic_visit(node)


def run(root: str) -> List[Finding]:
    findings: List[Finding] = []
    for rel in iter_py_files(root):
        text = read_text(os.path.join(root, rel))
        if text is None:
            continue
        try:
            tree = ast.parse(text)
        except SyntaxError as e:
            findings.append(Finding(
                CHECK, rel, e.lineno or 0, "syntax",
                f"unparseable module: {e.msg}"))
            continue
        _Scan(rel, findings).visit(tree)
    return findings
