"""Lowered-program contract verifier (pass #8, ``programs``).

The other seven passes read SOURCE; this one reads the PROGRAMS — the
StableHLO modules the framework actually dispatches — and machine-checks
the invariants the docs promise in prose:

* **zero-added-collectives** — the integrity guard and the tracer are
  pure observers: ``guard=False`` vs ``HVD_TPU_GUARD=0`` lowers
  byte-identical, ``guard=True`` and trace on/off add exactly 0
  collective instructions (docs/FAULT_TOLERANCE.md, docs/TRACING.md).
* **serving DCN-exclusion** — no collective of any serving step program
  (decode / mixed / speculative, every tier) carries a replica group
  spanning >1 slice: the token loop never touches DCN
  (docs/SERVING.md sharding section).
* **modeled == measured** — ``ops/comm_model``'s modeled per-tier bytes
  equal the lowered module's collective inventory, per tier program and
  for the hierarchical allreduce (docs/COLLECTIVES.md).
* **zero-recompile** — under a randomized request load, every program
  key the engine dispatches is in the warmup menu: the tier product is
  the whole compiled set, no mid-traffic XLA compile ever
  (docs/SERVING.md menu contract).
* **overlap interleave** — the overlapped train step's collectives are
  scheduled between segment computations, not all trailing
  (docs/tensor-fusion.md).

Unlike the bare-box passes this one needs jax, so it is GATED: inside
``run_all``/``tools/check.py`` it reports nothing unless
``HVD_TPU_VERIFY_PROGRAMS=1`` is set (and jax imports).  The heavy path
has two front doors — ``tools/verify_programs.py`` (its own CI job) and
the ``analysis``-marked tests in tests/test_program_contracts.py.  The
check helpers themselves are dependency-light (regex + comm_model's
numpy parser) so the self-tests can feed them synthetic drift.

Suppression: same machinery as every pass (``contract-ok: programs --
<why>`` has nowhere to live in generated text, so use the allowlist
file with the finding's key).
"""

from __future__ import annotations

import hashlib
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ._common import Finding

CHECK = "programs"

#: env gate: the jax-requiring verification only runs when this is "1"
#: (tools/verify_programs.py and the analysis-marked tests set it).
ENV_GATE = "HVD_TPU_VERIFY_PROGRAMS"

ENGINE_PY = "horovod_tpu/serving/engine.py"
TRAINING_PY = "horovod_tpu/training.py"
SPMD_OPS_PY = "horovod_tpu/ops/spmd_ops.py"

_COLLECTIVE_RE = re.compile(
    r"stablehlo\.(all_reduce|all_gather|reduce_scatter|"
    r"collective_permute|all_to_all)")


def collective_count(lowered_text: str) -> int:
    """Collective instructions in one lowered (StableHLO) module."""
    return len(_COLLECTIVE_RE.findall(lowered_text))


def _digest(text: str) -> str:
    return hashlib.sha256(text.encode()).hexdigest()


# -- pure check helpers (synthetic-testable without jax) ---------------------


def check_byte_identical(name: str, baseline: str, candidate: str,
                         file: str = TRAINING_PY) -> List[Finding]:
    """The strongest no-op claim: the two lowered modules are the SAME
    bytes (the guard_bench/trace_bench sha256 idiom)."""
    if _digest(baseline) == _digest(candidate):
        return []
    added = collective_count(candidate) - collective_count(baseline)
    return [Finding(
        CHECK, file, 0, f"byte-identical:{name}",
        f"{name}: lowered programs differ (sha256 mismatch, "
        f"{added:+d} collective(s)) — the no-op path must lower "
        "byte-identical to the baseline",
    )]


def check_added_collectives(name: str, baseline: str, candidate: str,
                            budget: int = 0,
                            file: str = TRAINING_PY) -> List[Finding]:
    """The candidate program may add at most ``budget`` (default 0)
    collective instructions over the baseline."""
    added = collective_count(candidate) - collective_count(baseline)
    if added <= budget:
        return []
    return [Finding(
        CHECK, file, 0, f"added-collectives:{name}",
        f"{name}: {added} collective(s) added over the baseline "
        f"(budget {budget}) — observers must not grow the collective "
        "inventory (the exchange rides the host control plane)",
    )]


def check_dcn_exclusion(name: str, lowered_text: str,
                        slice_ids: Sequence[int],
                        file: str = ENGINE_PY) -> List[Finding]:
    """No collective replica group of a serving program may span >1
    slice of ``slice_ids`` — DCN stays out of the token loop."""
    from ..ops.comm_model import measured_tier_bytes

    out: List[Finding] = []
    inv = measured_tier_bytes(lowered_text, slice_ids)
    for op in inv["ops"]:
        if op["tier"] == "dcn":
            out.append(Finding(
                CHECK, file, 0, f"serve-dcn:{name}:{op['op']}",
                f"{name}: {op['op']} (payload {op['payload_bytes']} B, "
                f"group size {op['group_size']}) spans >1 slice — a "
                "serving step collective crossed onto DCN; the token "
                "loop must stay inside one ICI slice "
                "(docs/SERVING.md)",
            ))
    return out


def check_menu_keys(name: str, warmed: Iterable[tuple],
                    dispatched: Iterable[tuple],
                    file: str = ENGINE_PY) -> List[Finding]:
    """Every program key dispatched under load must be in the warmup
    menu — an off-menu key is a mid-traffic XLA compile."""
    extra = sorted(set(dispatched) - set(warmed), key=repr)
    return [Finding(
        CHECK, file, 0, f"off-menu:{name}:{'-'.join(map(str, key))}",
        f"{name}: program key {key!r} dispatched but never warmed — a "
        "mid-traffic compile (multi-second p99 spike); the tier menu "
        "must cover every reachable (kind, tier...) combination",
    ) for key in extra]


def check_modeled_measured(name: str, modeled: Dict[str, int],
                           measured: Dict[str, int],
                           file: str = SPMD_OPS_PY) -> List[Finding]:
    """Per-tier modeled bytes must equal the lowered inventory, key by
    key (keys present in ``modeled`` are compared)."""
    out: List[Finding] = []
    for tier, want in modeled.items():
        got = measured.get(tier)
        if got != want:
            out.append(Finding(
                CHECK, file, 0, f"model-mismatch:{name}:{tier}",
                f"{name}: modeled {tier} = {want} B but the lowered "
                f"program measures {got} B — comm_model and the "
                "compiled collective inventory disagree "
                "(docs/COLLECTIVES.md byte model)",
            ))
    return out


# -- the PASSES entry --------------------------------------------------------


def run(root: str) -> List[Finding]:
    """Gated: bare boxes (tools/check.py, the <10s lint job) see an
    empty pass; ``HVD_TPU_VERIFY_PROGRAMS=1`` + importable jax runs the
    full program verification."""
    if os.environ.get(ENV_GATE, "") != "1":
        return []
    try:
        import jax  # noqa: F401
    except Exception:
        return [Finding(
            CHECK, "pyproject.toml", 0, "no-jax",
            f"{ENV_GATE}=1 but jax is not importable — run this pass "
            "from an environment with the framework installed "
            "(tools/verify_programs.py)",
        )]
    return verify(root)


# -- the jax-requiring verification ------------------------------------------


def _serve_load(rs, n: int, max_seq_len: int) -> List[Tuple[list, int]]:
    """Randomized (prompt, max_new_tokens) pairs with a templated
    prefix mix (prefix-cache hits AND misses both exercised)."""
    templates = [list(rs.randint(1, 100, size=rs.randint(4, 20)))
                 for _ in range(4)]
    load = []
    for _ in range(n):
        head = templates[rs.randint(len(templates))] if rs.rand() < 0.5 \
            else []
        tail = list(rs.randint(1, 100, size=rs.randint(2, 12)))
        prompt = (head + tail)[:max_seq_len // 2]
        gen = int(rs.randint(1, 9))
        load.append((prompt, gen))
    return load


def _drive(eng, load) -> None:
    import numpy as np

    ids = [eng.submit(np.asarray(p, np.int32), max_new_tokens=g)
           for p, g in load]
    eng.run()
    assert all(r in eng.results for r in ids)


def _verify_serving(shards_list: Sequence[int], requests: int,
                    seed: int) -> List[Finding]:
    """Engines per shard count (+ one speculative): warmup the whole
    menu, inventory every program family's lowering (DCN-exclusion +
    modeled == measured psum stream), then the zero-recompile lint
    under the randomized load."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models.transformer import TransformerConfig
    from ..ops.comm_model import (measured_tier_bytes,
                                  modeled_serve_psum_bytes)
    from ..serving import ServeConfig, ServingEngine

    findings: List[Finding] = []
    # virtual 2-slice split of the 8-device world: the deployment
    # mapping DCN-exclusion is checked against (a serving mesh only
    # ever takes one slice's chips, so any group crossing the split
    # is a real violation)
    n_dev = jax.device_count()
    world_slices = [d // max(n_dev // 2, 1) for d in range(n_dev)]

    kv = max(2, max(shards_list))
    cfg = TransformerConfig(
        vocab_size=128, num_layers=2, num_heads=2 * kv, num_kv_heads=kv,
        head_dim=16, max_seq_len=96, dtype=jnp.float32,
        attention_impl="dot", causal=True)
    serve = dict(block_size=8, num_blocks=0, token_budget=256,
                 watermark=2, prefill_tiers=(32,), decode_tiers=(1, 2, 4),
                 prefill_chunk=8)
    from ..models.transformer import Transformer
    params = Transformer(cfg).init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32),
        train=False)["params"]

    legs: List[Tuple[str, ServeConfig, int]] = []
    for s in shards_list:
        legs.append((f"shards{s}", ServeConfig(shards=s, **serve),
                     requests if s == min(shards_list)
                     else max(requests // 4, 16)))
    legs.append(("spec", ServeConfig(spec=True, spec_k=3, **serve),
                 max(requests // 4, 16)))

    for name, scfg, n_req in legs:
        eng = ServingEngine(cfg, params, serve=scfg)
        eng.warmup()
        warmed = set(eng._progs)
        # every program FAMILY's lowering: DCN-exclusion + modeled ==
        # measured psum stream, per tier the engine can dispatch
        for bt in eng.decode_tiers:
            pt = eng.page_tiers[0]
            txt = eng.lowered_decode_text(batch_tier=bt, pages=pt)
            findings += check_dcn_exclusion(
                f"{name}:decode:b{bt}:p{pt}", txt, world_slices)
            modeled = modeled_serve_psum_bytes(
                bt, 1, cfg.d_model, cfg.num_layers, eng.shards,
                "float32")
            measured = measured_tier_bytes(txt, [0] * max(eng.shards, 1))
            findings += check_modeled_measured(
                f"{name}:decode:b{bt}", {"ici": modeled["stream_bytes"]},
                {"ici": measured["ici_bytes"]}, file=ENGINE_PY)
            for c in eng.chunk_tiers:
                mtxt = eng.lowered_mixed_text(batch_tier=bt, chunk_tier=c)
                findings += check_dcn_exclusion(
                    f"{name}:mixed:b{bt}:c{c}", mtxt, world_slices)
                mmod = modeled_serve_psum_bytes(
                    bt, c, cfg.d_model, cfg.num_layers, eng.shards,
                    "float32")
                mmeas = measured_tier_bytes(mtxt,
                                            [0] * max(eng.shards, 1))
                findings += check_modeled_measured(
                    f"{name}:mixed:b{bt}:c{c}",
                    {"ici": mmod["stream_bytes"]},
                    {"ici": mmeas["ici_bytes"]}, file=ENGINE_PY)
            if eng.spec_w:
                stxt = eng.lowered_mixed_text(
                    batch_tier=bt, chunk_tier=eng.spec_w,
                    pages=eng.page_tiers[0])
                findings += check_dcn_exclusion(
                    f"{name}:spec:b{bt}:w{eng.spec_w}", stxt,
                    world_slices)
        # zero-recompile lint: the randomized load must dispatch only
        # warmed keys (and actually compile nothing new)
        rs = np.random.RandomState(seed + len(name))
        _drive(eng, _serve_load(rs, n_req, cfg.max_seq_len))
        findings += check_menu_keys(name, warmed, set(eng._progs))
        if eng.program_count != len(warmed):
            findings.append(Finding(
                CHECK, ENGINE_PY, 0, f"recompile:{name}",
                f"{name}: program_count grew {len(warmed)} -> "
                f"{eng.program_count} under load — a mid-traffic "
                "compile slipped past the menu",
            ))
    return findings


def _verify_training() -> List[Finding]:
    """Guard/trace byte-identity, zero-added-collectives (plain and
    ZeRO steps), and the overlap interleave shape — all on lowered
    text, no execution."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from .. import trace
    from ..models.transformer import Transformer, TransformerConfig
    from ..ops.comm_model import overlap_inventory
    from .. import training

    findings: List[Finding] = []
    cfg = TransformerConfig(
        vocab_size=64, num_layers=2, num_heads=4, head_dim=8,
        max_seq_len=16, dtype=jnp.float32, attention_impl="dot",
        causal=True)
    model = Transformer(cfg)
    world = jax.device_count()
    batch = max(world, 8)
    rs = np.random.RandomState(0)
    x = rs.randint(1, cfg.vocab_size,
                   size=(batch, cfg.max_seq_len)).astype(np.int32)
    y = rs.randint(0, cfg.vocab_size,
                   size=(batch, cfg.max_seq_len)).astype(np.int32)
    opt = optax.adamw(1e-3)
    state = training.replicate_state(training.create_train_state(
        model, opt, jax.random.PRNGKey(0), x[:1]))

    def lowered(step):
        return step.lower(state, x, y).as_text()

    def build(guard):
        return training.data_parallel_train_step(model, opt, guard=guard)

    plain_txt = lowered(build(False))
    # env-disabled (guard=None defers to HVD_TPU_GUARD) must be the
    # SAME bytes as guard=False — the observer leaves no residue
    os.environ["HVD_TPU_GUARD"] = "0"
    try:
        disabled_txt = lowered(build(None))
    finally:
        os.environ.pop("HVD_TPU_GUARD", None)
    findings += check_byte_identical("guard-disabled", plain_txt,
                                     disabled_txt)
    findings += check_added_collectives("guard-enabled", plain_txt,
                                        lowered(build(True)))

    # trace on/off: hash-identical lowering (the trace_bench idiom)
    trace.configure(enabled=True)
    on_txt = lowered(build(False))
    trace.configure(enabled=False)
    off_txt = lowered(build(False))
    trace.configure(enabled=True)
    findings += check_byte_identical("trace-on-off", on_txt, off_txt)

    # overlap: collectives interleaved with compute, not all trailing
    # (bucket_bytes small enough that the tiny model still splits into
    # several buckets — one bucket legitimately trails whole)
    ov_txt = lowered(training.data_parallel_train_step(
        model, opt, overlap=True, bucket_bytes=4096))
    inv = overlap_inventory(ov_txt, min_payload_bytes=1024)
    if not inv["interleaved"] or inv["exposed_fraction"] >= 1.0:
        findings.append(Finding(
            CHECK, TRAINING_PY, 0, "overlap-trailing",
            "overlapped train step lowers with every collective "
            f"trailing the backward (exposed_fraction="
            f"{inv['exposed_fraction']}) — the bucket-boundary "
            "schedule is not interleaving (docs/tensor-fusion.md)",
        ))

    # ZeRO: the guarded step adds 0 collectives over the unguarded one
    def zero_txt(guard):
        st, step, _specs = training.zero_train_setup(
            model, optax.adamw(1e-3), jax.random.PRNGKey(0), x[:1],
            guard=guard)
        return step.lower(st, x, y).as_text()

    findings += check_added_collectives("zero-guard", zero_txt(False),
                                        zero_txt(True))
    return findings


def _verify_hierarchical() -> List[Finding]:
    """modeled_collective_bytes == measured_tier_bytes on the lowered
    hierarchical allreduce over the topology's 2-D mesh."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from ..common import basics
    from ..common.topology import DCN_AXIS, ICI_AXIS
    from ..ops import spmd_ops
    from ..ops.comm_model import (measured_tier_bytes, mesh_slice_ids,
                                  modeled_collective_bytes)
    from ..ops.reduce_ops import Sum

    world = jax.device_count()
    n_ici = max(world // 2, 1)
    if world < 4 or world % n_ici:
        return []
    os.environ["HVD_TPU_SLICE_SIZE"] = str(n_ici)
    try:
        topo = basics._require_init().topology
        hmesh = topo.hierarchical_mesh()
        numel = 4096
        x = jnp.asarray(np.arange(world * numel, dtype=np.float32)
                        .reshape(world, numel))
        fn = jax.jit(jax.shard_map(
            lambda t: spmd_ops.hierarchical_allreduce(t, op=Sum),
            mesh=hmesh, in_specs=P((DCN_AXIS, ICI_AXIS)),
            out_specs=P((DCN_AXIS, ICI_AXIS)), check_vma=False))
        measured = measured_tier_bytes(fn.lower(x).as_text(),
                                       mesh_slice_ids(hmesh))
        modeled = modeled_collective_bytes((numel,), world, n_ici)
        return check_modeled_measured(
            "hierarchical-allreduce",
            {"ici": modeled["ici_bytes"], "dcn": modeled["dcn_bytes"]},
            {"ici": measured["ici_bytes"], "dcn": measured["dcn_bytes"]})
    finally:
        os.environ.pop("HVD_TPU_SLICE_SIZE", None)


def verify(root: str = ".", shards: Sequence[int] = (1, 2),
           requests: int = 512, seed: int = 0) -> List[Finding]:
    """The full jax-requiring verification — every invariant in the
    module docstring.  ``root`` is accepted for PASSES signature
    parity; the programs are built from the installed package, not
    read from disk."""
    import horovod_tpu as hvd

    if not hvd.is_initialized():
        hvd.init()
    findings: List[Finding] = []
    findings += _verify_training()
    findings += _verify_hierarchical()
    findings += _verify_serving(tuple(shards), requests, seed)
    return findings
