"""Shared plumbing for the static-analysis passes.

Everything in ``horovod_tpu.analysis`` is stdlib-only and never imports
the framework (no jax, no ctypes loads) — the suite must run on a bare
CI box in well under a second and must be loadable standalone by
``tools/check.py`` without executing ``horovod_tpu/__init__``.

Suppression model (docs/ANALYSIS.md):

* inline — the offending line (or the line directly above it) carries a
  ``contract-ok: <check> -- <justification>`` marker in a comment
  (``#``, ``//`` or ``<!-- -->``).  The justification is REQUIRED: a
  bare marker is itself reported, so nobody can wave a finding through
  silently.
* allowlist file — entries ``<check>:<key> -- <justification>`` in the
  file named by ``[tool.horovod_tpu.analysis] allowlist`` in
  pyproject.toml (default ``tools/analysis_allowlist.txt``).  Stale
  entries (matching nothing) and entries without a justification are
  reported too, so the list can only shrink back to honest.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

#: Relative layout anchors every pass shares (synthetic trees in the
#: self-tests recreate exactly these paths under a tmp root).
C_API_CC = "horovod_tpu/native/src/c_api.cc"
CONTROLLER_PY = "horovod_tpu/native/controller.py"
PACKAGE_DIR = "horovod_tpu"
NATIVE_SRC_DIR = "horovod_tpu/native/src"
INSTRUMENTS_PY = "horovod_tpu/metrics/instruments.py"
CHAOS_INIT_PY = "horovod_tpu/chaos/__init__.py"
RUNNING_MD = "docs/running.md"
METRICS_MD = "docs/METRICS.md"
FAULT_MD = "docs/FAULT_TOLERANCE.md"
#: ctypes harnesses cross-checked against the C API (beyond the
#: production binding in CONTROLLER_PY).
CTYPES_HARNESSES = (
    "tests/test_control_auth.py",
    "tests/test_fault_native.py",
)
DEFAULT_ALLOWLIST = "tools/analysis_allowlist.txt"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One contract violation.  ``key`` is the stable handle suppression
    matches on (env-var name, metric name, chaos site, C symbol)."""

    check: str
    file: str      # path relative to the analysis root
    line: int      # 1-based; 0 when the finding is file-scoped
    key: str
    message: str

    def render(self) -> str:
        loc = f"{self.file}:{self.line}" if self.line else self.file
        return f"{loc}: [{self.check}] {self.message}"


_MARKER_RE = re.compile(
    r"contract-ok:\s*(?P<check>[\w*-]+)\s*(?:--\s*(?P<why>.*?))?\s*(?:-->)?\s*$"
)


def read_text(path: str) -> Optional[str]:
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            return f.read()
    except OSError:
        return None


def iter_py_files(root: str, subdir: str = PACKAGE_DIR,
                  exclude_dirs: Tuple[str, ...] = ("analysis",
                                                   "__pycache__"),
                  ) -> List[str]:
    """Relative paths of the package's .py files, sorted for stable
    output.  The analysis package itself is excluded — its regex source
    would otherwise trip the very patterns it searches for."""
    base = os.path.join(root, subdir)
    out: List[str] = []
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = [d for d in dirnames if d not in exclude_dirs]
        for fn in filenames:
            if fn.endswith(".py"):
                out.append(os.path.relpath(os.path.join(dirpath, fn), root))
    return sorted(out)


def iter_native_files(root: str) -> List[str]:
    base = os.path.join(root, NATIVE_SRC_DIR)
    if not os.path.isdir(base):
        return []
    return sorted(
        os.path.join(NATIVE_SRC_DIR, fn)
        for fn in os.listdir(base)
        if fn.endswith((".h", ".cc"))
    )


def strip_comment(line: str, kind: str) -> str:
    """Drop the trailing comment of one source line (naive but
    sufficient: the tokens these passes search for never legitimately
    contain ``#`` / ``//``)."""
    marker = "//" if kind == "c" else "#"
    idx = line.find(marker)
    return line if idx < 0 else line[:idx]


class Suppressions:
    """Inline markers + the allowlist file, resolved per run."""

    def __init__(self, root: str):
        self.root = root
        self._inline_cache: Dict[str, List[str]] = {}
        self.extra_findings: List[Finding] = []
        self._allow: Dict[Tuple[str, str], str] = {}
        self._used: set = set()
        self._allow_path = self._resolve_allowlist_path()
        self._load_allowlist()

    # -- allowlist file ------------------------------------------------------

    def _resolve_allowlist_path(self) -> str:
        """``[tool.horovod_tpu.analysis] allowlist = "..."`` from
        pyproject.toml (regex scan — py3.10 has no tomllib)."""
        text = read_text(os.path.join(self.root, "pyproject.toml")) or ""
        in_section = False
        for line in text.splitlines():
            stripped = line.strip()
            if stripped.startswith("["):
                in_section = stripped == "[tool.horovod_tpu.analysis]"
                continue
            if in_section:
                m = re.match(r'allowlist\s*=\s*"([^"]+)"', stripped)
                if m:
                    return m.group(1)
        return DEFAULT_ALLOWLIST

    def _load_allowlist(self) -> None:
        text = read_text(os.path.join(self.root, self._allow_path))
        if text is None:
            return
        for lineno, raw in enumerate(text.splitlines(), 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            m = re.match(r"([\w-]+):(\S+)\s+--\s+(.+)$", line)
            if not m:
                self.extra_findings.append(Finding(
                    "allowlist", self._allow_path, lineno, line,
                    "malformed allowlist entry (want "
                    "'<check>:<key> -- <justification>'): " + line,
                ))
                continue
            self._allow[(m.group(1), m.group(2))] = m.group(3)

    # -- inline markers ------------------------------------------------------

    def _lines(self, relfile: str) -> List[str]:
        if relfile not in self._inline_cache:
            text = read_text(os.path.join(self.root, relfile)) or ""
            self._inline_cache[relfile] = text.splitlines()
        return self._inline_cache[relfile]

    def _inline_marker(self, f: Finding) -> Optional[Tuple[str, str, int]]:
        """(check, justification, lineno) of a marker on the finding's
        line or the line above it."""
        lines = self._lines(f.file)
        for lineno in (f.line, f.line - 1):
            if 1 <= lineno <= len(lines):
                m = _MARKER_RE.search(lines[lineno - 1])
                if m:
                    return m.group("check"), (m.group("why") or "").strip(), \
                        lineno
        return None

    # -- resolution ----------------------------------------------------------

    def filter(self, findings: Iterable[Finding]) -> List[Finding]:
        out: List[Finding] = []
        for f in findings:
            entry = self._allow.get((f.check, f.key))
            if entry is not None:
                self._used.add((f.check, f.key))
                continue
            marker = self._inline_marker(f)
            if marker is not None and marker[0] in (f.check, "*"):
                why, lineno = marker[1], marker[2]
                if not why:
                    out.append(Finding(
                        "allowlist", f.file, lineno, f.key,
                        f"contract-ok marker for [{f.check}] has no "
                        "justification (write 'contract-ok: "
                        f"{f.check} -- <why>')",
                    ))
                continue
            out.append(f)
        return out

    def stale_entries(self) -> List[Finding]:
        out = []
        for (check, key), why in sorted(self._allow.items()):
            if (check, key) not in self._used:
                out.append(Finding(
                    "allowlist", self._allow_path, 0, f"{check}:{key}",
                    f"stale allowlist entry {check}:{key} (nothing "
                    "matches it any more — delete the line)",
                ))
        return out
