"""Chaos-site parity lint.

The fault-injection subsystem names its points three times: the Python
``SITES`` catalogue (``chaos/__init__.py``), the native twin's
``chaos::Decide("...")`` call sites (``native/src``), and the
documented site table in ``docs/FAULT_TOLERANCE.md``.  A site present
in one layer but not the others is a rule that silently never fires —
the worst possible failure mode for the subsystem whose job is proving
failures are handled.

Checked equivalences:

* every ``chaos.point("...")`` / ``raise_point("...")`` literal in the
  package names a catalogued site;
* every catalogued non-native site has at least one Python call site
  (a catalogue entry nothing evaluates is dead);
* the native ``Decide`` sites are exactly the catalogue's
  ``transport.*`` entries (both directions);
* the FAULT_TOLERANCE.md site table is exactly the catalogue.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Set, Tuple

from ._common import (
    CHAOS_INIT_PY, FAULT_MD, Finding, iter_native_files, iter_py_files,
    read_text,
)

CHECK = "chaos"

#: catalogue prefix whose sites are evaluated in the native core
NATIVE_PREFIX = "transport."

_SITES_RE = re.compile(r"^SITES\s*=\s*\(", re.MULTILINE)
_STR_RE = re.compile(r"\"([a-z0-9_.]+)\"")
_POINT_RE = re.compile(r"\b(?:raise_)?point\(\s*\"([a-z0-9_.]+)\"")
_DECIDE_RE = re.compile(r"\bDecide\(\s*\"([a-z0-9_.]+)\"")
# site tokens always carry at least one dot — plain words in other
# backticked table columns (action names, knob values) must not match
_DOC_ROW_RE = re.compile(
    r"^\|\s*`([a-z0-9_]+(?:\.[a-z0-9_]+)+)`\s*\|", re.MULTILINE)


def catalogue(root: str) -> Tuple[Dict[str, int], str]:
    """site -> line of the SITES tuple in chaos/__init__.py."""
    text = read_text(os.path.join(root, CHAOS_INIT_PY))
    if text is None:
        return {}, ""
    m = _SITES_RE.search(text)
    if not m:
        return {}, text
    # balanced scan of the tuple literal
    i = text.index("(", m.start())
    depth, j = 0, i
    while j < len(text):
        if text[j] == "(":
            depth += 1
        elif text[j] == ")":
            depth -= 1
            if depth == 0:
                break
        j += 1
    out: Dict[str, int] = {}
    for sm in _STR_RE.finditer(text, i, j):
        out[sm.group(1)] = text.count("\n", 0, sm.start()) + 1
    return out, text


def run(root: str) -> List[Finding]:
    findings: List[Finding] = []
    sites, _ = catalogue(root)
    if not sites:
        findings.append(Finding(
            CHECK, CHAOS_INIT_PY, 0, "missing",
            "chaos/__init__.py SITES catalogue not found/empty — the "
            "site registry is gone"))
        return findings

    # -- Python call sites ---------------------------------------------------
    py_used: Set[str] = set()
    for rel in iter_py_files(root,
                             exclude_dirs=("analysis", "chaos",
                                           "__pycache__")):
        text = read_text(os.path.join(root, rel))
        if text is None:
            continue
        for m in _POINT_RE.finditer(text):
            site = m.group(1)
            py_used.add(site)
            if site not in sites:
                lineno = text.count("\n", 0, m.start()) + 1
                findings.append(Finding(
                    CHECK, rel, lineno, site,
                    f"chaos point {site!r} is evaluated here but not in "
                    "the SITES catalogue — no HVD_TPU_CHAOS rule can "
                    "ever be validated against it",
                ))

    for site, lineno in sorted(sites.items()):
        if site.startswith(NATIVE_PREFIX):
            continue
        if site not in py_used:
            findings.append(Finding(
                CHECK, CHAOS_INIT_PY, lineno, site,
                f"catalogued site {site!r} has no chaos.point()/"
                "raise_point() call site in the package (dead catalogue "
                "entry)",
            ))

    # -- native twin ---------------------------------------------------------
    native_used: Dict[str, Tuple[str, int]] = {}
    for rel in iter_native_files(root):
        text = read_text(os.path.join(root, rel))
        if text is None:
            continue
        for m in _DECIDE_RE.finditer(text):
            site = m.group(1)
            lineno = text.count("\n", 0, m.start()) + 1
            native_used.setdefault(site, (rel, lineno))
            if site not in sites:
                findings.append(Finding(
                    CHECK, rel, lineno, site,
                    f"native chaos site {site!r} is evaluated here but "
                    "not in the SITES catalogue",
                ))
            elif not site.startswith(NATIVE_PREFIX):
                findings.append(Finding(
                    CHECK, rel, lineno, site,
                    f"native code evaluates {site!r} but only "
                    f"{NATIVE_PREFIX}* sites are exported to the native "
                    "engine (chaos.configure_native_lib) — the rule "
                    "would never arrive",
                ))
    for site, lineno in sorted(sites.items()):
        if site.startswith(NATIVE_PREFIX) and site not in native_used:
            findings.append(Finding(
                CHECK, CHAOS_INIT_PY, lineno, site,
                f"catalogued native site {site!r} has no chaos::Decide "
                "call in native/src (dead catalogue entry)",
            ))

    # -- documented table ----------------------------------------------------
    doc_text = read_text(os.path.join(root, FAULT_MD))
    if doc_text is None:
        findings.append(Finding(CHECK, FAULT_MD, 0, "missing",
                                "docs/FAULT_TOLERANCE.md not found"))
        return findings
    doc_sites: Dict[str, int] = {}
    for m in _DOC_ROW_RE.finditer(doc_text):
        doc_sites[m.group(1)] = doc_text.count("\n", 0, m.start()) + 1
    for site, lineno in sorted(sites.items()):
        if site not in doc_sites:
            findings.append(Finding(
                CHECK, CHAOS_INIT_PY, lineno, site,
                f"site {site!r} is catalogued but missing from the "
                "docs/FAULT_TOLERANCE.md site table",
            ))
    for site, lineno in sorted(doc_sites.items()):
        if site not in sites:
            findings.append(Finding(
                CHECK, FAULT_MD, lineno, site,
                f"docs/FAULT_TOLERANCE.md documents site {site!r} but "
                "the SITES catalogue does not contain it",
            ))
    return findings
