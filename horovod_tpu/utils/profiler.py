"""jax.profiler bridge for the negotiated-collective spans.

Reference analog: SURVEY.md §5.1 — the reference's timeline is its own
Chrome-trace writer; its NVTX hooks put the same spans into the vendor
profiler so one capture shows framework activity next to kernel
activity.  The TPU-native equivalent: every negotiated collective emits
``TraceMe`` spans (via :class:`jax.profiler.TraceAnnotation`) with the
SAME activity names the Chrome timeline uses (ENQUEUE / XLA_COMM), so a
single ``jax.profiler.trace`` XPlane capture shows where negotiation
and collective execution sit relative to XLA's own ops.

Span semantics (TraceMe spans are thread-local, so each side of the
handoff gets its own span — the negotiation wait is the *gap*):

  * ``hvd_tpu::<name>::ENQUEUE``   — training thread, inside enqueue();
  * ``hvd_tpu::<op>::XLA_COMM``    — background exec thread, dispatch →
    data-ready of the fused collective program.

Overhead when no capture is active is one atomic load per span (TraceMe
fast path), so the bridge is always on; set ``HVD_TPU_PROFILER_BRIDGE=0``
to compile it out at import.

Capture recipe (works on the 8-device CPU mesh and on TPU)::

    import jax
    jax.profiler.start_trace("/tmp/hvd-trace")
    ... training steps / hvd.allreduce calls ...
    jax.profiler.stop_trace()
    # open the trace:
    #   tensorboard --logdir /tmp/hvd-trace   (Profile plugin), or
    #   load plugins/profile/<ts>/<host>.trace.json.gz in ui.perfetto.dev

``tools/profile_capture.py`` scripts exactly this and produced the
committed example trace (docs/example_trace.json.gz).
"""

from __future__ import annotations

import contextlib
import os

_ENABLED = os.environ.get("HVD_TPU_PROFILER_BRIDGE", "1") != "0"

if _ENABLED:
    try:
        from jax.profiler import TraceAnnotation
    except Exception:  # pragma: no cover - ancient jax
        _ENABLED = False

_NULL = contextlib.nullcontext()


def span(name: str, activity: str):
    """Context manager for one framework span in the XPlane capture."""
    if not _ENABLED:
        return _NULL
    return TraceAnnotation(f"hvd_tpu::{name}::{activity}")
