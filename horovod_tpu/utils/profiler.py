"""jax.profiler bridge for the negotiated-collective spans.

Reference analog: SURVEY.md §5.1 — the reference's timeline is its own
Chrome-trace writer; its NVTX hooks put the same spans into the vendor
profiler so one capture shows framework activity next to kernel
activity.

Since the ``horovod_tpu.trace`` recorder landed, this module is a thin
alias over it: ONE instrumentation point (the controller's
enqueue/exec call sites) now produces BOTH views —

  * the XPlane capture span, named ``hvd_tpu::<name>::<activity>``
    exactly as before (``jax.profiler.TraceAnnotation``; existing
    ``tools/profile_capture.py`` recipes and the committed example
    trace keep their names), and
  * a ring-buffer record at the catalogued ``collective.enqueue`` /
    ``collective.exec`` site, which the ``/trace`` Chrome export and
    the flight recorder serve (docs/TRACING.md).

There is no second span-naming scheme to drift: the activity string is
derived from the trace site at ONE place below.

Overhead when no capture is active is one atomic load per span (TraceMe
fast path) plus the ring store; ``HVD_TPU_PROFILER_BRIDGE=0`` drops the
XPlane half, ``HVD_TPU_TRACE=0`` the ring half (both = a null context).

Capture recipe (works on the 8-device CPU mesh and on TPU)::

    import jax
    jax.profiler.start_trace("/tmp/hvd-trace")
    ... training steps / hvd.allreduce calls ...
    jax.profiler.stop_trace()
    # open the trace:
    #   tensorboard --logdir /tmp/hvd-trace   (Profile plugin), or
    #   load plugins/profile/<ts>/<host>.trace.json.gz in ui.perfetto.dev

``tools/profile_capture.py`` scripts exactly this and produced the
committed example trace (docs/example_trace.json.gz).
"""

from __future__ import annotations

import os

from .. import trace as _trace

_BRIDGE = os.environ.get("HVD_TPU_PROFILER_BRIDGE", "1") != "0"


def span(name: str, activity: str):
    """Context manager for one framework span: the XPlane capture gets
    ``hvd_tpu::<name>::<activity>``, the trace ring gets the catalogued
    site for the activity (ENQUEUE -> collective.enqueue, anything else
    -> collective.exec) with the collective's name as an arg."""
    xname = f"hvd_tpu::{name}::{activity}" if _BRIDGE else False
    if activity == "ENQUEUE":
        return _trace.span("collective.enqueue", _xname=xname, name=name)
    return _trace.span("collective.exec", _xname=xname, name=name)
