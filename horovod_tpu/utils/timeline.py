"""Chrome-trace timeline writer.

Reference parity: horovod/common/timeline.h/.cc (SURVEY.md §5.1) — a JSON
``about:tracing`` file with one row per tensor and spans for each phase of
its life.  The reference's phases are NEGOTIATE → QUEUE → MEMCPY_IN → COMM
→ MEMCPY_OUT; under XLA negotiation and memcpys don't exist, so the emitted
phases are ENQUEUE (python-side submit), COMPILE (executable-cache miss) and
XLA_COMM (dispatch→ready).  File format is identical, so the same
chrome://tracing / Perfetto workflow applies.

This Python writer is the fallback; the native core's C++ writer thread
(native/src/timeline.cc) takes over when loaded, matching the reference's
dedicated writer thread design.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional


class Timeline:
    def __init__(self, filename: str, rank: int = 0):
        self._filename = filename
        self._rank = rank
        self._lock = threading.Lock()
        self._file = open(filename, "w")
        self._file.write("[\n")
        self._first = True
        self._t0 = time.monotonic_ns()
        self._closed = False
        self._emit(
            {
                "name": "process_name",
                "ph": "M",
                "pid": rank,
                "args": {"name": f"hvd_tpu rank {rank}"},
            }
        )

    def _now_us(self) -> float:
        return (time.monotonic_ns() - self._t0) / 1e3

    def _emit(self, event: dict) -> None:
        with self._lock:
            if self._closed:
                return
            if not self._first:
                self._file.write(",\n")
            self._first = False
            json.dump(event, self._file)

    def start(self, tensor_name: str, activity: str) -> None:
        """Reference: Timeline::ActivityStart."""
        self._emit(
            {
                "name": activity,
                "cat": "hvd_tpu",
                "ph": "B",
                "pid": self._rank,
                "tid": hash(tensor_name) % (1 << 31),
                "ts": self._now_us(),
                "args": {"tensor": tensor_name},
            }
        )

    def end(self, tensor_name: str, activity: str) -> None:
        """Reference: Timeline::ActivityEnd."""
        self._emit(
            {
                "name": activity,
                "cat": "hvd_tpu",
                "ph": "E",
                "pid": self._rank,
                "tid": hash(tensor_name) % (1 << 31),
                "ts": self._now_us(),
            }
        )

    def instant(self, name: str) -> None:
        """Reference: Timeline::MarkCycleStart (HOROVOD_TIMELINE_MARK_CYCLES)."""
        self._emit(
            {
                "name": name,
                "cat": "hvd_tpu",
                "ph": "i",
                "s": "g",
                "pid": self._rank,
                "ts": self._now_us(),
            }
        )

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._file.write("\n]\n")
            self._file.close()
