"""Utilities (reference analog: horovod/common/utils/ + logging/timeline)."""
