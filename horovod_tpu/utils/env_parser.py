"""Environment-variable configuration.

Reference parity: horovod/common/utils/env_parser.cc + SURVEY.md §5.6 — env
is the single source of truth at init time; the launcher CLI and YAML config
file both converge on these variables.  Knob names keep the reference's
spelling with an ``HVD_TPU_`` prefix (the launcher also accepts the classic
``HOROVOD_`` spelling for drop-in compatibility).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def _get(name: str, default: Optional[str] = None) -> Optional[str]:
    """Look up ``HVD_TPU_<name>`` falling back to ``HOROVOD_<name>``."""
    v = os.environ.get(f"HVD_TPU_{name}")
    if v is None:
        v = os.environ.get(f"HOROVOD_{name}")
    return v if v is not None else default


def _get_int(name: str, default: int) -> int:
    v = _get(name)
    try:
        return int(v) if v is not None else default
    except ValueError:
        return default


def _get_int_validated(name: str, default: int, minimum: int = 0) -> int:
    """Strict integer knob: a set-but-garbage or out-of-range value is a
    configuration ERROR, not a silent default.  Used for the fusion/
    overlap byte thresholds, where a typo'd ``64MB`` or a negative value
    would otherwise silently fall through to the one-bucket-per-tensor
    path and tank collective efficiency without any signal."""
    v = _get(name)
    if v is None:
        return default
    # name the variable the user ACTUALLY set — the error must point at
    # the HOROVOD_* compatibility alias when that is where the value
    # came from, or "unset it" sends them after the wrong knob
    var = (
        f"HVD_TPU_{name}"
        if os.environ.get(f"HVD_TPU_{name}") is not None
        else f"HOROVOD_{name}"
    )
    try:
        value = int(v)
    except ValueError:
        raise ValueError(
            f"{var} must be an integer (bytes/count), got "
            f"{v!r} — unset it or pass a plain integer"
        ) from None
    if value < minimum:
        raise ValueError(
            f"{var} must be >= {minimum}, got {value} "
            f"(0 disables fusion: one bucket per tensor)"
            if minimum == 0 else
            f"{var} must be >= {minimum}, got {value}"
        )
    return value


def _get_float(name: str, default: float) -> float:
    v = _get(name)
    try:
        return float(v) if v is not None else default
    except ValueError:
        return default


def _get_bool(name: str, default: bool) -> bool:
    v = _get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


@dataclasses.dataclass
class Config:
    """Runtime knobs, mirroring the reference's ~40 HOROVOD_* env vars
    (SURVEY.md §5.6).  Only the knobs meaningful on TPU are kept; the rest
    are accepted and ignored by the launcher for compatibility."""

    # Tensor fusion (horovod/common/fusion_buffer_manager.cc):
    fusion_threshold_bytes: int = 64 * 1024 * 1024  # HOROVOD_FUSION_THRESHOLD
    # Background controller cycle (horovod/common/operations.cc RunLoopOnce):
    cycle_time_ms: float = 1.0  # HOROVOD_CYCLE_TIME
    # Response cache (horovod/common/response_cache.cc):
    cache_capacity: int = 1024  # HOROVOD_CACHE_CAPACITY
    # Timeline (horovod/common/timeline.cc):
    timeline_filename: str = ""  # HOROVOD_TIMELINE
    timeline_mark_cycles: bool = False  # HOROVOD_TIMELINE_MARK_CYCLES
    # Stall inspector (horovod/common/stall_inspector.cc):
    stall_check_disable: bool = False  # HOROVOD_STALL_CHECK_DISABLE
    stall_warning_time_seconds: float = 60.0  # HOROVOD_STALL_CHECK_TIME_SECONDS
    stall_shutdown_time_seconds: float = 0.0  # HOROVOD_STALL_SHUTDOWN_TIME_SECONDS
    # Autotune (horovod/common/parameter_manager.cc):
    autotune: bool = False  # HOROVOD_AUTOTUNE
    autotune_log: str = ""  # HOROVOD_AUTOTUNE_LOG
    # Backward/collective overlap scheduler (ops/overlap.py,
    # docs/tensor-fusion.md): bucket size of the BucketSchedule (0 = one
    # bucket per tensor), and the metrics-driven BucketAutotuner sweeping
    # bucket sizes against live step time (docs/autotune.md).
    overlap_bucket_bytes: int = 4 * 1024 * 1024  # HVD_TPU_OVERLAP_BUCKET_BYTES
    overlap_autotune: bool = False  # HVD_TPU_OVERLAP_AUTOTUNE
    overlap_autotune_trials: int = 8  # HVD_TPU_OVERLAP_AUTOTUNE_TRIALS
    overlap_autotune_steps: int = 3  # HVD_TPU_OVERLAP_AUTOTUNE_STEPS
    # Hierarchical allreduce (nccl_operations.cc NCCLHierarchicalAllreduce):
    hierarchical_allreduce: bool = False  # HOROVOD_HIERARCHICAL_ALLREDUCE
    # DCN-hop wire format for routed hierarchical allreduces
    # (compression.DcnCompression; "" = full precision):
    dcn_wire_dtype: str = ""  # HVD_TPU_DCN_WIRE_DTYPE
    # Elastic:
    elastic: bool = False  # HOROVOD_ELASTIC
    # Logging:
    log_level: str = "warning"  # HOROVOD_LOG_LEVEL
    # TPU specific: dispatch collectives via XLA (the only backend; kept for
    # BASELINE.json's HOROVOD_TPU_OPERATIONS=XLA contract).
    tpu_operations: str = "XLA"

    @staticmethod
    def from_env() -> "Config":
        return Config(
            fusion_threshold_bytes=_get_int_validated(
                "FUSION_THRESHOLD", 64 * 1024 * 1024),
            cycle_time_ms=_get_float("CYCLE_TIME", 1.0),
            cache_capacity=_get_int("CACHE_CAPACITY", 1024),
            timeline_filename=_get("TIMELINE", "") or "",
            timeline_mark_cycles=_get_bool("TIMELINE_MARK_CYCLES", False),
            stall_check_disable=_get_bool("STALL_CHECK_DISABLE", False),
            stall_warning_time_seconds=_get_float("STALL_CHECK_TIME_SECONDS", 60.0),
            stall_shutdown_time_seconds=_get_float("STALL_SHUTDOWN_TIME_SECONDS", 0.0),
            autotune=_get_bool("AUTOTUNE", False),
            autotune_log=_get("AUTOTUNE_LOG", "") or "",
            overlap_bucket_bytes=_get_int_validated(
                "OVERLAP_BUCKET_BYTES", 4 * 1024 * 1024),
            overlap_autotune=_get_bool("OVERLAP_AUTOTUNE", False),
            overlap_autotune_trials=_get_int_validated(
                "OVERLAP_AUTOTUNE_TRIALS", 8, minimum=1),
            overlap_autotune_steps=_get_int_validated(
                "OVERLAP_AUTOTUNE_STEPS", 3, minimum=1),
            hierarchical_allreduce=_get_bool("HIERARCHICAL_ALLREDUCE", False),
            dcn_wire_dtype=(_get("DCN_WIRE_DTYPE", "") or "").lower(),
            elastic=_get_bool("ELASTIC", False),
            log_level=(_get("LOG_LEVEL", "warning") or "warning").lower(),
            tpu_operations=(_get("TPU_OPERATIONS", "XLA") or "XLA").upper(),
        )
