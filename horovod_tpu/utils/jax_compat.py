"""Compatibility shims across jax versions.

The framework (and its tests/examples) target the modern spelling
``jax.shard_map(f, mesh=..., in_specs=..., out_specs=..., check_vma=...)``.
Older jax releases (< 0.5) only ship
``jax.experimental.shard_map.shard_map`` with the ``check_rep`` keyword.
``install()`` bridges the gap by publishing a signature-adapting wrapper
as ``jax.shard_map`` when (and only when) the attribute is missing — on
modern jax it is a no-op, and nothing is ever overwritten.

Installed from ``horovod_tpu/__init__`` so every consumer (the engine's
compiled collectives, run_per_rank, the parallel strategies, user
scripts) sees one working spelling regardless of the image's jax.
"""

from __future__ import annotations

import jax


def install() -> None:
    _install_shard_map()
    _install_axis_size()


def _install_shard_map() -> None:
    if hasattr(jax, "shard_map"):
        return
    try:
        from jax.experimental.shard_map import shard_map as _legacy
    except ImportError:  # no shard_map at all: leave jax untouched
        return

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kwargs):
        if check_vma is not None and "check_rep" not in kwargs:
            kwargs["check_rep"] = check_vma  # renamed keyword, same role
        return _legacy(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, **kwargs)

    jax.shard_map = shard_map


def _install_axis_size() -> None:
    if hasattr(jax.lax, "axis_size"):
        return

    def axis_size(axis_name):
        # this jax's axis_frame() already resolves to the static size
        return jax.core.axis_frame(axis_name)

    jax.lax.axis_size = axis_size
