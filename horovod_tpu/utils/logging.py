"""Logging setup.

Reference parity: horovod/common/logging.cc (glog-style levels selected by
HOROVOD_LOG_LEVEL) — here a thin shim over :mod:`logging` with the same
level names, shared by the Python layer and surfaced to the native core.
Env lookup goes through utils.env_parser so HVD_TPU_*/HOROVOD_* fallback
and bool grammar stay consistent framework-wide.

Structured context: every record carries ``rank`` / ``host`` / ``step``
fields, stamped from one process-wide context (:func:`set_log_context`
— ``hvd.init`` sets the rank, the elastic driver marks itself
``driver``, the training loop keeps ``step`` current), so the driver,
worker and fleet loggers share ONE formatter and a multi-process log
collates by rank instead of by guesswork.  ``HVD_TPU_LOG_JSON=1`` opts
into one-JSON-object-per-line output (machine-ingestable; the same
fields), the default stays the human text format.
"""

from __future__ import annotations

import json
import logging
import socket
import sys
import time
from typing import Optional

from .env_parser import _get, _get_bool

_LEVELS = {
    "trace": logging.DEBUG,  # python logging has no TRACE; map to DEBUG
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

_LOGGER = logging.getLogger("horovod_tpu")
_configured = False

#: JSON-lines opt-in (read below through the env_parser `_get_bool`
#: grammar, so `HOROVOD_LOG_JSON` falls back like every other knob)
ENV_LOG_JSON = "HVD_TPU_LOG_JSON"

#: process-wide structured-log context (one dict, mutated in place so
#: the installed filter sees updates without re-registration)
_context = {"rank": "-", "host": socket.gethostname(), "step": "-"}


def set_log_context(rank=None, host=None, step=None) -> None:
    """Update the fields every subsequent record carries.  ``rank`` may
    be an int or a role string ("driver"); ``step`` is kept current by
    the training loop (one dict store per step)."""
    if rank is not None:
        _context["rank"] = rank
    if host is not None:
        _context["host"] = host
    if step is not None:
        _context["step"] = step


class _ContextFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        record.rank = _context["rank"]
        record.host = _context["host"]
        record.step = _context["step"]
        return True


class _JsonFormatter(logging.Formatter):
    """One JSON object per line: level, message, logger and the shared
    rank/host/step context (HVD_TPU_LOG_JSON=1; docs/running.md)."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "t": round(time.time(), 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
            "rank": getattr(record, "rank", "-"),
            "host": getattr(record, "host", "-"),
            "step": getattr(record, "step", "-"),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out)


def get_logger() -> logging.Logger:
    global _configured
    if not _configured:
        level_name = (_get("LOG_LEVEL", "warning") or "warning").lower()
        handler = logging.StreamHandler(sys.stderr)
        if _get_bool("LOG_JSON", False):
            handler.setFormatter(_JsonFormatter())
        else:
            hide_time = _get_bool("LOG_HIDE_TIME", False)
            fmt = "[%(levelname)s] hvd_tpu: %(message)s" if hide_time else \
                "%(asctime)s [%(levelname)s] hvd_tpu: %(message)s"
            handler.setFormatter(logging.Formatter(fmt))
        _LOGGER.addFilter(_ContextFilter())
        _LOGGER.addHandler(handler)
        _LOGGER.setLevel(_LEVELS.get(level_name, logging.WARNING))
        _LOGGER.propagate = False
        _configured = True
    return _LOGGER
