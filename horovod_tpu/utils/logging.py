"""Logging setup.

Reference parity: horovod/common/logging.cc (glog-style levels selected by
HOROVOD_LOG_LEVEL) — here a thin shim over :mod:`logging` with the same
level names, shared by the Python layer and surfaced to the native core.
Env lookup goes through utils.env_parser so HVD_TPU_*/HOROVOD_* fallback
and bool grammar stay consistent framework-wide.
"""

from __future__ import annotations

import logging
import sys

from .env_parser import _get, _get_bool

_LEVELS = {
    "trace": logging.DEBUG,  # python logging has no TRACE; map to DEBUG
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

_LOGGER = logging.getLogger("horovod_tpu")
_configured = False


def get_logger() -> logging.Logger:
    global _configured
    if not _configured:
        level_name = (_get("LOG_LEVEL", "warning") or "warning").lower()
        handler = logging.StreamHandler(sys.stderr)
        hide_time = _get_bool("LOG_HIDE_TIME", False)
        fmt = "[%(levelname)s] hvd_tpu: %(message)s" if hide_time else \
            "%(asctime)s [%(levelname)s] hvd_tpu: %(message)s"
        handler.setFormatter(logging.Formatter(fmt))
        _LOGGER.addHandler(handler)
        _LOGGER.setLevel(_LEVELS.get(level_name, logging.WARNING))
        _LOGGER.propagate = False
        _configured = True
    return _LOGGER
