"""Dataset sources: where samples come from.

Reference analog: the reference has no input subsystem of its own — its
examples lean on ``torch.utils.data.DataLoader`` / ``tf.data`` and the
Spark estimators stream Petastorm row groups (SURVEY.md §2.4).  The
TPU-native framework needs one because the deployment target is a plain
JAX process on a TPU VM: there is no framework DataLoader to borrow, and
an unfed MXU is the first thing that erases the compiled train step's
throughput (PERF.md).

A :class:`DataSource` is the minimal random-access contract the sharded
loader needs: ``len(src)`` and ``src.batch(indices) -> (inputs, labels)``
returning numpy arrays.  Random access (rather than iteration) is what
makes deterministic per-rank sharding, elastic re-sharding and epoch
shuffling composable on top (sharding.py) — the same reason the
reference's ElasticSampler deals in indices.

Three on-disk/in-memory source families ship here:

* :class:`SyntheticSource` — deterministic random tensors, the bench's
  classic workload, now behind the same interface as real data;
* :class:`NpyShardSource` — directories of ``*-inputs.npy`` /
  ``*-labels.npy`` shard pairs, memory-mapped so a worker touches only
  the rows its shard reads (the array analog of Petastorm row groups;
  :func:`write_npy_shards` produces the layout);
* :class:`ImageFolderSource` — the torchvision ``ImageFolder`` layout
  (``root/<class>/<image>``), PIL-decoded and resized host-side.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "DataSource",
    "ArraySource",
    "SyntheticSource",
    "NpyShardSource",
    "ImageFolderSource",
    "write_npy_shards",
    "open_source",
]

#: File extensions ImageFolderSource admits (PIL handles all of them).
_IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp")


class DataSource:
    """Random-access sample store.

    Subclasses implement :meth:`__len__` and :meth:`sample`; ``batch`` has
    a generic gather-and-stack default that sources with a cheaper bulk
    path (mmap fancy-indexing, vectorized synthesis) override.
    """

    #: short label for metrics / bench JSON ("synthetic", "npy", ...)
    kind = "custom"

    def __len__(self) -> int:
        raise NotImplementedError

    def sample(self, index: int) -> Tuple[np.ndarray, np.ndarray]:
        """Return ``(input, label)`` numpy arrays for one sample."""
        raise NotImplementedError

    def batch(self, indices: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        """Gather ``indices`` into stacked ``(inputs, labels)`` arrays."""
        pairs = [self.sample(int(i)) for i in indices]
        inputs = np.stack([p[0] for p in pairs])
        labels = np.asarray([p[1] for p in pairs])
        return inputs, labels


class ArraySource(DataSource):
    """In-memory arrays — the trivial source (and the test workhorse)."""

    kind = "array"

    def __init__(self, inputs: np.ndarray, labels: np.ndarray):
        if len(inputs) != len(labels):
            raise ValueError(
                f"inputs ({len(inputs)}) and labels ({len(labels)}) "
                "disagree on sample count"
            )
        self.inputs = inputs
        self.labels = labels

    def __len__(self) -> int:
        return len(self.inputs)

    def sample(self, index: int) -> Tuple[np.ndarray, np.ndarray]:
        return self.inputs[index], self.labels[index]

    def batch(self, indices: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        idx = np.asarray(indices)
        return self.inputs[idx], self.labels[idx]


class SyntheticSource(DataSource):
    """Deterministic random ImageNet-shaped samples.

    Index ``i`` always yields the same tensor regardless of sharding or
    epoch, so elastic re-shards see a consistent dataset.  Synthesis is
    vectorized per batch (one RandomState per sample would dominate at
    small images).
    """

    kind = "synthetic"

    def __init__(self, num_samples: int, image_size: int = 224,
                 channels: int = 3, num_classes: int = 1000,
                 seed: int = 0, dtype=np.float32):
        self.num_samples = int(num_samples)
        self.image_size = int(image_size)
        self.channels = int(channels)
        self.num_classes = int(num_classes)
        self.seed = int(seed)
        self.dtype = np.dtype(dtype)

    def __len__(self) -> int:
        return self.num_samples

    def sample(self, index: int) -> Tuple[np.ndarray, np.ndarray]:
        inputs, labels = self.batch([index])
        return inputs[0], labels[0]

    def batch(self, indices: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        idx = np.asarray(indices, dtype=np.int64)
        shape = (len(idx), self.image_size, self.image_size, self.channels)
        # per-sample determinism independent of batch composition: sample
        # i's bytes come from a counter-based Philox stream keyed (seed, i)
        rows = np.empty(shape, dtype=self.dtype)
        for row, i in enumerate(idx):
            g = np.random.Generator(np.random.Philox(key=self.seed + 1,
                                                     counter=int(i)))
            rows[row] = g.standard_normal(shape[1:], dtype=np.float32)
        labels = (idx * 2654435761 + self.seed) % self.num_classes
        return rows, labels.astype(np.int32)


class NpyShardSource(DataSource):
    """Directory of ``<stem>-inputs.npy`` / ``<stem>-labels.npy`` pairs.

    Shards are opened with ``mmap_mode="r"`` so construction is O(#shards)
    metadata reads and a batch read touches only the pages its rows live
    on — the property that lets a 100 GB dataset feed a host with a few
    GB of RAM.  A single un-sharded ``inputs.npy``/``labels.npy`` pair is
    the degenerate one-shard case of the same layout.
    """

    kind = "npy"

    def __init__(self, root: str):
        self.root = str(root)
        stems = sorted(
            f[: -len("-inputs.npy")]
            for f in os.listdir(self.root)
            if f.endswith("-inputs.npy")
        )
        if os.path.exists(os.path.join(self.root, "inputs.npy")):
            stems.insert(0, "")
        if not stems:
            raise FileNotFoundError(
                f"no '*-inputs.npy' shards under {self.root!r} "
                "(see horovod_tpu.data.write_npy_shards)"
            )
        self._inputs = []
        self._labels = []
        lengths = []
        for stem in stems:
            prefix = f"{stem}-" if stem else ""
            x = np.load(os.path.join(self.root, f"{prefix}inputs.npy"),
                        mmap_mode="r")
            y = np.load(os.path.join(self.root, f"{prefix}labels.npy"),
                        mmap_mode="r")
            if len(x) != len(y):
                raise ValueError(
                    f"shard {stem or 'inputs'!r}: inputs ({len(x)}) and "
                    f"labels ({len(y)}) disagree on sample count"
                )
            self._inputs.append(x)
            self._labels.append(y)
            lengths.append(len(x))
        self._offsets = np.concatenate([[0], np.cumsum(lengths)])

    def __len__(self) -> int:
        return int(self._offsets[-1])

    def _locate(self, index: int) -> Tuple[int, int]:
        shard = int(np.searchsorted(self._offsets, index, side="right")) - 1
        return shard, index - int(self._offsets[shard])

    def sample(self, index: int) -> Tuple[np.ndarray, np.ndarray]:
        s, off = self._locate(int(index))
        return np.asarray(self._inputs[s][off]), np.asarray(
            self._labels[s][off])

    def batch(self, indices: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        idx = np.asarray(indices, dtype=np.int64)
        shard_ids = np.searchsorted(self._offsets, idx, side="right") - 1
        first = self._inputs[0]
        inputs = np.empty((len(idx),) + first.shape[1:], dtype=first.dtype)
        labels = np.empty((len(idx),), dtype=self._labels[0].dtype)
        # group by shard so each mmap is fancy-indexed once per batch
        for s in np.unique(shard_ids):
            rows = np.nonzero(shard_ids == s)[0]
            local = idx[rows] - int(self._offsets[s])
            order = np.argsort(local)  # mmap reads like sequential order
            inputs[rows[order]] = self._inputs[s][local[order]]
            labels[rows[order]] = self._labels[s][local[order]]
        return inputs, labels


class ImageFolderSource(DataSource):
    """``root/<class_name>/<image file>`` — the torchvision ImageFolder
    layout, decoded with PIL and resized host-side.

    The decode is the worker pool's job (workers.py): PIL releases the
    GIL inside decode/resize, so threads parallelize it.
    """

    kind = "folder"

    def __init__(self, root: str, image_size: int = 224,
                 classes: Optional[Sequence[str]] = None):
        try:
            from PIL import Image  # noqa: F401
        except ImportError as e:  # pragma: no cover - PIL ships in image
            raise ImportError(
                "ImageFolderSource needs Pillow for image decode "
                "(pip install Pillow)"
            ) from e
        self.root = str(root)
        self.image_size = int(image_size)
        if classes is None:
            classes = sorted(
                d for d in os.listdir(self.root)
                if os.path.isdir(os.path.join(self.root, d))
            )
        self.classes = list(classes)
        if not self.classes:
            raise FileNotFoundError(
                f"no class directories under {self.root!r} "
                "(expected root/<class>/<image> layout)"
            )
        self._files = []
        self._file_labels = []
        for label, cls in enumerate(self.classes):
            cdir = os.path.join(self.root, cls)
            for f in sorted(os.listdir(cdir)):
                if f.lower().endswith(_IMAGE_EXTS):
                    self._files.append(os.path.join(cdir, f))
                    self._file_labels.append(label)
        if not self._files:
            raise FileNotFoundError(
                f"no image files ({'/'.join(_IMAGE_EXTS)}) under "
                f"{self.root!r}"
            )

    def __len__(self) -> int:
        return len(self._files)

    def sample(self, index: int) -> Tuple[np.ndarray, np.ndarray]:
        from PIL import Image

        with Image.open(self._files[index]) as im:
            im = im.convert("RGB")
            # resize-shortest-side + center crop: the standard eval
            # transform; augmentation belongs in the loader's transform
            w, h = im.size
            scale = self.image_size / min(w, h)
            im = im.resize((max(self.image_size, round(w * scale)),
                            max(self.image_size, round(h * scale))))
            w, h = im.size
            left = (w - self.image_size) // 2
            top = (h - self.image_size) // 2
            im = im.crop((left, top, left + self.image_size,
                          top + self.image_size))
            arr = np.asarray(im, dtype=np.uint8)
        return arr, np.int32(self._file_labels[index])


def write_npy_shards(root: str, inputs: np.ndarray, labels: np.ndarray,
                     num_shards: int = 1) -> list:
    """Write ``inputs``/``labels`` as the NpyShardSource layout.

    Returns the shard stems written.  Used by tests, by ``bench.py
    --data npy`` self-seeding, and as the documented way to materialize
    a real-array dataset for the pipeline.
    """
    if len(inputs) != len(labels):
        raise ValueError("inputs and labels disagree on sample count")
    if num_shards < 1 or num_shards > max(len(inputs), 1):
        raise ValueError(f"bad num_shards {num_shards} for "
                         f"{len(inputs)} samples")
    os.makedirs(root, exist_ok=True)
    stems = []
    bounds = np.linspace(0, len(inputs), num_shards + 1, dtype=np.int64)
    for s in range(num_shards):
        lo, hi = int(bounds[s]), int(bounds[s + 1])
        stem = f"shard-{s:05d}"
        np.save(os.path.join(root, f"{stem}-inputs.npy"), inputs[lo:hi])
        np.save(os.path.join(root, f"{stem}-labels.npy"), labels[lo:hi])
        stems.append(stem)
    return stems


def open_source(kind: str, path: Optional[str] = None,
                image_size: int = 224, **synthetic_kwargs) -> DataSource:
    """Open a source by bench-flag name (``synthetic``/``npy``/``folder``)."""
    if kind == "synthetic":
        return SyntheticSource(image_size=image_size, **synthetic_kwargs)
    if path is None:
        raise ValueError(f"--data {kind} requires a dataset path")
    if kind == "npy":
        return NpyShardSource(path)
    if kind == "folder":
        return ImageFolderSource(path, image_size=image_size)
    raise ValueError(f"unknown data source kind {kind!r} "
                     "(expected synthetic|npy|folder)")
