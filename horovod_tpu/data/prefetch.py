"""Double-buffered device prefetcher: overlap host->device with compute.

The one structural fact about feeding a TPU from the host: the transfer
of batch N+1 must run while batch N computes, or every step pays
``transfer + compute`` instead of ``max(transfer, compute)``.  XLA gives
no free overlap for host-produced arrays — ``jax.device_put`` must be
*issued* before the step needs the data — so a background thread stages
batches into a bounded queue of device-resident arrays ahead of the
training thread.

``depth`` (``HVD_TPU_PREFETCH_DEPTH``, default 2) is the double buffer:
one batch on device being consumed, one in flight.  Deeper queues buy
tolerance to host-side jitter (a slow decode burst) at the cost of HBM
for the staged batches; depth 2 is the classic sweet spot and matches
what flax's ``jax_utils.prefetch_to_device`` defaults to.

Instrumented via the PR-1 metrics subsystem: queue-depth gauge, host-wait
(input starvation) and produce/transfer histograms.  Local counters are
mirrored in :meth:`stats` so bench.py can emit them in its result JSON
without scraping the registry.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, Iterable, Iterator, Optional

import numpy as np

from .. import chaos as _chaos
from .. import trace
from ..metrics import instruments as _instr

__all__ = ["DevicePrefetcher", "prefetch_to_device", "default_prefetch_depth"]

#: Env knob: staged device batches (0 = prefetch off, synchronous puts).
PREFETCH_ENV = "HVD_TPU_PREFETCH_DEPTH"

_SENTINEL = object()


def default_prefetch_depth() -> int:
    env = os.environ.get(PREFETCH_ENV)
    if env is not None:
        n = int(env)
        if n < 0:
            raise ValueError(f"{PREFETCH_ENV} must be >= 0, got {n}")
        return n
    return 2


def _host_cast(batch, cast):
    """Apply the host-side dtype cast to the float arrays of a batch.

    Casting fp32 image tensors to bf16 on the host halves the bytes that
    cross PCIe / the tunnel — the transfer is the scarce resource, and
    the first conv consumes bf16 anyway (the on-device cast is free but
    the transfer of the fp32 bytes is not).  Integer arrays (labels) pass
    through untouched.
    """
    if cast is None:
        return batch
    dtype = np.dtype(cast)
    return tuple(
        np.asarray(a, dtype=dtype)
        if isinstance(a, np.ndarray) and np.issubdtype(a.dtype, np.floating)
        else a
        for a in batch
    )


class DevicePrefetcher:
    """Iterate device-resident batches, staged ``depth`` ahead.

    Wraps an iterator of host batches (tuples of numpy arrays).  Each
    batch is optionally cast (``cast="bfloat16"``), placed with
    ``jax.device_put`` (optionally against an explicit ``sharding``), and
    queued.  With ``depth=0`` the prefetch thread is bypassed entirely —
    synchronous per-next staging, the A/B baseline for measuring what
    the overlap is worth.

    The background thread is a daemon and also shuts down cleanly on
    ``close()``/GC; a producer exception re-raises on the consumer side
    in order.

    **Long-lived (serving) use.**  Exhaustion is sticky on purpose for
    the epoch-loop case — iterating past the end keeps raising
    StopIteration instead of silently re-reading — but a *staging queue*
    (the serving engine's request intake) outlives any one stream, so
    the lifecycle is explicit: :meth:`restart` re-arms an exhausted or
    closed prefetcher on a fresh iterable (cumulative :meth:`stats`
    keep summing), and :meth:`poll` is the non-blocking consume —
    ``None`` while the producer is still staging, :data:`EXHAUSTED`
    once the stream truly ended.
    """

    #: poll() return marker: the current stream ended (sticky until
    #: restart()).  Distinct from None = nothing staged *yet*.
    EXHAUSTED = object()

    def __init__(self, host_batches: Iterable, *,
                 depth: Optional[int] = None,
                 cast: Optional[str] = None,
                 sharding=None,
                 device_put: bool = True,
                 source_kind: str = "custom",
                 put_timing: Optional[Callable[[], None]] = None):
        del put_timing  # reserved
        self._host_iter = iter(host_batches)
        self.depth = default_prefetch_depth() if depth is None else int(depth)
        self.cast = cast
        self.sharding = sharding
        self.device_put = device_put
        self.source_kind = source_kind
        # local mirrors of the registry instruments, for bench JSON
        self._batches = 0
        self._wait_s = 0.0
        self._produce_s = 0.0
        self._put_s = 0.0
        self._starved = 0
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop: Optional[threading.Event] = None
        self._closed = False
        self._exhausted = False
        self._start()

    def _start(self) -> None:
        if self.depth > 0:
            self._queue = queue.Queue(maxsize=self.depth)
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._producer, name="hvd-tpu-prefetch", daemon=True)
            self._thread.start()

    # -- staging -------------------------------------------------------------

    def _stage(self, batch):
        """Cast + device_put one host batch; returns the staged batch."""
        # chaos: delay = staging jitter; raise/drop re-raise on the
        # consumer side through the queue; hang freezes the producer
        # thread (the training thread then starves — the input-bound
        # failure mode)
        if _chaos.active:
            _chaos.raise_point("data.prefetch")
        t0 = time.perf_counter()
        batch = _host_cast(batch, self.cast)
        if self.device_put:
            import jax

            if self.sharding is not None:
                batch = jax.device_put(batch, self.sharding)
            else:
                batch = jax.device_put(batch)
        dt = time.perf_counter() - t0
        self._put_s += dt
        _instr.DATA_DEVICE_PUT.observe(dt)
        trace.add_span("data.device_put", t0, t0 + dt)
        return batch

    def _producer(self):
        # bind queue, iterator AND stop event locally: after restart()
        # replaces them, a producer that was blocked past the close()
        # join deadline must keep talking to ITS stream's queue — and
        # must still see ITS stream's stop request (a shared _closed
        # flag would be reset by restart(), resurrecting the zombie to
        # keep consuming the abandoned iterator forever)
        q, it, stop = self._queue, self._host_iter, self._stop
        try:
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    q.put(_SENTINEL)
                    return
                dt = time.perf_counter() - t0
                self._produce_s += dt
                trace.add_span("data.produce", t0, t0 + dt)
                q.put(self._stage(item))
        except BaseException as e:  # re-raise on the consumer side
            q.put(e)

    # -- iteration -----------------------------------------------------------

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self.depth == 0:
            # synchronous path: the measured baseline without overlap
            t0 = time.perf_counter()
            try:
                item = next(self._host_iter)
            except StopIteration:
                self._exhausted = True
                raise
            self._produce_s += time.perf_counter() - t0
            staged = self._stage(item)
            self._account_delivery(waited=0.0)
            return staged
        t0 = time.perf_counter()
        item = self._queue.get()
        waited = time.perf_counter() - t0
        out = self._resolve(item)
        if out is self.EXHAUSTED:
            raise StopIteration
        self._account_delivery(waited=waited)
        return out

    def _resolve(self, item):
        """Queue item -> delivered batch, EXHAUSTED, or raised error."""
        if item is _SENTINEL:
            self._queue.put(_SENTINEL)  # idempotent exhaustion
            self._exhausted = True
            return self.EXHAUSTED
        if isinstance(item, BaseException):
            self._queue.put(item)
            raise item
        return item

    def poll(self, block: bool = False):
        """Non-blocking consume for long-lived (staging-queue) use:
        returns a staged batch, ``None`` when nothing is staged yet, or
        :data:`EXHAUSTED` once the stream ended.  ``block=True`` waits
        like ``next`` but still returns EXHAUSTED instead of raising.
        With ``depth=0`` there is no queue to peek — any poll runs the
        synchronous ``next`` (i.e. it may block on the host iterator).
        """
        if self._closed:
            # close() drained the queue (sentinel included) and the
            # producer exited without re-queueing it — a blocking get
            # here would hang forever; closed is terminal like exhausted
            return self.EXHAUSTED
        if self.depth == 0:
            try:
                return next(self)
            except StopIteration:
                return self.EXHAUSTED
        t0 = time.perf_counter()
        try:
            item = self._queue.get(block=block)
        except queue.Empty:
            return None
        out = self._resolve(item)
        if out is self.EXHAUSTED:
            return out
        self._account_delivery(waited=time.perf_counter() - t0)
        return out

    def _account_delivery(self, waited: float) -> None:
        self._batches += 1
        self._wait_s += waited
        if waited > 0.001:
            # span the INPUT WAIT (host starvation) only when it is
            # real — a hot queue would otherwise spam ~0-width spans
            end = time.perf_counter()
            trace.add_span("data.wait", end - waited, end)
            self._starved += 1
        _instr.DATA_HOST_WAIT.observe(waited)
        _instr.DATA_BATCHES.labels(source=self.source_kind).inc()
        _instr.DATA_PREFETCH_DEPTH.set(
            self._queue.qsize() if self._queue is not None else 0)

    # -- stats / lifecycle ---------------------------------------------------

    def stats(self) -> dict:
        """Pipeline counters for this iterator's lifetime (bench JSON).
        ``*_total`` fields sum cleanly across epoch iterators; the means
        are per delivered batch."""
        n = max(self._batches, 1)
        return {
            "batches": self._batches,
            "prefetch_depth": self.depth,
            "input_wait_ms_total": round(self._wait_s * 1e3, 3),
            "input_wait_ms_mean": round(self._wait_s / n * 1e3, 3),
            "host_produce_ms_total": round(self._produce_s * 1e3, 3),
            "host_produce_ms_mean": round(self._produce_s / n * 1e3, 3),
            "device_put_ms_total": round(self._put_s * 1e3, 3),
            "device_put_ms_mean": round(self._put_s / n * 1e3, 3),
            "starved_batches": self._starved,
        }

    @property
    def exhausted(self) -> bool:
        """True once the host iterator's end was delivered to the
        consumer (sticky until :meth:`restart`)."""
        return self._exhausted

    @property
    def closed(self) -> bool:
        return self._closed

    def restart(self, host_batches: Iterable) -> None:
        """Re-arm on a fresh host iterable — the explicit reuse contract
        for long-lived staging queues (one prefetcher per serving
        engine, not one per stream).  Only legal once the previous
        stream is done: exhausted, or torn down with :meth:`close` (an
        active stream's producer thread would race the new one).
        Cumulative :meth:`stats` keep summing across streams."""
        if not (self._exhausted or self._closed):
            raise RuntimeError(
                "restart() on an active prefetcher; close() it or drain "
                "it to exhaustion first")
        if self._thread is not None:
            self._closed = True
            self._stop.set()  # per-stream: survives the _closed reset below
            self._drain_queue()  # unblock a producer parked on a full queue
            self._thread.join(timeout=5)
        self._host_iter = iter(host_batches)
        self._closed = False
        self._exhausted = False
        self._start()

    def _drain_queue(self) -> None:
        if self._queue is None:
            return
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass

    def close(self) -> None:
        self._closed = True
        if self._stop is not None:
            self._stop.set()
        self._drain_queue()  # unblock a producer waiting on a full queue
        if self._thread is not None:
            self._thread.join(timeout=5)
        # release the upstream pipeline too (map_ordered holds a worker
        # pool open until its generator is closed)
        close_upstream = getattr(self._host_iter, "close", None)
        if close_upstream is not None:
            try:
                close_upstream()
            except Exception:
                pass  # generator mid-next on a stuck thread: GC handles it

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass


def prefetch_to_device(host_batches: Iterable, depth: Optional[int] = None,
                       **kwargs) -> DevicePrefetcher:
    """Functional spelling of :class:`DevicePrefetcher` (flax-idiom name)."""
    return DevicePrefetcher(host_batches, depth=depth, **kwargs)
