"""DataLoader: sharded source -> worker pool -> device prefetcher.

The one object user code touches.  Equivalent composed pipeline::

    sampler = ShardedIndexSampler(len(source), ...)      # sharding.py
    host    = map_ordered(collate, sampler.batches(bs))  # workers.py
    batches = DevicePrefetcher(host, depth=2)            # prefetch.py

Usage (the drop-in loop for training.py's compiled step)::

    loader = hvd.data.DataLoader(source, batch_size=128, cast="bfloat16")
    for epoch in range(epochs):
        loader.set_epoch(epoch)
        for images, labels in loader:        # device-resident already
            state, loss = step(state, images, labels)

``batch_size`` is per shard (= per process).  The shard resolves from the
live topology at each ``__iter__`` — an elastic exec-restart lands in a
new world and the next epoch re-shards with no user code (steady-state
path; mid-epoch rollback accounting remains ``ElasticSampler``'s job).
"""

from __future__ import annotations

import time
from typing import Callable, Iterator, Optional

from .. import chaos as _chaos
from ..metrics import instruments as _instr
from . import prefetch as _prefetch
from . import sharding as _sharding
from . import workers as _workers
from .sources import DataSource, open_source

__all__ = ["DataLoader", "make_loader"]


class DataLoader:
    """Sharded, worker-fed, device-prefetched batch iterator.

    Args:
      source: a :class:`~horovod_tpu.data.DataSource`.
      batch_size: samples per batch *per shard* (per process).
      shuffle/seed: epoch shuffling of the global index order.
      drop_remainder: keep batch shapes static (no tail recompile).
      transform: ``fn(inputs, labels) -> (inputs, labels)`` applied on the
        worker pool (augmentation, normalization, dtype massaging).
      num_workers: host decode threads (default ``HVD_TPU_DATA_WORKERS``).
      prefetch_depth: staged device batches (default
        ``HVD_TPU_PREFETCH_DEPTH``); 0 = synchronous staging.
      cast: host-side dtype cast for float arrays ("bfloat16" halves the
        host->device bytes).
      sharding: optional ``jax.sharding.Sharding`` for the device
        placement of each batch (multi-chip processes).
      device_put: False yields host numpy batches — the torch/mxnet
        adapter path, where the framework owns device placement.
      shard: pin a :class:`ShardSpec` (tests); default = live topology.
    """

    def __init__(self, source: DataSource, batch_size: int, *,
                 shuffle: bool = True, seed: int = 0,
                 drop_remainder: bool = True,
                 transform: Optional[Callable] = None,
                 num_workers: Optional[int] = None,
                 prefetch_depth: Optional[int] = None,
                 cast: Optional[str] = None,
                 sharding=None,
                 device_put: bool = True,
                 shard: Optional[_sharding.ShardSpec] = None):
        self.source = source
        self.batch_size = int(batch_size)
        self.transform = transform
        self.num_workers = num_workers
        self.prefetch_depth = prefetch_depth
        self.cast = cast
        self.sharding = sharding
        self.device_put = device_put
        self.sampler = _sharding.ShardedIndexSampler(
            len(source), shard=shard, shuffle=shuffle, seed=seed,
            drop_remainder=drop_remainder)
        self._last: Optional[_prefetch.DevicePrefetcher] = None

    # -- epoch plumbing ------------------------------------------------------

    def set_epoch(self, epoch: int) -> None:
        """New epoch: fresh shuffle (mirrors DistributedSampler.set_epoch)."""
        self.sampler.set_epoch(epoch)

    def __len__(self) -> int:
        """Batches this shard yields per epoch."""
        return self.sampler.num_batches(self.batch_size)

    # -- iteration -----------------------------------------------------------

    def _collate(self, indices):
        # chaos: delay = a slow decode burst; raise/drop = a decode
        # failure surfacing at the training thread's yield point (the
        # ordered window then cancels the in-flight tail)
        if _chaos.active and _chaos.point("data.batch") is _chaos.DROP:
            raise _chaos.ChaosInjected("chaos: batch dropped at data.batch")
        t0 = time.perf_counter()
        inputs, labels = self.source.batch(indices)
        if self.transform is not None:
            inputs, labels = self.transform(inputs, labels)
        _instr.DATA_BATCH_PRODUCE.observe(time.perf_counter() - t0)
        return inputs, labels

    def __iter__(self) -> Iterator:
        if self._last is not None:
            # an abandoned prior iteration (break / next(iter(loader)))
            # must not keep its producer thread and staged device batches
            # alive — close it before building the new pipeline
            self._last.close()
        workers = (_workers.default_num_workers()
                   if self.num_workers is None else self.num_workers)
        depth = (_prefetch.default_prefetch_depth()
                 if self.prefetch_depth is None else self.prefetch_depth)
        host = _workers.map_ordered(
            self._collate, self.sampler.batches(self.batch_size),
            num_workers=workers,
            # the decode window feeds the staging queue: one extra batch
            # cooking per staged slot keeps the pool busy across jitter
            window=max(2 * max(depth, 1), workers or 1),
        )
        self._last = _prefetch.DevicePrefetcher(
            host, depth=depth, cast=self.cast, sharding=self.sharding,
            device_put=self.device_put, source_kind=self.source.kind)
        return self._last

    # -- instrumentation -----------------------------------------------------

    def stats(self) -> dict:
        """Pipeline stats of the most recent iteration (bench JSON)."""
        if self._last is None:
            return {}
        return self._last.stats()


def make_loader(data: str, path: Optional[str] = None, *,
                batch_size: int, image_size: int = 224,
                synthetic_samples: int = 2048,
                seed: int = 0, **loader_kwargs) -> DataLoader:
    """Build a loader from bench-style flags (``--data``/``--data-path``).

    ``synthetic`` ignores ``path`` and serves ``synthetic_samples``
    deterministic ImageNet-shaped samples; ``npy``/``folder`` open the
    on-disk layouts (sources.py).  uint8 image sources are normalized to
    float32 in [0, 1] on the worker pool, matching the standard decode
    path.
    """
    source = open_source(data, path, image_size=image_size,
                         **({"num_samples": synthetic_samples,
                             "seed": seed} if data == "synthetic" else {}))
    transform = loader_kwargs.pop("transform", None)
    if transform is None and data in ("npy", "folder"):
        transform = _normalize_uint8
    return DataLoader(source, batch_size, transform=transform,
                      seed=seed, **loader_kwargs)


def _normalize_uint8(inputs, labels):
    import numpy as np

    if inputs.dtype == np.uint8:
        inputs = inputs.astype(np.float32) / 255.0
    return inputs, labels
