"""``horovod_tpu.data`` — the async device-feeding input pipeline.

The prerequisite for real-workload throughput: a per-rank sharded dataset
(driven by the live topology, so elastic restarts re-shard), a host-side
worker pool for decode/augment, and a double-buffered device prefetcher
that stages batch N+1 while batch N computes.  See ``docs/DATA.md``.

Quick start::

    import horovod_tpu as hvd
    from horovod_tpu import data

    hvd.init()
    loader = data.make_loader("npy", "/data/imagenet-npy",
                              batch_size=128, cast="bfloat16")
    for epoch in range(90):
        loader.set_epoch(epoch)
        for images, labels in loader:      # device-resident, prefetched
            state, loss = step(state, images, labels)

Env knobs: ``HVD_TPU_DATA_WORKERS`` (decode threads),
``HVD_TPU_PREFETCH_DEPTH`` (staged device batches, 0 = off).
"""

from .loader import DataLoader, make_loader
from .prefetch import (
    DevicePrefetcher,
    default_prefetch_depth,
    prefetch_to_device,
)
from .sharding import ShardSpec, ShardedIndexSampler, current_shard
from .sources import (
    ArraySource,
    DataSource,
    ImageFolderSource,
    NpyShardSource,
    SyntheticSource,
    open_source,
    write_npy_shards,
)
from .workers import default_num_workers, map_ordered

__all__ = [
    "DataLoader",
    "make_loader",
    "DevicePrefetcher",
    "prefetch_to_device",
    "default_prefetch_depth",
    "ShardSpec",
    "ShardedIndexSampler",
    "current_shard",
    "ArraySource",
    "DataSource",
    "ImageFolderSource",
    "NpyShardSource",
    "SyntheticSource",
    "open_source",
    "write_npy_shards",
    "default_num_workers",
    "map_ordered",
]
