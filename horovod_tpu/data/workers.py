"""Host-side worker pool: parallel decode/collate with ordered delivery.

The hot property is the *bounded in-flight window*: up to ``window``
batches are being decoded concurrently while results are handed out in
submission order.  That keeps (a) batch order deterministic — the
compiled step's inputs must not depend on thread scheduling, (b) host
memory bounded — at most ``window`` decoded batches exist at once, and
(c) the pool saturated — a slow batch (cold page cache, big JPEG) does
not drain the pipeline because the window keeps later batches cooking.

Threads, not processes: the work is numpy slicing and PIL decode, both of
which release the GIL, and thread workers share the sources' mmaps
without pickling.  ``HVD_TPU_DATA_WORKERS=0`` degrades to synchronous
inline decode (debugging, single-threaded determinism checks).
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator, Optional, TypeVar

__all__ = ["default_num_workers", "map_ordered"]

T = TypeVar("T")
R = TypeVar("R")

#: Env knob: host decode/collate threads (0 = inline, no pool).
WORKERS_ENV = "HVD_TPU_DATA_WORKERS"


def default_num_workers() -> int:
    """``HVD_TPU_DATA_WORKERS`` or min(4, cpu_count).

    Four threads decode ~1 GB/s of JPEG on a typical host — past the
    point where a single PCIe/tunnel transfer stream is the bottleneck —
    while staying polite on shared CI boxes.
    """
    env = os.environ.get(WORKERS_ENV)
    if env is not None:
        n = int(env)
        if n < 0:
            raise ValueError(f"{WORKERS_ENV} must be >= 0, got {n}")
        return n
    return min(4, os.cpu_count() or 1)


def map_ordered(fn: Callable[[T], R], items: Iterable[T], *,
                num_workers: Optional[int] = None,
                window: int = 4) -> Iterator[R]:
    """Yield ``fn(item)`` in input order with a bounded concurrent window.

    Generator-lazy: nothing is submitted until iteration starts, and at
    most ``window`` futures are in flight.  An exception from ``fn``
    propagates at the yield point for its item (order preserved), after
    which the remaining window is cancelled.
    """
    if num_workers is None:
        num_workers = default_num_workers()
    if num_workers == 0:
        for item in items:
            yield fn(item)
        return
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")

    with ThreadPoolExecutor(
        max_workers=num_workers,
        thread_name_prefix="hvd-tpu-data",
    ) as pool:
        it = iter(items)
        inflight = []
        try:
            for item in it:
                inflight.append(pool.submit(fn, item))
                if len(inflight) >= window:
                    yield inflight.pop(0).result()
            while inflight:
                yield inflight.pop(0).result()
        finally:
            for f in inflight:
                f.cancel()
