"""Per-rank dataset sharding driven by the live topology.

Reference analog: ``torch.utils.data.DistributedSampler`` as used by every
reference example, plus the re-shard-on-reset behavior of its elastic
sampler (horovod/torch/elastic/sampler.py — already mirrored by
``horovod_tpu.elastic.ElasticSampler`` for the rollback-window case).

The split here is deliberately the same as the reference's: shuffle the
epoch's indices with a world-independent permutation (seeded by
``seed + epoch``), truncate to a multiple of the world size, and stride
the result across ranks.  Because the permutation does not depend on the
world, an elastic restart that changes ``num_shards`` re-shards the SAME
epoch ordering — ranks see disjoint, jointly-exhaustive slices before and
after the resize (mid-epoch progress accounting stays ElasticSampler's
job; this sampler is the steady-state/per-epoch path).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional

import numpy as np

__all__ = ["ShardSpec", "current_shard", "ShardedIndexSampler"]


@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """Which slice of the dataset this process reads."""

    shard: int
    num_shards: int

    def __post_init__(self):
        if not 0 <= self.shard < self.num_shards:
            raise ValueError(
                f"shard {self.shard} out of range [0, {self.num_shards})"
            )


def current_shard() -> ShardSpec:
    """The live process's shard, from ``common.topology`` rank/size.

    One shard per *process* (``cross_rank``/``cross_size``): a process
    feeds all its local chips from one host pipeline, and the in-step
    sharding over local devices is the mesh's job (``P(axis)`` in
    training.py).  Before ``hvd.init()`` — or on a single-process world —
    the whole dataset is one shard, so the loader works standalone.
    Resolved at call time, never cached: an elastic exec-restart lands in
    a new world and the next epoch re-shards automatically.
    """
    import horovod_tpu as hvd

    if hvd.is_initialized():
        return ShardSpec(hvd.cross_rank(), max(hvd.cross_size(), 1))
    return ShardSpec(0, 1)


class ShardedIndexSampler:
    """Deterministic per-epoch index stream for one shard.

    ``batches(batch_size)`` yields ``np.ndarray`` index blocks of exactly
    ``batch_size`` (``drop_remainder=True``, the default, keeps the
    compiled step's shapes constant — a ragged tail batch would trigger
    an XLA recompile per epoch) for this rank's slice of the shuffled
    epoch ordering.
    """

    def __init__(self, num_samples: int, *, shard: Optional[ShardSpec] = None,
                 shuffle: bool = True, seed: int = 0,
                 drop_remainder: bool = True):
        if num_samples <= 0:
            raise ValueError(f"empty dataset (num_samples={num_samples})")
        self.num_samples = int(num_samples)
        self._fixed_shard = shard
        self.shuffle = shuffle
        self.seed = int(seed)
        self.drop_remainder = drop_remainder
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = int(epoch)

    @property
    def shard(self) -> ShardSpec:
        return self._fixed_shard or current_shard()

    def shard_indices(self) -> np.ndarray:
        """This rank's slice of the current epoch's global ordering."""
        order = np.arange(self.num_samples)
        if self.shuffle:
            np.random.RandomState(self.seed + self.epoch).shuffle(order)
        spec = self.shard
        # truncate so every shard has identical length (the reference's
        # DistributedSampler drops the tail the same way); strided so a
        # world resize re-slices the same ordering
        per = self.num_samples // spec.num_shards
        if per == 0:
            raise ValueError(
                f"dataset of {self.num_samples} samples cannot feed "
                f"{spec.num_shards} shards"
            )
        return order[: per * spec.num_shards][spec.shard :: spec.num_shards]

    def num_batches(self, batch_size: int) -> int:
        n = len(self.shard_indices())
        if self.drop_remainder:
            return n // batch_size
        return -(-n // batch_size)

    def batches(self, batch_size: int) -> Iterator[np.ndarray]:
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        idx = self.shard_indices()
        stop = (len(idx) // batch_size) * batch_size if self.drop_remainder \
            else len(idx)
        for lo in range(0, stop, batch_size):
            yield idx[lo : lo + batch_size]
