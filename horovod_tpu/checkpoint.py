"""Checkpoint save/resume helpers.

Reference parity (SURVEY.md §5.4): the reference has no bespoke format —
rank 0 writes a framework checkpoint, resume re-broadcasts from root
(examples/ pattern: ``torch.save`` + ``broadcast_parameters`` +
``broadcast_optimizer_state``).  This module packages exactly that
pattern for the JAX loop:

  * :func:`save_checkpoint` — rank 0 serializes the state pytree
    (flax msgpack; any pytree of arrays works) to ``<dir>/ckpt-<step>``;
  * :func:`restore_checkpoint` — every worker reads the latest checkpoint
    if present (shared filesystem), or rank 0 reads and the state is
    broadcast (``broadcast=True``) — the §5.4(b) resume flow.
  * :func:`save_state_checkpoint` / :func:`restore_state_checkpoint` —
    the same contract for ``hvd.elastic`` object states (pickled
    snapshots), feeding the elastic auto-resume path
    (``state.enable_auto_resume``; docs/FAULT_TOLERANCE.md).

Both families use ``ckpt-<step>`` names so :func:`latest_checkpoint`
serves either — but use ONE family per directory: a same-step save from
the other family would overwrite, and pruning counts them together.
Cross-family reads fail loudly (the state format carries a magic
header), never with a bare deserialization error.

Every write is CRASH-ATOMIC: the payload goes to a uniquely named temp
file in the same directory, is fsync'd, and is published with
``os.replace`` — a worker killed mid-save (the exact fault the chaos
subsystem injects) can leave a stray ``.tmp`` behind but never a
truncated ``ckpt-N`` that :func:`latest_checkpoint` would then resume
from.  Stale temp files are swept by the same pruning pass that trims
old checkpoints.

Orbax remains the right tool for sharded multi-host checkpoints of very
large models; these helpers cover the reference's replicated-weights
contract without extra dependencies.
"""

from __future__ import annotations

import os
import pickle
import re
import time
from typing import Any, Optional, Tuple

import flax.serialization
import jax
import numpy as np

from . import functions
from .common import basics

_CKPT_RE = re.compile(r"^ckpt-(\d+)$")
_TMP_RE = re.compile(r"^ckpt-\d+\.tmp\.\d+$")

#: Header distinguishing pickled elastic-state checkpoints from flax
#: msgpack pytree checkpoints (both live under the same ckpt-N names so
#: latest_checkpoint() serves either family).
_STATE_MAGIC = b"HVDTPU-STATE1\n"


def _is_root() -> bool:
    return not basics.is_initialized() or basics.rank() == 0


def _atomic_publish(directory: str, name: str, payload: bytes) -> str:
    """Write ``payload`` to ``<directory>/<name>`` crash-atomically:
    unique same-directory temp (two savers can't collide), fsync, then
    ``os.replace`` — readers only ever see absent or complete files."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, name)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic publish
    except BaseException:
        # a failed/interrupted save must not leave the temp behind when
        # we still control the process (a SIGKILL leaves it for _prune)
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    return path


def save_checkpoint(directory: str, state: Any, step: int,
                    keep: int = 3) -> Optional[str]:
    """Rank-0 checkpoint write (reference: the ``if hvd.rank() == 0:
    torch.save(...)`` idiom).  Returns the path written (root only)."""
    if not _is_root():
        return None
    payload = flax.serialization.to_bytes(
        jax.tree_util.tree_map(np.asarray, state)
    )
    path = _atomic_publish(directory, f"ckpt-{int(step)}", payload)
    _prune(directory, keep)
    return path


def _prune(directory: str, keep: int) -> None:
    ckpts = []
    for name in os.listdir(directory):
        if (m := _CKPT_RE.match(name)):
            ckpts.append((int(m.group(1)), name))
        elif _TMP_RE.match(name):
            # debris from a writer killed mid-save (chaos kill, OOM):
            # harmless to resume logic, but sweep it so the directory
            # doesn't accrete one orphan per injected fault.  AGE-GATED:
            # a fresh temp may belong to a concurrent saver still
            # writing (per-PID names exist exactly to allow that) —
            # deleting it would make that saver's os.replace fail
            tmp_path = os.path.join(directory, name)
            try:
                if time.time() - os.path.getmtime(tmp_path) > 300:
                    os.remove(tmp_path)
            except OSError:
                pass
    ckpts.sort()
    for _, name in ckpts[:-keep] if keep else []:
        try:
            os.remove(os.path.join(directory, name))
        except OSError:
            pass  # a concurrent pruner (elastic restart race) got it


def latest_checkpoint(directory: str) -> Optional[str]:
    if not os.path.isdir(directory):
        return None
    ckpts = sorted(
        (int(m.group(1)), name)
        for name in os.listdir(directory)
        if (m := _CKPT_RE.match(name))
    )
    return os.path.join(directory, ckpts[-1][1]) if ckpts else None


def checkpoint_step(path: str) -> Optional[int]:
    """The step encoded in a ``ckpt-N`` path, or None."""
    m = _CKPT_RE.match(os.path.basename(path))
    return int(m.group(1)) if m else None


def restore_checkpoint(directory: str, state: Any,
                       broadcast: bool = True) -> Any:
    """Restore the latest checkpoint into ``state``'s structure.

    With ``broadcast=True`` only rank 0 needs to see the file; the loaded
    state is broadcast to all workers (reference resume flow:
    load-on-root + broadcast_parameters/broadcast_optimizer_state).
    Returns ``state`` unchanged when no checkpoint exists.
    """
    path = latest_checkpoint(directory)
    multi = basics.is_initialized() and basics.cross_size() > 1
    if not multi:
        if path is None:
            return state
        return _read_pytree(path, state)

    if broadcast:
        found = functions.broadcast_object(path is not None, root_rank=0)
        if not found:
            return state
        if basics.rank() == 0:
            loaded = _read_pytree(path, state)
        else:
            loaded = state
        host = jax.tree_util.tree_map(np.asarray, loaded)
        return functions.broadcast_object(host, root_rank=0)

    if path is None:
        return state
    return _read_pytree(path, state)


def _read_pytree(path: str, state: Any) -> Any:
    with open(path, "rb") as f:
        payload = f.read()
    if payload.startswith(_STATE_MAGIC):
        # a pickled elastic-state checkpoint landed in this directory:
        # say so instead of surfacing a bare msgpack decode error (and
        # crash-looping a resuming job on it)
        raise ValueError(
            f"{path} is an elastic STATE checkpoint "
            "(save_state_checkpoint format); restore it with "
            "restore_state_checkpoint / state.enable_auto_resume, or "
            "keep pytree and state checkpoints in separate directories"
        )
    return flax.serialization.from_bytes(state, payload)


# -- elastic object-state checkpoints (auto-resume feed) ---------------------


def save_state_checkpoint(directory: str, state: Any, step: int,
                          keep: int = 3, *, snapshot: Any = None,
                          all_ranks: bool = False) -> Optional[str]:
    """Persist an ``hvd.elastic`` state's snapshot as ``ckpt-<step>``
    (rank 0 only; crash-atomic).  The state must expose ``_snapshot()``
    (ObjectState/TpuState do); anything picklable inside survives.

    ``snapshot`` publishes an ALREADY-TAKEN snapshot instead of calling
    ``state._snapshot()`` (the preemption guard took its bounded under
    a deadline — re-snapshotting could block on the very condition it
    raced).  ``all_ranks=True`` bypasses the rank-0 gate: a preempted
    worker is the sole authority on its own progress, whatever its
    rank (crash-atomic publication makes concurrent writers safe).

    Pairs with :func:`restore_state_checkpoint` and with the automatic
    reset-epoch path ``state.enable_auto_resume(directory)``.
    """
    if not all_ranks and not _is_root():
        return None
    payload = _STATE_MAGIC + pickle.dumps(
        {"step": int(step),
         "snapshot": state._snapshot() if snapshot is None else snapshot}
    )
    path = _atomic_publish(directory, f"ckpt-{int(step)}", payload)
    _prune(directory, keep)
    return path


def peek_state_checkpoint(directory: str) -> Optional[Tuple[int, Any]]:
    """Load the latest state checkpoint as ``(step, snapshot)`` without
    touching any live state; None when the directory holds none (or only
    pytree-format checkpoints)."""
    path = latest_checkpoint(directory)
    if path is None:
        return None
    try:
        with open(path, "rb") as f:
            head = f.read(len(_STATE_MAGIC))
            if head != _STATE_MAGIC:
                return None  # a flax pytree checkpoint, not a state one
            blob = pickle.loads(f.read())
        return int(blob["step"]), blob["snapshot"]
    # a corrupt/alien file can raise nearly anything out of pickle
    # (UnpicklingError, ValueError, AttributeError for a moved class...)
    except Exception as e:
        from .utils.logging import get_logger

        # resumability must not crash-loop a booting worker on one bad
        # file (version skew, torn disk): warn and resume without it
        get_logger().error(
            "checkpoint: %s unusable (%s: %s); ignoring it",
            path, type(e).__name__, e,
        )
        return None


def restore_state_checkpoint(directory: str, state: Any) -> Optional[int]:
    """Apply the latest state checkpoint's snapshot to ``state`` (every
    rank reads locally — shared filesystem, as with the pytree path).
    Returns the restored step, or None when nothing was restored."""
    found = peek_state_checkpoint(directory)
    if found is None:
        return None
    step, snapshot = found
    state._apply_snapshot(snapshot)
    if hasattr(state, "save"):
        state.save()  # the restored view becomes the committed baseline
    return step
