"""Checkpoint save/resume helpers.

Reference parity (SURVEY.md §5.4): the reference has no bespoke format —
rank 0 writes a framework checkpoint, resume re-broadcasts from root
(examples/ pattern: ``torch.save`` + ``broadcast_parameters`` +
``broadcast_optimizer_state``).  This module packages exactly that
pattern for the JAX loop:

  * :func:`save_checkpoint` — rank 0 serializes the state pytree
    (flax msgpack; any pytree of arrays works) to ``<dir>/ckpt-<step>``;
  * :func:`restore_checkpoint` — every worker reads the latest checkpoint
    if present (shared filesystem), or rank 0 reads and the state is
    broadcast (``broadcast=True``) — the §5.4(b) resume flow.

Orbax remains the right tool for sharded multi-host checkpoints of very
large models; these helpers cover the reference's replicated-weights
contract without extra dependencies.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

import flax.serialization
import jax
import numpy as np

from . import functions
from .common import basics

_CKPT_RE = re.compile(r"^ckpt-(\d+)$")


def _is_root() -> bool:
    return not basics.is_initialized() or basics.rank() == 0


def save_checkpoint(directory: str, state: Any, step: int,
                    keep: int = 3) -> Optional[str]:
    """Rank-0 checkpoint write (reference: the ``if hvd.rank() == 0:
    torch.save(...)`` idiom).  Returns the path written (root only)."""
    if not _is_root():
        return None
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"ckpt-{int(step)}")
    payload = flax.serialization.to_bytes(
        jax.tree_util.tree_map(np.asarray, state)
    )
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)  # atomic publish
    _prune(directory, keep)
    return path


def _prune(directory: str, keep: int) -> None:
    ckpts = sorted(
        (int(m.group(1)), name)
        for name in os.listdir(directory)
        if (m := _CKPT_RE.match(name))
    )
    for _, name in ckpts[:-keep] if keep else []:
        os.remove(os.path.join(directory, name))


def latest_checkpoint(directory: str) -> Optional[str]:
    if not os.path.isdir(directory):
        return None
    ckpts = sorted(
        (int(m.group(1)), name)
        for name in os.listdir(directory)
        if (m := _CKPT_RE.match(name))
    )
    return os.path.join(directory, ckpts[-1][1]) if ckpts else None


def restore_checkpoint(directory: str, state: Any,
                       broadcast: bool = True) -> Any:
    """Restore the latest checkpoint into ``state``'s structure.

    With ``broadcast=True`` only rank 0 needs to see the file; the loaded
    state is broadcast to all workers (reference resume flow:
    load-on-root + broadcast_parameters/broadcast_optimizer_state).
    Returns ``state`` unchanged when no checkpoint exists.
    """
    path = latest_checkpoint(directory)
    multi = basics.is_initialized() and basics.cross_size() > 1
    if not multi:
        if path is None:
            return state
        with open(path, "rb") as f:
            return flax.serialization.from_bytes(state, f.read())

    if broadcast:
        found = functions.broadcast_object(path is not None, root_rank=0)
        if not found:
            return state
        if basics.rank() == 0:
            with open(path, "rb") as f:
                loaded = flax.serialization.from_bytes(state, f.read())
        else:
            loaded = state
        host = jax.tree_util.tree_map(np.asarray, loaded)
        return functions.broadcast_object(host, root_rank=0)

    if path is None:
        return state
    with open(path, "rb") as f:
        return flax.serialization.from_bytes(state, f.read())
