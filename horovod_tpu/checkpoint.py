"""Checkpoint save/resume helpers.

Reference parity (SURVEY.md §5.4): the reference has no bespoke format —
rank 0 writes a framework checkpoint, resume re-broadcasts from root
(examples/ pattern: ``torch.save`` + ``broadcast_parameters`` +
``broadcast_optimizer_state``).  This module packages exactly that
pattern for the JAX loop:

  * :func:`save_checkpoint` — rank 0 serializes the state pytree
    (flax msgpack; any pytree of arrays works) to ``<dir>/ckpt-<step>``;
  * :func:`restore_checkpoint` — every worker reads the latest checkpoint
    if present (shared filesystem), or rank 0 reads and the state is
    broadcast (``broadcast=True``) — the §5.4(b) resume flow.
  * :func:`save_state_checkpoint` / :func:`restore_state_checkpoint` —
    the same contract for ``hvd.elastic`` object states (pickled
    snapshots), feeding the elastic auto-resume path
    (``state.enable_auto_resume``; docs/FAULT_TOLERANCE.md).

Both families use ``ckpt-<step>`` names so :func:`latest_checkpoint`
serves either — but use ONE family per directory: a same-step save from
the other family would overwrite, and pruning counts them together.
Cross-family reads fail loudly (the state format carries a magic
header), never with a bare deserialization error.

Every write is CRASH-ATOMIC: the payload goes to a uniquely named temp
file in the same directory, is fsync'd, and is published with
``os.replace`` — a worker killed mid-save (the exact fault the chaos
subsystem injects) can leave a stray ``.tmp`` behind but never a
truncated ``ckpt-N`` that :func:`latest_checkpoint` would then resume
from.  Stale temp files are swept by the same pruning pass that trims
old checkpoints.

Every write is also CHECKSUMMED: the published file carries a CRC32 of
its payload in a small header, verified on every read.  Atomicity
protects against *torn* files; the checksum protects against *lying*
ones — a bit-flipped or bad-sector checkpoint that still unpickles (or
unpickles into garbage) would otherwise brick auto-resume or silently
poison the restored state (docs/FAULT_TOLERANCE.md, silent corruption).
A checkpoint failing its checksum is skipped with a loud log and the
readers fall back to the NEXT-OLDEST ring entry instead of raising
mid-resume; pre-checksum files (no header) still load unverified.  The
``checkpoint.payload`` chaos site flips bits in the exact bytes about
to be published, driving the corrupt-latest-checkpoint drill.

Orbax remains the right tool for sharded multi-host checkpoints of very
large models; these helpers cover the reference's replicated-weights
contract without extra dependencies.
"""

from __future__ import annotations

import os
import pickle
import re
import time
import zlib
from typing import Any, List, Optional, Tuple

import flax.serialization
import jax
import numpy as np

from . import functions
from .common import basics

_CKPT_RE = re.compile(r"^ckpt-(\d+)$")
_TMP_RE = re.compile(r"^ckpt-\d+\.tmp\.\d+$")

#: Header distinguishing pickled elastic-state checkpoints from flax
#: msgpack pytree checkpoints (both live under the same ckpt-N names so
#: latest_checkpoint() serves either family).
_STATE_MAGIC = b"HVDTPU-STATE1\n"

#: Content-integrity header: ``magic + crc32 as 8 hex chars + \n`` wraps
#: every published payload (either family).  Files without it are
#: pre-checksum checkpoints and load unverified.
_CKSUM_MAGIC = b"HVDTPU-CRC32\n"
_CKSUM_HEAD = len(_CKSUM_MAGIC) + 9  # 8 hex digits + newline

#: directories whose non-state entries peek_state_checkpoint already
#: warned about (once per process; see the ring-walk comment there)
_warned_non_state_dirs: set = set()


def _is_root() -> bool:
    return not basics.is_initialized() or basics.rank() == 0


def _atomic_publish(directory: str, name: str, payload: bytes) -> str:
    """Write ``payload`` to ``<directory>/<name>`` crash-atomically:
    unique same-directory temp (two savers can't collide), fsync, then
    ``os.replace`` — readers only ever see absent or complete files.
    The payload is wrapped in a CRC32 header so readers can tell a
    lying file from a true one (module docstring); the
    ``checkpoint.payload`` chaos site sees the exact bytes about to hit
    disk (post-checksum, so an injected flip is DETECTABLE — a ``drop``
    rule silently loses the write, the lost-checkpoint fault)."""
    from . import chaos as _chaos
    from . import trace

    # the directory must exist even when a DROP rule loses the write:
    # the caller's pruning pass lists it unconditionally
    os.makedirs(directory, exist_ok=True)
    payload = (_CKSUM_MAGIC + b"%08x\n" % zlib.crc32(payload) + payload)
    if _chaos.active:
        out = _chaos.point("checkpoint.payload", payload)
        if out is _chaos.DROP:
            return os.path.join(directory, name)  # write silently lost
        payload = out
    path = os.path.join(directory, name)
    tmp = f"{path}.tmp.{os.getpid()}"
    with trace.span("checkpoint.publish", name=name, bytes=len(payload)):
        try:
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # atomic publish
        except BaseException:
            # a failed/interrupted save must not leave the temp behind
            # when we still control the process (a SIGKILL leaves it
            # for _prune)
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
    return path


def save_checkpoint(directory: str, state: Any, step: int,
                    keep: int = 3) -> Optional[str]:
    """Rank-0 checkpoint write (reference: the ``if hvd.rank() == 0:
    torch.save(...)`` idiom).  Returns the path written (root only)."""
    if not _is_root():
        return None
    payload = flax.serialization.to_bytes(
        jax.tree_util.tree_map(np.asarray, state)
    )
    path = _atomic_publish(directory, f"ckpt-{int(step)}", payload)
    _prune(directory, keep)
    return path


def _prune(directory: str, keep: int) -> None:
    ckpts = []
    for name in os.listdir(directory):
        if (m := _CKPT_RE.match(name)):
            ckpts.append((int(m.group(1)), name))
        elif _TMP_RE.match(name):
            # debris from a writer killed mid-save (chaos kill, OOM):
            # harmless to resume logic, but sweep it so the directory
            # doesn't accrete one orphan per injected fault.  AGE-GATED:
            # a fresh temp may belong to a concurrent saver still
            # writing (per-PID names exist exactly to allow that) —
            # deleting it would make that saver's os.replace fail
            tmp_path = os.path.join(directory, name)
            try:
                if time.time() - os.path.getmtime(tmp_path) > 300:
                    os.remove(tmp_path)
            except OSError:
                pass
    ckpts.sort()
    for _, name in ckpts[:-keep] if keep else []:
        try:
            os.remove(os.path.join(directory, name))
        except OSError:
            pass  # a concurrent pruner (elastic restart race) got it


def _ring_newest_first(directory: str) -> List[Tuple[int, str]]:
    """Every ``ckpt-N`` in the directory as ``(step, path)``, newest
    first — the fallback order corrupt-file recovery walks."""
    if not os.path.isdir(directory):
        return []
    ckpts = sorted(
        ((int(m.group(1)), name)
         for name in os.listdir(directory)
         if (m := _CKPT_RE.match(name))),
        reverse=True,
    )
    return [(step, os.path.join(directory, name)) for step, name in ckpts]


def latest_checkpoint(directory: str) -> Optional[str]:
    ring = _ring_newest_first(directory)
    return ring[0][1] if ring else None


def _read_verified(path: str) -> Optional[bytes]:
    """Read a checkpoint file and verify its content checksum.  Returns
    the inner payload, or None (with a LOUD log) when the stored CRC32
    does not match — a torn/bit-flipped/lying file the caller must skip.
    Files without the checksum header (pre-checksum format) pass
    through unverified."""
    with open(path, "rb") as f:
        blob = f.read()
    if not blob.startswith(_CKSUM_MAGIC):
        return blob  # pre-checksum checkpoint: load unverified
    from .utils.logging import get_logger

    head = blob[len(_CKSUM_MAGIC):_CKSUM_HEAD]
    payload = blob[_CKSUM_HEAD:]
    try:
        want = int(head[:8], 16)
    except ValueError:
        want = -1
    got = zlib.crc32(payload)
    if got != want:
        get_logger().error(
            "checkpoint: %s FAILED its content checksum (stored %s, "
            "computed %08x) — corrupt or torn file; SKIPPING it and "
            "falling back to the next-oldest ring entry",
            path, head[:8].decode("ascii", "replace"), got,
        )
        return None
    return payload


def discard_newer_than(directory: str, step: int) -> List[str]:
    """Remove every ``ckpt-N`` with ``N > step`` — the guard's rollback
    primitive: checkpoints written after the last *verified* step are
    inside the poisoned window and must not win auto-resume
    (docs/FAULT_TOLERANCE.md, silent corruption).  Concurrent-survivor
    safe (a peer pruning the same ring is tolerated).  Returns the
    removed paths."""
    removed = []
    for s, path in _ring_newest_first(directory):
        if s <= step:
            break
        try:
            os.remove(path)
            removed.append(path)
        except OSError:
            pass  # a concurrent survivor's rollback got it first
    return removed


def checkpoint_step(path: str) -> Optional[int]:
    """The step encoded in a ``ckpt-N`` path, or None."""
    m = _CKPT_RE.match(os.path.basename(path))
    return int(m.group(1)) if m else None


def restore_checkpoint(directory: str, state: Any,
                       broadcast: bool = True) -> Any:
    """Restore the latest USABLE checkpoint into ``state``'s structure.

    With ``broadcast=True`` only rank 0 needs to see the file; the loaded
    state is broadcast to all workers (reference resume flow:
    load-on-root + broadcast_parameters/broadcast_optimizer_state).
    Returns ``state`` unchanged when no checkpoint exists.  A newest
    entry failing its content checksum (or msgpack-undecodable) is
    skipped with a loud log and the next-oldest ring entry loads
    instead — a bit-flipped file degrades resume by one save, never
    bricks it.
    """
    multi = basics.is_initialized() and basics.cross_size() > 1
    if not multi:
        loaded = _load_latest_pytree(directory, state)
        return state if loaded is None else loaded

    if broadcast:
        loaded = (_load_latest_pytree(directory, state)
                  if basics.rank() == 0 else None)
        found = functions.broadcast_object(loaded is not None, root_rank=0)
        if not found:
            return state
        host = jax.tree_util.tree_map(
            np.asarray, loaded if loaded is not None else state)
        return functions.broadcast_object(host, root_rank=0)

    loaded = _load_latest_pytree(directory, state)
    return state if loaded is None else loaded


def _load_latest_pytree(directory: str, state: Any) -> Optional[Any]:
    """Newest-first ring walk: skip checksum-failed and undecodable
    entries (loudly); None when nothing usable remains."""
    from .utils.logging import get_logger

    for _step, path in _ring_newest_first(directory):
        payload = _read_verified(path)
        if payload is None:
            continue  # checksum failure already logged loudly
        if payload.startswith(_STATE_MAGIC):
            # a pickled elastic-state checkpoint landed in this
            # directory: say so instead of surfacing a bare msgpack
            # decode error (and crash-looping a resuming job on it)
            raise ValueError(
                f"{path} is an elastic STATE checkpoint "
                "(save_state_checkpoint format); restore it with "
                "restore_state_checkpoint / state.enable_auto_resume, or "
                "keep pytree and state checkpoints in separate "
                "directories"
            )
        try:
            return flax.serialization.from_bytes(state, payload)
        except Exception as e:
            get_logger().error(
                "checkpoint: %s undecodable (%s: %s); skipping it and "
                "falling back to the next-oldest ring entry",
                path, type(e).__name__, e,
            )
    return None


# -- elastic object-state checkpoints (auto-resume feed) ---------------------


def save_state_checkpoint(directory: str, state: Any, step: int,
                          keep: int = 3, *, snapshot: Any = None,
                          all_ranks: bool = False) -> Optional[str]:
    """Persist an ``hvd.elastic`` state's snapshot as ``ckpt-<step>``
    (rank 0 only; crash-atomic).  The state must expose ``_snapshot()``
    (ObjectState/TpuState do); anything picklable inside survives.

    ``snapshot`` publishes an ALREADY-TAKEN snapshot instead of calling
    ``state._snapshot()`` (the preemption guard took its bounded under
    a deadline — re-snapshotting could block on the very condition it
    raced).  ``all_ranks=True`` bypasses the rank-0 gate: a preempted
    worker is the sole authority on its own progress, whatever its
    rank (crash-atomic publication makes concurrent writers safe).

    Pairs with :func:`restore_state_checkpoint` and with the automatic
    reset-epoch path ``state.enable_auto_resume(directory)``.
    """
    if not all_ranks and not _is_root():
        return None
    payload = _STATE_MAGIC + pickle.dumps(
        {"step": int(step),
         "snapshot": state._snapshot() if snapshot is None else snapshot}
    )
    path = _atomic_publish(directory, f"ckpt-{int(step)}", payload)
    _prune(directory, keep)
    return path


def peek_state_checkpoint(directory: str) -> Optional[Tuple[int, Any]]:
    """Load the newest USABLE state checkpoint as ``(step, snapshot)``
    without touching any live state; None when the directory holds none
    (or only pytree-format checkpoints).

    Usable means: content checksum verifies (or pre-checksum format)
    AND the pickle decodes.  A corrupt newest entry — the exact fault
    the ``checkpoint.payload`` chaos site injects — is skipped with a
    loud log and the walk falls back to the next-oldest ring entry, so
    one bit-flipped file costs one save of progress instead of bricking
    auto-resume."""
    from .utils.logging import get_logger

    for _step, path in _ring_newest_first(directory):
        payload = _read_verified(path)
        if payload is None:
            continue  # checksum failure already logged loudly
        if not payload.startswith(_STATE_MAGIC):
            # either a flax pytree checkpoint (one-family-per-dir means
            # every entry will look like this and the walk returns
            # None) or a state file whose HEADER bytes were corrupted
            # (no checksum magic survived to verify against) — keep
            # walking so the ring fallback covers header damage too.
            # Logged ONCE per directory: a legitimate pytree dir would
            # otherwise warn per entry per resume check
            if directory not in _warned_non_state_dirs:
                _warned_non_state_dirs.add(directory)
                get_logger().warning(
                    "checkpoint: %s is not a state checkpoint (pytree "
                    "family, pre-checksum file, or corrupted header); "
                    "skipping such entries in the ring walk", path)
            continue
        try:
            blob = pickle.loads(payload[len(_STATE_MAGIC):])
            return int(blob["step"]), blob["snapshot"]
        # a corrupt/alien file can raise nearly anything out of pickle
        # (UnpicklingError, ValueError, AttributeError for a moved
        # class...) — resumability must not crash-loop a booting worker
        # on one bad file (version skew, torn disk): skip and fall back
        except Exception as e:
            get_logger().error(
                "checkpoint: %s unusable (%s: %s); skipping it and "
                "falling back to the next-oldest ring entry",
                path, type(e).__name__, e,
            )
    return None


def restore_state_checkpoint(directory: str, state: Any) -> Optional[int]:
    """Apply the latest state checkpoint's snapshot to ``state`` (every
    rank reads locally — shared filesystem, as with the pytree path).
    Returns the restored step, or None when nothing was restored."""
    found = peek_state_checkpoint(directory)
    if found is None:
        return None
    step, snapshot = found
    state._apply_snapshot(snapshot)
    if hasattr(state, "save"):
        state.save()  # the restored view becomes the committed baseline
    return step
