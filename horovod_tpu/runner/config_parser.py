"""CLI-flag / YAML-config to env-var translation.

Reference parity: horovod/runner/common/util/config_parser.py (SURVEY.md
§5.6): three equivalent layers — env vars, CLI flags, --config-file YAML —
all converging on env vars read at init.  Knob names keep the reference's
spelling so existing horovodrun config files translate 1:1.
"""

from __future__ import annotations

from typing import Dict, Optional

# flag/yaml key -> env suffix (HVD_TPU_<suffix>); mirrors the reference's
# _add_arg set in runner/launch.py + config_parser constants.
_KNOBS = {
    "fusion_threshold": "FUSION_THRESHOLD",
    "cycle_time_ms": "CYCLE_TIME",
    "cache_capacity": "CACHE_CAPACITY",
    "timeline_filename": "TIMELINE",
    "timeline_mark_cycles": "TIMELINE_MARK_CYCLES",
    "stall_check_disable": "STALL_CHECK_DISABLE",
    "stall_warning_time_seconds": "STALL_CHECK_TIME_SECONDS",
    "stall_shutdown_time_seconds": "STALL_SHUTDOWN_TIME_SECONDS",
    "autotune": "AUTOTUNE",
    "autotune_log": "AUTOTUNE_LOG",
    "hierarchical_allreduce": "HIERARCHICAL_ALLREDUCE",
    "log_level": "LOG_LEVEL",
    "elastic": "ELASTIC",
}


def config_to_env(args, config_file: Optional[dict] = None) -> Dict[str, str]:
    """Build the HVD_TPU_* env block for workers from parsed CLI args and
    an optional YAML config dict (CLI wins, matching the reference's
    precedence)."""
    env: Dict[str, str] = {}
    merged = dict(config_file or {})
    for key in _KNOBS:
        val = getattr(args, key, None)
        if val is None and key in merged:
            val = merged[key]
        if val is None:
            continue
        if isinstance(val, bool):
            val = "1" if val else "0"
        env[f"HVD_TPU_{_KNOBS[key]}"] = str(val)
    return env


def load_config_file(path: str) -> dict:
    """Reference: --config-file YAML (runner/launch.py)."""
    import yaml

    with open(path) as f:
        data = yaml.safe_load(f) or {}
    if not isinstance(data, dict):
        raise ValueError(f"config file {path} must contain a mapping")
    return data
