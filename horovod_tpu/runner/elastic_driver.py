"""Elastic driver: discovery polling, worker lifecycle, rendezvous.

Reference parity: horovod/runner/elastic/driver.py (ElasticDriver),
discovery.py (HostDiscoveryScript), registration.py / rendezvous.py
(SURVEY.md §2.4, §3.4).  Responsibilities are the same set:

  * poll ``--host-discovery-script`` (~1 s) for the current ``host:slots``
    set;
  * spawn one worker process per slot (localhost exec or ssh), each told
    only the driver's address + a stable worker id — world shape always
    arrives via rendezvous;
  * detect failures (process exit, notification-socket drop), blacklist
    the failed slot, and drive a reset epoch: push ``hosts_updated`` to
    survivors, collect rendezvous requests from the expected member set,
    hand out rank/size/coordinator assignments;
  * enforce ``--min-np`` (wait for capacity, bounded by
    HVD_TPU_ELASTIC_TIMEOUT) and ``--max-np`` (cap spawned slots);
  * declare success when every live worker exits 0.

The assignment makes the lowest worker id rank 0, whose host then serves
the JAX coordination service for the epoch — the analog of the reference
restarting its rendezvous server on reset.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..common import wire_auth
from ..common.retry import env_float, retry_call
from ..elastic.worker import ENV_DRIVER, ENV_ELASTIC, ENV_WORKER_ID
from ..metrics import instruments as _metrics
from ..utils.logging import get_logger

_LOCAL_HOSTS = ("localhost", "127.0.0.1")


def _signed_line(obj: dict) -> bytes:
    """One HMAC-signed JSON line (reference: secret.py-signed RPC)."""
    return (json.dumps(
        wire_auth.sign_message(obj, wire_auth.job_secret())
    ) + "\n").encode()


def _verified(msg: dict) -> Optional[dict]:
    """Verify+strip the signature; None = forged/unsigned (drop peer)."""
    out = wire_auth.verify_message(msg, wire_auth.job_secret())
    if out is None:
        get_logger().warning(
            "elastic driver: dropping control message with "
            "missing/invalid signature")
    return out


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class HostDiscovery:
    """Wraps the user's discovery script (reference:
    runner/elastic/discovery.py HostDiscoveryScript): executable printing
    one ``host`` or ``host:slots`` per line.

    The script is an external dependency that flakes in real clusters
    (cloud API hiccup, ssh probe timing out), so invocations ride the
    shared backoff+jitter policy: ``HVD_TPU_DISCOVERY_TIMEOUT`` seconds
    per attempt (default 30), ``HVD_TPU_DISCOVERY_RETRIES`` attempts
    (default 3) before the failure surfaces to the poll loop — which
    already tolerates it by keeping the previous host set."""

    def __init__(self, script: str, default_slots: int = 1):
        self.script = script
        self.default_slots = default_slots
        self.timeout = env_float("HVD_TPU_DISCOVERY_TIMEOUT", 30.0)
        self.retries = int(env_float("HVD_TPU_DISCOVERY_RETRIES", 3))

    def _run_script(self) -> str:
        out = subprocess.run(
            [self.script], capture_output=True, text=True,
            timeout=self.timeout,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"host discovery script failed ({out.returncode}): "
                f"{out.stderr.strip()}"
            )
        return out.stdout

    def find_available_hosts(self) -> List[Tuple[str, int]]:
        try:
            stdout = retry_call(
                self._run_script,
                site="elastic.discovery",
                retry_on=(RuntimeError, OSError,
                          subprocess.TimeoutExpired),
                attempts=max(1, self.retries),
                describe=f"host discovery ({self.script})",
            )
        except RuntimeError:
            raise
        except (OSError, subprocess.TimeoutExpired) as e:
            # normalize to the contract the poll loops catch
            # (`except RuntimeError` keeps the previous host set) — a
            # persistent flake must degrade the poll, never crash the
            # driver and reap the fleet
            raise RuntimeError(f"host discovery failed: {e}") from e
        hosts = []
        for line in stdout.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                h, s = line.rsplit(":", 1)
                hosts.append((h, int(s)))
            else:
                hosts.append((line, self.default_slots))
        return hosts


class _Worker:
    def __init__(self, worker_id: int, host: str, slot: int,
                 proc: subprocess.Popen):
        self.worker_id = worker_id
        self.host = host
        self.slot = slot
        self.proc = proc
        self.exit_code: Optional[int] = None
        # slot removed by discovery: the worker stays a member until the
        # next rendezvous, where it is told to shut down (it arrives
        # there via its own exec-restart; no cross-member teardown)
        self.leaving = False

    @property
    def alive(self) -> bool:
        # exit_code only — NOT a live proc.poll().  Deaths must become
        # visible through the monitor's detection pass (which blacklists
        # the slot) before any membership decision sees them; a live poll
        # here let a just-died worker vanish from _occupied_slots() while
        # its slot was not yet blacklisted, so the discovery poll
        # "refilled" the dead slot with a fresh worker (failure=False)
        # instead of taking the failure-recovery path.
        return self.exit_code is None


class ElasticDriver:
    """See module docstring.  One instance per ``tpurun`` elastic job."""

    def __init__(
        self,
        command: List[str],
        discovery: HostDiscovery,
        min_np: int,
        max_np: Optional[int] = None,
        knob_env: Optional[Dict[str, str]] = None,
        poll_interval: float = 1.0,
        timeout: Optional[float] = None,
        verbose: bool = False,
    ):
        self.command = command
        self.discovery = discovery
        self.min_np = min_np
        self.max_np = max_np
        self.knob_env = knob_env or {}
        self.poll_interval = poll_interval
        self.timeout = timeout or env_float("HVD_TPU_ELASTIC_TIMEOUT",
                                            600.0)
        self.verbose = verbose
        # per-job control-plane secret: signs the driver<->worker JSON
        # lines AND the workers' native-star hello; exported through the
        # driver's own environ so _spawn's env copies inherit it
        os.environ.setdefault(wire_auth.SECRET_ENV, wire_auth.make_secret())

        # reentrant: _desired_slots guards the hold map internally and
        # is called both with and without the lock held (the min-np
        # refill wait holds it; the discovery reconcile does not)
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._workers: Dict[int, _Worker] = {}
        self._blacklist: set = set()  # (host, slot) pairs
        # hosts quarantined after an integrity attribution (guard.py):
        # a machine whose chip computed wrong values leaves the spawn
        # pool entirely — EVERY slot it advertises is skipped, not just
        # the one the attributed worker held (docs/FAULT_TOLERANCE.md)
        self._host_blacklist: set = set()
        self._next_worker_id = 0
        self._epoch = 0
        # rendezvous state: worker_id -> socket awaiting an assignment
        self._pending_rendezvous: Dict[int, socket.socket] = {}
        self._notify_socks: Dict[int, socket.socket] = {}
        self._server: Optional[socket.socket] = None
        self._shutdown = False
        # a live worker reported control-plane failure ("failing" line):
        # drives a failure=True reset epoch even with no process exit
        self._failure_reported = False
        # -- fleet autoscaling state (docs/FLEET.md) -----------------------
        # explicit world-size target (request_world_size); None = track
        # discovery capacity, the pre-fleet behavior, unchanged
        self._world_target: Optional[int] = None
        # wake the discovery poll immediately after a resize request
        self._poll_asap = False
        # a 'leaving' worker's clean exit must trigger a planned reset
        # epoch for the survivors (the preemption path: the worker
        # leaves FIRST, unlike driver-ordered scale-down)
        self._leaver_exited = False
        # (host, slot) -> monotonic expiry: slots vacated by preemption
        # are held against immediate refill (the machine is going away;
        # discovery is the authority again once the hold expires)
        self._slot_hold: Dict[Tuple[str, int], float] = {}
        self._autoscaler = None

    # -- server ------------------------------------------------------------

    def _start_server(self) -> Tuple[str, int]:
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("", 0))
        srv.listen(128)
        self._server = srv
        threading.Thread(target=self._accept_loop, daemon=True).start()
        return socket.gethostname(), srv.getsockname()[1]

    def _accept_loop(self) -> None:
        while not self._shutdown:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handle_conn, args=(conn,), daemon=True
            ).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            f = conn.makefile("r")
            line = f.readline()
            if not line:
                conn.close()
                return
            msg = json.loads(line)
        except (OSError, ValueError):
            conn.close()
            return
        msg = _verified(msg)
        if msg is None:
            conn.close()
            return
        kind = msg.get("type")
        wid = msg.get("worker_id")
        if kind == "register":
            with self._cv:
                self._notify_socks[wid] = conn
            # keep the socket open (its EOF doubles as a liveness signal)
            # and keep READING it: a worker entering exec-restart recovery
            # reports "failing" here so the driver can push failure=True
            # to the other members immediately — their recovery then
            # starts from their own commit polls instead of racing the
            # jax coordination service's fatal handler
            self._drain_notify_conn(wid, conn, f)
        elif kind == "rendezvous":
            with self._cv:
                self._pending_rendezvous[wid] = conn
                self._cv.notify_all()
        else:
            conn.close()

    def _drain_notify_conn(self, wid, conn: socket.socket, f) -> None:
        """Read worker->driver reports on the registered connection until
        EOF (runs on the per-connection handler thread)."""
        while not self._shutdown:
            try:
                line = f.readline()
            except OSError:
                return
            if not line:
                return  # EOF: liveness handled by the send path
            try:
                msg = _verified(json.loads(line))
            except ValueError:
                continue
            if msg is None:
                continue
            if msg.get("type") == "failing":
                get_logger().warning(
                    "elastic: worker %s reports failure: %s",
                    wid, msg.get("reason", ""))
                with self._cv:
                    if msg.get("integrity"):
                        self._quarantine_host(wid)
                    self._failure_reported = True
                    self._cv.notify_all()
            elif msg.get("type") == "leaving":
                # planned departure (preemption notice): mark the
                # worker leaving BEFORE its exit 0 can be observed (so
                # it books as a scale-down, not job completion) and
                # hold its slot against an immediate refill
                get_logger().warning(
                    "elastic: worker %s leaving (planned): %s",
                    wid, msg.get("reason", ""))
                with self._cv:
                    w = self._workers.get(wid)
                    if w is not None:
                        w.leaving = True
                        self._slot_hold[(w.host, w.slot)] = (
                            time.monotonic() + env_float(
                                "HVD_TPU_FLEET_REFILL_HOLD", 60.0))
                    self._cv.notify_all()
                # ack on the same connection: the worker's leave path
                # waits for this before exiting, so the 'leaving' mark
                # is BOOKED (not merely in a socket buffer) when the
                # exit 0 lands — a slow reader thread can't mis-book
                # the preemption as job completion
                try:
                    conn.sendall(_signed_line({"type": "leaving_ack"}))
                except OSError:
                    pass

    def _quarantine_host(self, wid: int) -> None:
        """Integrity attribution (guard.py closed loop): quarantine the
        attributed worker's WHOLE host — a lying chip taints its
        machine, and refilling any of its slots would hand the fleet
        back to it.  SIBLING workers still running there are hard-
        killed too: leaving them computing would keep re-tripping the
        guard until the survivors' rollback fuse kills the whole job;
        their exits book through ``_observe_exits`` as normal failures.
        Caller must hold ``self._cv``."""
        w = self._workers.get(wid)
        if w is None or w.host in self._host_blacklist:
            return
        self._host_blacklist.add(w.host)
        _metrics.GUARD_QUARANTINES.inc()
        get_logger().error(
            "elastic: host %s QUARANTINED after integrity attribution "
            "of worker %s", w.host, wid)
        for s in self._workers.values():
            if s.alive and s.host == w.host and s.worker_id != wid:
                get_logger().error(
                    "elastic: killing worker %d — sibling slot on "
                    "quarantined host %s", s.worker_id, s.host)
                try:
                    s.proc.kill()
                except OSError:
                    pass

    # -- worker lifecycle --------------------------------------------------

    def _spawn(self, host: str, slot: int, driver_addr: str) -> _Worker:
        wid = self._next_worker_id
        self._next_worker_id += 1
        env = dict(os.environ)
        # a driver itself launched under tpurun must not leak its own
        # placement into workers; the rendezvous assignment supplies theirs
        env.pop("HVD_TPU_LOCAL_RANK", None)
        env.pop("HVD_TPU_LOCAL_SIZE", None)
        env.update(self.knob_env)
        env[ENV_ELASTIC] = "1"
        env[ENV_DRIVER] = driver_addr
        env[ENV_WORKER_ID] = str(wid)
        if host in _LOCAL_HOSTS:
            proc = subprocess.Popen(self.command, env=env)
        else:
            # secret via ssh stdin, never the argv (cmdline is world-
            # readable on both hosts for the job's lifetime)
            secret = env.get(wire_auth.SECRET_ENV, "")
            env_prefix = " ".join(
                f"{k}={subprocess.list2cmdline([v])}"
                for k, v in env.items()
                if k.startswith("HVD_TPU_") and k != wire_auth.SECRET_ENV
            )
            remote = (f"IFS= read -r {wire_auth.SECRET_ENV} && "
                      f"export {wire_auth.SECRET_ENV} && "
                      f"cd {os.getcwd()} && {env_prefix} "
                      + subprocess.list2cmdline(self.command))
            proc = subprocess.Popen(
                ["ssh", "-o", "StrictHostKeyChecking=no", host, remote],
                stdin=subprocess.PIPE,
            )
            proc.stdin.write((secret + "\n").encode())
            proc.stdin.close()
        w = _Worker(wid, host, slot, proc)
        self._workers[wid] = w
        _metrics.ELASTIC_SPAWNS.inc()
        if self.verbose:
            print(f"[tpurun elastic] spawned worker {wid} on {host}:{slot}",
                  file=sys.stderr)
        return w

    def _observe_exits(self) -> Tuple[bool, bool]:
        """Poll every worker process once and book-keep any deaths: record
        the exit code, drop the notification socket, blacklist the slot on
        failure, flag job completion on a clean active exit.  This is the
        ONLY place exits become visible (``_Worker.alive`` deliberately
        reads the recorded code, not the live process), so every code path
        that waits on workers must call it — otherwise a death during that
        wait is invisible (or worse, visible without its blacklist).
        Caller must hold ``self._cv``.  Returns (any_exit, any_failure)."""
        log = get_logger()
        any_exit = any_failure = False
        for w in list(self._workers.values()):
            if w.exit_code is None:
                code = w.proc.poll()
                if code is not None:
                    w.exit_code = code
                    any_exit = True
                    self._notify_socks.pop(w.worker_id, None)
                    if code == 0 and not w.leaving:
                        # a clean exit of an active member means training
                        # completed: the job is winding down — stop
                        # spawning into freed slots.  (A 'leaving' worker
                        # exiting 0 is just a scale-down; elasticity must
                        # survive it.)
                        self._completing = True
                    elif code == 0 and w.leaving:
                        # a preempted worker left on its own (unlike a
                        # driver-ordered scale-down, where the epoch ran
                        # first): the survivors need a planned reset epoch
                        # contract-ok: locks -- _observe_exits runs with self._cv held (docstring contract; every caller acquires it)
                        self._leaver_exited = True
                    if code != 0:
                        log.warning(
                            "elastic: worker %d (%s:%d) failed with exit "
                            "code %d", w.worker_id, w.host, w.slot, code)
                        self._blacklist.add((w.host, w.slot))
                        any_failure = True
                        _metrics.ELASTIC_FAILURES.inc()
        return any_exit, any_failure

    def _alive_workers(self) -> List[_Worker]:
        return [w for w in self._workers.values() if w.alive]

    def _occupied_slots(self) -> set:
        return {(w.host, w.slot) for w in self._workers.values() if w.alive}

    def _desired_slots(self, hosts: List[Tuple[str, int]]) -> List[Tuple[str, int]]:
        # the hold map is written by the notification-reader thread
        # (a 'leaving' report) — expire + snapshot it under the lock
        # (reentrant, so callers already holding _cv are fine)
        now = time.monotonic()
        with self._cv:
            for k in [k for k, exp in self._slot_hold.items()
                      if exp <= now]:
                del self._slot_hold[k]
            held = set(self._slot_hold)
        slots = []
        for h, n in hosts:
            if h in self._host_blacklist:
                continue  # quarantined after integrity attribution
            for s in range(n):
                if (h, s) not in self._blacklist and (h, s) not in held:
                    slots.append((h, s))
        if self.max_np is not None:
            slots = slots[: self.max_np]
        # the autoscaler's explicit target caps capacity-tracking: the
        # LOWEST slots stay, so a shrink always removes the same
        # (deterministic) members and a later grow refills from where
        # it shrank
        if self._world_target is not None:
            slots = slots[: self._world_target]
        return slots

    # -- autoscaler entry point (docs/FLEET.md) ----------------------------

    def request_world_size(self, n: Optional[int]) -> int:
        """Resize the training world to ``n`` workers, honored at the
        next epoch boundary: the discovery reconcile spawns into free
        (non-blacklisted, non-held) slots to grow, or marks the
        highest-slot members ``leaving`` to shrink — those members get
        the driver's ``shutdown`` reply at the rendezvous their next
        commit check delivers them to, so no step is ever cut mid-air.
        The explicit entry point the fleet autoscaler calls instead of
        faking failures (upstream elastic's only lever, SURVEY §5.3).

        ``n`` is clamped to ``[min_np, max_np]``; ``None`` returns the
        driver to pure capacity tracking (every discovered slot, the
        pre-fleet behavior).  Thread-safe; returns the clamped target
        (or -1 for None).  Fewer discovered slots than the target is
        not an error — the world converges as far as capacity allows,
        and further when discovery finds more."""
        with self._cv:
            if n is not None:
                n = max(self.min_np, int(n))
                if self.max_np is not None:
                    n = min(n, self.max_np)
            self._world_target = n
            self._poll_asap = True
            self._cv.notify_all()
        get_logger().info("elastic: world-size target set to %s", n)
        return -1 if n is None else n

    def current_world(self) -> int:
        """Live, non-leaving workers — the autoscaler's ``current``."""
        with self._cv:
            return sum(1 for w in self._workers.values()
                       if w.alive and not w.leaving)

    # -- rendezvous epoch --------------------------------------------------

    def _query_ports(self, sock: socket.socket):
        """Ask the rank-0-elect worker to allocate the epoch's
        coordinator + native ports on its host.  The reply deadline is
        ``HVD_TPU_ELASTIC_NOTIFY_TIMEOUT`` (default 30 s) — env-tunable
        because a loaded rank-0 host legitimately takes longer than a
        hard-coded 30 under CI-grade contention."""
        try:
            sock.sendall(_signed_line({"type": "allocate_ports"}))
            sock.settimeout(env_float("HVD_TPU_ELASTIC_NOTIFY_TIMEOUT",
                                      30.0))
            reply = _verified(json.loads(sock.makefile("r").readline()))
            sock.settimeout(None)
            if reply is None or reply.get("type") != "ports":
                return None
            return reply
        except (OSError, ValueError):
            return None

    def _notify_hosts_updated(self, failure: bool = False) -> None:
        """Push the membership change; ``failure=True`` tells survivors a
        peer died, so they must take the restart recovery path (a graceful
        in-process teardown would trip on the dead peer's barrier).

        A survivor dying MID-NOTIFY must not take the monitor down: every
        send failure is caught (any exception, not just OSError) and the
        remaining survivors are still notified.  The dead socket is
        dropped; the death itself is booked by ``_observe_exits`` — the
        ONE place exits become visible (exit code + blacklist + metrics +
        completion flag) — which the very next ``_complete_rendezvous``
        wait iteration runs.  A send failure with the process still alive
        is the normal exec-restart window (the restarting worker's socket
        closed at execv; it re-registers after boot).

        Sends run OUTSIDE the driver lock: a frozen worker whose recv
        buffer fills would otherwise block ``sendall`` while holding the
        only lock, deadlocking every other driver thread."""
        with self._cv:
            targets = list(self._notify_socks.items())
            line = _signed_line({"type": "hosts_updated",
                                 "epoch": self._epoch,
                                 "failure": failure})
        dead = []
        for wid, sock in targets:
            try:
                sock.sendall(line)
            except Exception:
                dead.append(wid)
        if dead:
            with self._cv:
                for wid in dead:
                    self._notify_socks.pop(wid, None)

    def _complete_rendezvous(self, driver_host: str) -> bool:
        """Wait until every live worker has requested rendezvous, then
        hand out assignments.  Returns False on timeout/below-min-np."""
        deadline = time.time() + self.timeout
        with self._cv:
            while True:
                # full bookkeeping, not a bare poll: a worker that crashes
                # during rendezvous must blacklist its slot too, or the
                # discovery poll refills it into a crash loop
                self._observe_exits()
                expected = {w.worker_id for w in self._alive_workers()}
                have = set(self._pending_rendezvous)
                if not expected:
                    return False
                if expected <= have:
                    break
                if time.time() > deadline:
                    return False
                self._cv.wait(timeout=0.2)

            members = sorted(
                wid for wid in expected if not self._workers[wid].leaving
            )
            if not members:
                return False
            self._members = list(members)
            rank0 = self._workers[members[0]]
            coord_host = ("127.0.0.1" if rank0.host in _LOCAL_HOSTS
                          else rank0.host)
            # two-phase: the rank-0-elect allocates the ports ON ITS OWN
            # HOST (probing them here would race/miss on a remote machine
            # — reference analog: the rendezvous server owning its port)
            ports = self._query_ports(self._pending_rendezvous[members[0]])
            if ports is None:
                return False
            coordinator = f"{coord_host}:{ports['coordinator_port']}"
            native_port = ports["native_port"]
            # per-host placement for hvd.local_rank()/local_process_count()
            # (reference: the per-host slot numbering horovodrun exports)
            host_members: Dict[str, List[int]] = {}
            for wid in members:
                host_members.setdefault(
                    self._workers[wid].host, []
                ).append(wid)
            for rank, wid in enumerate(members):
                sock = self._pending_rendezvous.pop(wid)
                peers = host_members[self._workers[wid].host]
                reply = {
                    "type": "assignment",
                    "rank": rank,
                    "num_processes": len(members),
                    "coordinator": coordinator,
                    "native_port": native_port,
                    "local_rank": peers.index(wid),
                    "local_size": len(peers),
                    "epoch": self._epoch,
                }
                try:
                    sock.sendall(_signed_line(reply))
                except OSError:
                    pass
                sock.close()
            # leaving workers (removed slots) and latecomers from dead
            # epochs are told to shut down; they clean up their restart
            # state file and exit 0
            for wid, sock in list(self._pending_rendezvous.items()):
                if wid not in members:
                    try:
                        sock.sendall(_signed_line({"type": "shutdown"}))
                    except OSError:
                        pass
                    sock.close()
                    self._pending_rendezvous.pop(wid, None)
            # "failing" reports that arrived while THIS epoch was being
            # arranged are part of the failure it just recovered from —
            # carrying them forward would trigger a spurious next epoch
            # (a genuinely new failure gets re-reported or shows up as an
            # out-of-band rendezvous)
            self._failure_reported = False
            _metrics.ELASTIC_RENDEZVOUS.inc()
            _metrics.ELASTIC_WORLD_SIZE.set(len(members))
            _metrics.ELASTIC_EPOCH.set(self._epoch)
            if self.verbose:
                print(f"[tpurun elastic] epoch {self._epoch}: world="
                      f"{len(members)} coordinator={coordinator}",
                      file=sys.stderr)
        return True

    def _reconcile(self, hosts: List[Tuple[str, int]],
                   local_addr: str) -> bool:
        """Converge the spawned-worker set onto the desired slot set
        (discovery capacity minus blacklist/holds, capped by max-np and
        the autoscaler's :meth:`request_world_size` target): spawn into
        added slots, mark workers on removed slots ``leaving`` (they
        stay members until the next rendezvous hands them ``shutdown``
        — the epoch boundary).  Returns whether membership changed, so
        the caller drives the reset epoch.  A method (not loop-inline)
        so resize unit tests exercise both directions processlessly."""
        desired = set(self._desired_slots(hosts))
        with self._cv:
            occupied = self._occupied_slots()
            added = desired - occupied
            # already-leaving workers are in flight toward their
            # shutdown reply — re-marking them every poll would spin
            # membership epochs until they exit
            removed = {(w.host, w.slot) for w in self._alive_workers()
                       if not w.leaving} - desired
            if not added and not removed:
                return False
            for w in self._alive_workers():
                if (w.host, w.slot) in removed:
                    # keep it alive through the next rendezvous; it
                    # exits after the "shutdown" reply
                    w.leaving = True
            for h, s in sorted(added):
                self._spawn(h, s, local_addr)
        return True

    # -- main loop ---------------------------------------------------------

    def run(self) -> int:
        from .launch import ensure_sigterm_unwinds

        # a terminated driver must unwind so the finally below reaps the
        # worker fleet instead of orphaning it
        restore_handler = ensure_sigterm_unwinds()
        # driver-side scrape endpoint (its own env var: the driver shares
        # a host with worker 0, so it must not claim the workers' base
        # port): HVD_TPU_DRIVER_METRICS_PORT, same off-by-default rules
        from ..metrics import exposition as _exposition
        from ..utils.logging import set_log_context

        # the driver shares the workers' log formatter: its records
        # carry rank="driver" so a collated multi-process log separates
        # cleanly (HVD_TPU_LOG_JSON gives the machine-ingestable form)
        set_log_context(rank="driver")
        # ... and the workers' /trace surface: the driver records real
        # spans of its own (fleet.scale decisions), so the recorder
        # installs FULLY here — rank -1 keeps its exports/bundles off
        # every worker's pid lane in a merge, and the flight baseline
        # makes driver bundles carry true metric DELTAS
        from .. import trace as _trace

        _trace.install_from_env(rank=-1)
        _exposition.maybe_start_from_env(
            env_var="HVD_TPU_DRIVER_METRICS_PORT")
        host, port = self._start_server()
        # workers resolve the driver by this address; local workers can
        # always use loopback
        driver_addr = f"{host}:{port}"
        try:
            return self._run(driver_addr, host)
        finally:
            self._shutdown = True
            if self._autoscaler is not None:
                self._autoscaler.stop()
            try:
                self._server.close()
            except OSError:
                pass
            from .launch import reap_workers

            # terminate → grace → kill: jaxlib's preemption notifier
            # swallows a bare SIGTERM in every initialized worker
            reap_workers([w.proc for w in self._workers.values()
                          if w.alive])
            restore_handler()

    def _run(self, driver_addr: str, driver_host: str) -> int:
        log = get_logger()
        # a resize plan whose first entry is t=0 sets the INITIAL world
        # target too (the autoscaler only starts after the first
        # rendezvous — without this, a "start at 2 of 4 slots" drill
        # would boot at capacity and immediately shrink)
        from ..fleet.policy import plan_from_env

        plan = plan_from_env()
        if plan is not None and plan.plan[0][0] <= 0:
            self.request_world_size(plan.plan[0][1])
        # wait for the initial host set to satisfy min_np
        deadline = time.time() + self.timeout
        while True:
            hosts = self.discovery.find_available_hosts()
            slots = self._desired_slots(hosts)
            if len(slots) >= self.min_np:
                break
            if time.time() > deadline:
                print(f"[tpurun elastic] timed out waiting for >= "
                      f"{self.min_np} slots", file=sys.stderr)
                return 1
            time.sleep(self.poll_interval)

        local_addr = driver_addr
        if all(h in _LOCAL_HOSTS for h, _ in slots):
            local_addr = f"127.0.0.1:{driver_addr.rsplit(':', 1)[1]}"
        with self._cv:
            for h, s in slots:
                self._spawn(h, s, local_addr)
        if not self._complete_rendezvous(driver_host):
            return 1

        # fleet autoscaler (docs/FLEET.md): a timed drill plan
        # (HVD_TPU_FLEET_PLAN) or armed SLO targets start the loop
        # that drives request_world_size; nothing set = pre-fleet
        # capacity tracking, untouched
        from ..fleet.autoscaler import maybe_training_autoscaler

        self._autoscaler = maybe_training_autoscaler(
            self.request_world_size, self.current_world,
            min_size=self.min_np, max_size=self.max_np)
        if self._autoscaler is not None:
            self._autoscaler.start()

        last_poll = time.time()
        while True:
            time.sleep(0.1)
            with self._cv:
                _, had_failure = self._observe_exits()
                if self._failure_reported:
                    # a live member says its control plane died: run a
                    # failure reset epoch now — survivors recover from
                    # their commit polls instead of waiting for the
                    # failing process's death to close sockets
                    self._failure_reported = False
                    had_failure = True
                membership_changed = had_failure
                alive = self._alive_workers()
            if not alive and not membership_changed:
                # job over: success iff every member of the final epoch
                # exited cleanly (recovered-from failures of earlier
                # epochs don't count against the job — reference behavior)
                members = getattr(self, "_members", [])
                ok = members and all(
                    self._workers[wid].exit_code == 0 for wid in members
                )
                return 0 if ok else 1

            # a leaving (preempted) worker's clean exit happened: the
            # survivors need a planned reset epoch NOW, and a resize
            # request wants its reconcile before the next poll tick
            with self._cv:
                if self._leaver_exited:
                    self._leaver_exited = False
                    membership_changed = True
                poll_now = self._poll_asap
                self._poll_asap = False

            # discovery poll (suspended once the job is completing)
            if not getattr(self, "_completing", False) and (
                    poll_now
                    or time.time() - last_poll >= self.poll_interval):
                last_poll = time.time()
                try:
                    hosts = self.discovery.find_available_hosts()
                except RuntimeError as e:
                    log.warning("elastic: discovery failed: %s", e)
                    hosts = None
                if hosts is not None:
                    membership_changed |= self._reconcile(hosts,
                                                          local_addr)

            # a worker that exec-restarted itself (failure recovery) shows
            # up as an out-of-band rendezvous request: serve it with a new
            # epoch even if no process exit was observed
            with self._cv:
                if self._pending_rendezvous and not membership_changed:
                    membership_changed = True

            if membership_changed:
                with self._cv:
                    alive = self._alive_workers()
                if len(alive) < self.min_np:
                    # wait (bounded) for discovery to refill capacity
                    refill_deadline = time.time() + self.timeout
                    while len(alive) < self.min_np:
                        if time.time() > refill_deadline:
                            print("[tpurun elastic] world below --min-np "
                                  "and no new hosts; aborting",
                                  file=sys.stderr)
                            return 1
                        time.sleep(self.poll_interval)
                        try:
                            hosts = self.discovery.find_available_hosts()
                        except RuntimeError:
                            continue
                        with self._cv:
                            # a worker dying during this wait must be
                            # reaped (and its slot blacklisted) here, or
                            # the ghost counts toward min_np below; a
                            # crash also upgrades the pending notification
                            # to failure=True so survivors take the
                            # restart-recovery path, not the graceful one
                            had_failure |= self._observe_exits()[1]
                            desired = set(self._desired_slots(hosts))
                            for h, s in sorted(desired -
                                               self._occupied_slots()):
                                self._spawn(h, s, local_addr)
                            alive = self._alive_workers()
                self._epoch += 1
                self._notify_hosts_updated(failure=had_failure)
                if not self._complete_rendezvous(driver_host):
                    print("[tpurun elastic] rendezvous failed; aborting",
                          file=sys.stderr)
                    return 1
