"""Launchers (reference analog: horovod/runner/ — SURVEY.md §2.4).

``tpurun`` replaces ``horovodrun``: it starts one process per host (or N
local processes for single-host simulation), exports the coordination env
the same way horovodrun exports HOROVOD_GLOO_RENDEZVOUS_ADDR, and monitors
children, terminating all on first failure.  The JAX coordination service
replaces the reference's HTTP rendezvous store; there is no NIC-probing
driver/task RPC layer because TPU pods have a known, homogeneous network
(SURVEY.md §5.8).
"""

from .launch import run, run_commandline  # noqa: F401
