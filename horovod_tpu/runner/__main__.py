"""``python -m horovod_tpu.runner`` == tpurun (reference:
``python -m horovod.runner`` alias for horovodrun)."""

from .launch import main

main()
