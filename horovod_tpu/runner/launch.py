"""tpurun: the launcher CLI.

Reference parity: horovod/runner/launch.py + gloo_run.py (SURVEY.md §2.4,
§3.3): parse -np/-H/--hostfile/knob flags/--config-file, start one worker
process per slot with the coordination env exported, monitor, and kill
everything on first failure.  Differences, by TPU design:

  * rendezvous = the JAX coordination service (workers call
    ``jax.distributed.initialize`` against HVD_TPU_COORDINATOR), replacing
    the launcher-hosted HTTP KV store;
  * no NIC-probing driver/task RPC layer (SURVEY.md §2.4 "driver/task
    bootstrap") — TPU pod networking is known and homogeneous;
  * remote hosts are reached with plain ssh like the reference's gloo_run,
    one process per host (a TPU host drives all its local chips).
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

from .config_parser import config_to_env, load_config_file


def ensure_sigterm_unwinds():
    """Convert SIGTERM into SystemExit so a terminated launcher unwinds
    through its finally-blocks and kills the worker fleet — the default
    handler exits without unwinding and ORPHANS every worker (observed:
    orphaned elastic workers surviving their driver and polluting later
    jobs on the host).  No-op off the main thread, where the default
    behavior stands anyway.

    Returns a zero-arg restore callable: library embeddings (estimator
    fit() inside a Spark driver, RayExecutor in a user process) must not
    leave the process-wide handler permanently replaced."""

    def _raise(signum, frame):
        raise SystemExit(128 + signum)

    try:
        prev = signal.signal(signal.SIGTERM, _raise)
    except ValueError:
        return lambda: None

    def _restore():
        try:
            signal.signal(signal.SIGTERM, prev)
        except (ValueError, TypeError):
            pass

    return _restore


def reap_workers(procs: List["subprocess.Popen"],
                 grace_s: float = 5.0) -> None:
    """terminate → grace → SIGKILL → wait.  SIGTERM alone does NOT stop
    a worker: jaxlib's preemption notifier installs a SIGTERM handler in
    every process that ran jax.distributed.initialize, so terminated
    workers keep running (observed: orphans surviving their driver)."""
    alive = [p for p in procs if p.poll() is None]
    for p in alive:
        p.terminate()
    deadline = time.time() + grace_s
    while time.time() < deadline:
        if all(p.poll() is not None for p in alive):
            return
        time.sleep(0.1)
    for p in alive:
        if p.poll() is None:
            p.kill()
    for p in alive:
        # SIGKILL cannot be blocked, so this wait is bounded; without it
        # the killed children linger as zombies in long-lived callers
        p.wait()


def monitor_lockstep(procs: List["subprocess.Popen"],
                     label: str = "tpurun") -> int:
    """Exit-code lockstep monitoring: first nonzero exit terminates the
    rest (reference: gloo_run's monitor loop).  Shared by the launcher
    and the estimator/executor subprocess backends.  Any exception —
    including the SIGTERM-as-SystemExit from ensure_sigterm_unwinds —
    reaps the fleet before propagating."""
    restore_handler = ensure_sigterm_unwinds()
    try:
        while True:
            codes = [p.poll() for p in procs]
            for rank, code in enumerate(codes):
                if code is not None and code != 0:
                    print(f"[{label}] rank {rank} exited with {code}; "
                          "terminating remaining workers", file=sys.stderr)
                    reap_workers(procs)
                    return code
            if all(c == 0 for c in codes):
                return 0
            time.sleep(0.1)
    except BaseException:
        reap_workers(procs)
        raise
    finally:
        restore_handler()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def parse_host_spec(spec: str) -> List[Tuple[str, int]]:
    """'h1:4,h2:4' -> [(h1, 4), (h2, 4)] (reference: runner/hosts.py)."""
    hosts = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, slots = part.rsplit(":", 1)
            hosts.append((name, int(slots)))
        else:
            hosts.append((part, 1))
    return hosts


def parse_hostfile(path: str) -> List[Tuple[str, int]]:
    """One 'host slots=N' per line (reference: --hostfile format)."""
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            fields = line.split()
            slots = 1
            for fld in fields[1:]:
                if fld.startswith("slots="):
                    slots = int(fld.split("=", 1)[1])
            hosts.append((fields[0], slots))
    return hosts


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpurun",
        description="Launch a distributed training job "
                    "(horovodrun-compatible surface, TPU backend).",
    )
    p.add_argument("-np", "--num-proc", type=int, default=None,
                   help="total number of worker processes")
    p.add_argument("-H", "--hosts", default=None,
                   help="comma-separated host:slots list")
    p.add_argument("--hostfile", default=None,
                   help="file with one 'host slots=N' per line")
    p.add_argument("--config-file", default=None,
                   help="YAML file of knob settings (reference format)")
    p.add_argument("--ssh-port", type=int, default=None)
    p.add_argument("--output-filename", default=None,
                   help="redirect each rank's output to <file>.rank")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--check-build", action="store_true",
                   help="print build capabilities and exit")
    p.add_argument("--disable-native", action="store_true",
                   help="force the Python fallback controller")
    # knob flags (reference: horovodrun's tunable flags; see config_parser)
    p.add_argument("--fusion-threshold", dest="fusion_threshold", type=int)
    p.add_argument("--cycle-time-ms", dest="cycle_time_ms", type=float)
    p.add_argument("--cache-capacity", dest="cache_capacity", type=int)
    p.add_argument("--timeline-filename", dest="timeline_filename")
    p.add_argument("--timeline-mark-cycles", dest="timeline_mark_cycles",
                   action="store_const", const=True)
    p.add_argument("--no-stall-check", dest="stall_check_disable",
                   action="store_const", const=True)
    p.add_argument("--stall-warning-time", dest="stall_warning_time_seconds",
                   type=float)
    p.add_argument("--stall-shutdown-time",
                   dest="stall_shutdown_time_seconds", type=float)
    p.add_argument("--autotune", dest="autotune", action="store_const",
                   const=True)
    p.add_argument("--autotune-log", dest="autotune_log")
    p.add_argument("--log-level", dest="log_level")
    # elastic flags (reference: horovodrun --min-np/--max-np/
    # --host-discovery-script — runner/elastic/settings.py)
    p.add_argument("--min-np", type=int, default=None,
                   help="minimum workers to keep running (elastic mode)")
    p.add_argument("--max-np", type=int, default=None,
                   help="maximum workers (elastic mode)")
    p.add_argument("--host-discovery-script", default=None,
                   help="executable printing current 'host:slots' lines; "
                        "enables elastic mode")
    p.add_argument("--slots", type=int, default=1,
                   help="default slots per discovered host (elastic)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="the training command, e.g. python train.py")
    return p


def check_build() -> str:
    """Reference: horovodrun --check-build output."""
    import horovod_tpu

    from ..native import _lib_path, _maybe_build

    _maybe_build()
    native = os.path.exists(_lib_path())
    lines = [
        f"horovod_tpu v{horovod_tpu.__version__}",
        "",
        "Available backends:",
        "    [X] XLA (ICI/DCN collectives)",
        f"    [{'X' if native else ' '}] native C++ controller core",
        "",
        "Available integrations:",
        "    [X] JAX / optax",
        "    [X] PyTorch (CPU bridge)" if _torch_available() else
        "    [ ] PyTorch (CPU bridge)",
        "    [ ] TensorFlow (not present in this environment)",
    ]
    return "\n".join(lines)


def _torch_available() -> bool:
    try:
        import torch  # noqa: F401

        return True
    except ImportError:
        return False


def _with_job_secret(knob_env: Dict[str, str]) -> Dict[str, str]:
    """Return knob_env carrying the per-job control-plane secret: the
    negotiation star's HMAC hello (native/src/secret.h) and the elastic
    JSON-line signing (common/wire_auth.py) both read HVD_TPU_SECRET.
    An inherited secret (launcher itself running under a parent job) is
    kept so nested launches stay mutually reachable."""
    from ..common import wire_auth

    env = dict(knob_env)
    env.setdefault(
        wire_auth.SECRET_ENV,
        os.environ.get(wire_auth.SECRET_ENV) or wire_auth.make_secret(),
    )
    return env


def _worker_env(base: Dict[str, str], knob_env: Dict[str, str],
                coordinator: str, native_port: int, num_proc: int,
                rank: int, disable_native: bool,
                local_rank: int = 0, local_size: int = 1) -> Dict[str, str]:
    env = dict(base)
    env.update(knob_env)
    env["HVD_TPU_COORDINATOR"] = coordinator
    # second port for the native controller's TCP negotiation star
    # (reference analog: the Gloo rendezvous port horovodrun exports)
    env["HVD_TPU_NATIVE_PORT"] = str(native_port)
    env["HVD_TPU_NUM_PROCESSES"] = str(num_proc)
    env["HVD_TPU_PROCESS_ID"] = str(rank)
    # per-host placement (reference: HOROVOD_LOCAL_RANK/LOCAL_SIZE the
    # launchers export) — hvd.local_rank() reads these
    env["HVD_TPU_LOCAL_RANK"] = str(local_rank)
    env["HVD_TPU_LOCAL_SIZE"] = str(local_size)
    if disable_native:
        env["HVD_TPU_DISABLE_NATIVE"] = "1"
    return env


def prebuild_tf_bridge(verbose: bool = False) -> None:
    """Build the TF XLA custom-call bridge ONCE before fan-out.

    Without this, N freshly-launched workers each import TF and compile
    the bridge concurrently on the same host; on a loaded single-core
    box that stretched worker boot past the jax.distributed rendezvous
    deadline and killed the fleet (round-4 verdict weak #2).  The check
    is two stat calls when the bridge is fresh (the common case); only
    a stale/missing bridge pays one subprocess (whose TF-import cost the
    workers would each have paid anyway).  Set HVD_TPU_PREBUILD_TF=0 to
    skip.  No-op when tensorflow is not installed.
    """
    if os.environ.get("HVD_TPU_PREBUILD_TF", "1") in ("0", "false"):
        return
    import importlib.util

    try:
        if importlib.util.find_spec("tensorflow") is None:
            return
    except (ImportError, ValueError):
        return
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(here, "tensorflow", "src", "xla_bridge.cc")
    out = os.path.join(here, "tensorflow", "libhvd_tf_xla.so")
    if not os.path.exists(src):
        return
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return  # fresh — nothing to do
    if verbose:
        print("[tpurun] pre-building the TF XLA bridge before fan-out",
              file=sys.stderr)
    # the worker-side builder (xla_ops._build_and_load) owns the build
    # recipe; run it once in a throwaway process so workers find a fresh
    # .so and skip their own compiles
    subprocess.run(
        [sys.executable, "-c",
         "from horovod_tpu.tensorflow import xla_ops; xla_ops.available()"],
        env=dict(os.environ, TF_CPP_MIN_LOG_LEVEL="3"),
        capture_output=not verbose, timeout=600, check=False,
    )


def _launch_local(command: List[str], num_proc: int,
                  knob_env: Dict[str, str], output_filename: Optional[str],
                  verbose: bool, disable_native: bool) -> int:
    """Single-host launch: np processes on localhost, lockstep monitored.
    Reference: gloo_run's local exec path + exit-code monitoring."""
    prebuild_tf_bridge(verbose)
    coordinator = f"127.0.0.1:{_free_port()}"
    native_port = _free_port()
    knob_env = _with_job_secret(knob_env)
    procs: List[subprocess.Popen] = []
    outputs = []
    try:
        for rank in range(num_proc):
            env = _worker_env(os.environ.copy(), knob_env, coordinator,
                              native_port, num_proc, rank, disable_native,
                              local_rank=rank, local_size=num_proc)
            stdout = stderr = None
            if output_filename:
                f = open(f"{output_filename}.{rank}", "w")
                outputs.append(f)
                stdout = stderr = f
            if verbose:
                print(f"[tpurun] rank {rank}: {' '.join(command)}",
                      file=sys.stderr)
            procs.append(subprocess.Popen(
                command, env=env, stdout=stdout, stderr=stderr
            ))
        # monitor: first nonzero exit kills the job (reference behavior)
        return monitor_lockstep(procs)
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        return 130
    finally:
        for f in outputs:
            f.close()


def _launch_ssh(command: List[str], hosts: List[Tuple[str, int]],
                num_proc: int, knob_env: Dict[str, str],
                ssh_port: Optional[int], verbose: bool,
                disable_native: bool) -> int:
    """Multi-host launch over ssh, one process per host slot (reference:
    gloo_run.py's ssh exec).  The first host runs rank 0 and hosts the
    coordination service."""
    from ..common import wire_auth

    coord_host = hosts[0][0]
    coordinator = f"{coord_host}:{_free_port()}"
    native_port = _free_port()
    knob_env = _with_job_secret(knob_env)
    # the secret must NEVER ride the ssh argv (visible to every local
    # user via /proc/*/cmdline for the job's lifetime): it travels on
    # ssh's stdin instead, read into the env by the remote preamble
    secret = knob_env.pop(wire_auth.SECRET_ENV)
    procs: List[subprocess.Popen] = []
    rank = 0
    for host, slots in hosts:
        used = min(slots, max(num_proc - rank, 0))
        for local_rank in range(used):
            env = _worker_env({}, knob_env, coordinator, native_port,
                              num_proc, rank, disable_native,
                              local_rank=local_rank, local_size=used)
            env_prefix = " ".join(
                f"{k}={subprocess.list2cmdline([v])}" for k, v in env.items()
            )
            remote_cmd = (
                f"IFS= read -r {wire_auth.SECRET_ENV} && "
                f"export {wire_auth.SECRET_ENV} && "
                f"cd {os.getcwd()} && {env_prefix} "
                + subprocess.list2cmdline(command)
            )
            ssh_cmd = ["ssh", "-o", "StrictHostKeyChecking=no"]
            if ssh_port:
                ssh_cmd += ["-p", str(ssh_port)]
            ssh_cmd += [host, remote_cmd]
            if verbose:
                print(f"[tpurun] rank {rank} on {host}", file=sys.stderr)
            p = subprocess.Popen(ssh_cmd, stdin=subprocess.PIPE)
            p.stdin.write((secret + "\n").encode())
            p.stdin.close()
            procs.append(p)
            rank += 1
    # same exit-code lockstep as the local path: first nonzero exit
    # reaps the whole fleet (reference: gloo_run's remote monitor)
    return monitor_lockstep(procs)


def run_commandline(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.check_build:
        print(check_build())
        return 0
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print("tpurun: no command given (e.g. tpurun -np 4 python train.py)",
              file=sys.stderr)
        return 2

    config = load_config_file(args.config_file) if args.config_file else {}
    knob_env = config_to_env(args, config)

    if args.host_discovery_script:
        # elastic mode (reference: horovodrun --host-discovery-script
        # switching launch.py into the ElasticDriver path)
        from .elastic_driver import ElasticDriver, HostDiscovery

        if args.disable_native:
            knob_env["HVD_TPU_DISABLE_NATIVE"] = "1"
        driver = ElasticDriver(
            command=command,
            discovery=HostDiscovery(args.host_discovery_script,
                                    default_slots=args.slots),
            min_np=args.min_np or args.num_proc or 1,
            max_np=args.max_np,
            knob_env=knob_env,
            verbose=args.verbose,
        )
        return driver.run()
    if args.min_np or args.max_np:
        print("tpurun: --min-np/--max-np require --host-discovery-script",
              file=sys.stderr)
        return 2

    if args.hostfile:
        hosts = parse_hostfile(args.hostfile)
    elif args.hosts:
        hosts = parse_host_spec(args.hosts)
    else:
        hosts = [("localhost", args.num_proc or 1)]
    total_slots = sum(s for _, s in hosts)
    num_proc = args.num_proc or total_slots
    if num_proc > total_slots:
        print(f"tpurun: requested -np {num_proc} but only {total_slots} "
              "slots available", file=sys.stderr)
        return 2

    local_only = all(h in ("localhost", "127.0.0.1", socket.gethostname())
                     for h, _ in hosts)
    if local_only:
        return _launch_local(command, num_proc, knob_env,
                             args.output_filename, args.verbose,
                             args.disable_native)
    return _launch_ssh(command, hosts, num_proc, knob_env, args.ssh_port,
                       args.verbose, args.disable_native)


def run(command: List[str], np: int = 1, **kwargs) -> int:
    """Programmatic launcher (reference: horovod.run)."""
    argv = ["-np", str(np)]
    for k, v in kwargs.items():
        flag = "--" + k.replace("_", "-")
        if isinstance(v, bool):
            if v:
                argv.append(flag)
        else:
            argv += [flag, str(v)]
    return run_commandline(argv + ["--"] + list(command))


def main() -> None:
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
