"""DistributedOptimizer for torch models.

Reference parity: horovod/torch/optimizer.py (_DistributedOptimizer) —
SURVEY.md §3.2's hot path: a hook fires as each parameter's gradient is
accumulated, submits an async (compressed) allreduce, and ``step()``
synchronizes all handles before applying the update.  Local gradient
aggregation over ``backward_passes_per_step`` is preserved.

The reference registers hooks on the autograd graph's grad accumulator
nodes; modern torch exposes the same moment directly via
``register_post_accumulate_grad_hook``, which we use.
"""

from __future__ import annotations

import contextlib
from typing import Iterable, Optional, Tuple

import torch

from ..ops.reduce_ops import Average, ReduceOp
from . import mpi_ops
from .compression import Compression


class _DistributedOptimizer(torch.optim.Optimizer):
    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step, op, gradient_predivide_factor,
                 process_set):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self._op = op
        self._process_set = process_set
        self.backward_passes_per_step = backward_passes_per_step
        self._gradient_predivide_factor = gradient_predivide_factor

        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = []
            for i, group in enumerate(self.param_groups):
                for j, p in enumerate(group["params"]):
                    named.append((f"allreduce.noname.{i}.{j}", p))
        self._param_names = {p: name for name, p in named}

        self._handles = {}  # param -> (handle, ctx)
        self._passes = {}  # param -> local accumulation count
        self._synchronized = False
        self._should_synchronize = True
        self._hook_handles = []
        self._register_hooks()

    # -- hooks --------------------------------------------------------------

    def _register_hooks(self):
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._passes[p] = 0
                    self._hook_handles.append(
                        p.register_post_accumulate_grad_hook(
                            self._make_hook()
                        )
                    )

    def _make_hook(self):
        def hook(p):
            self._passes[p] += 1
            if self._passes[p] == self.backward_passes_per_step:
                self._passes[p] = 0
                self._allreduce_grad_async(p)
        return hook

    def _allreduce_grad_async(self, p):
        name = self._param_names.get(p, "allreduce.noname")
        grad = p.grad
        if self.backward_passes_per_step > 1:
            grad = grad / self.backward_passes_per_step
        if self._gradient_predivide_factor != 1.0:
            grad = grad / self._gradient_predivide_factor
        compressed, ctx = self._compression.compress(grad)
        handle = mpi_ops.allreduce_async(
            compressed, name=name, op=self._op,
            process_set=self._process_set,
        )
        self._handles[p] = (handle, ctx)

    # -- synchronization ----------------------------------------------------

    def synchronize(self):
        """Wait for all outstanding allreduces and install averaged grads
        (reference: _DistributedOptimizer.synchronize)."""
        for p, (handle, ctx) in list(self._handles.items()):
            output = mpi_ops.synchronize(handle)
            grad = self._compression.decompress(output, ctx)
            if self._gradient_predivide_factor != 1.0:
                grad = grad * self._gradient_predivide_factor
            p.grad = grad.to(p.grad.dtype)
        self._handles.clear()
        self._synchronized = True

    @contextlib.contextmanager
    def skip_synchronize(self):
        """Reference: optimizer.skip_synchronize() for manual
        ``optimizer.synchronize()`` + gradient clipping patterns."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        if self._should_synchronize:
            if self._synchronized:
                import warnings

                warnings.warn(
                    "optimizer.step() called after optimizer.synchronize(); "
                    "use optimizer.skip_synchronize() to avoid reducing "
                    "gradients twice (reference warning text)"
                )
            self.synchronize()
        self._synchronized = False
        return super(self.__class__, self).step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() but "
                "before optimizer.step() or optimizer.synchronize()"
            )
        return super(self.__class__, self).zero_grad(*args, **kwargs)


def DistributedOptimizer(
    optimizer: torch.optim.Optimizer,
    named_parameters: Optional[Iterable[Tuple[str, torch.nn.Parameter]]] = None,
    compression=Compression.none,
    backward_passes_per_step: int = 1,
    op: ReduceOp = Average,
    gradient_predivide_factor: float = 1.0,
    process_set=None,
):
    """Wrap a torch optimizer with distributed gradient averaging
    (reference: horovod/torch/optimizer.py DistributedOptimizer — same
    dynamic-subclass trick so isinstance checks keep working)."""
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step, op, gradient_predivide_factor,
               process_set)
