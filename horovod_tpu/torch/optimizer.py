"""DistributedOptimizer for torch models.

Reference parity: horovod/torch/optimizer.py (_DistributedOptimizer) —
SURVEY.md §3.2's hot path: a hook fires as each parameter's gradient is
accumulated, submits an async (compressed) allreduce, and ``step()``
synchronizes all handles before applying the update.  Local gradient
aggregation over ``backward_passes_per_step`` is preserved.

The reference registers hooks on the autograd graph's grad accumulator
nodes; modern torch exposes the same moment directly via
``register_post_accumulate_grad_hook``, which we use.

Overlap: the hook body itself does no bridge/enqueue work on the
autograd thread — it posts the parameter to a single submission worker
and returns, so backward proceeds while compression + the dlpack bridge
+ engine enqueue happen concurrently and negotiation overlaps the rest
of the backward pass (the reference gets this overlap from its
background thread consuming the hook's immediate EnqueueTensorAllreduce;
here the enqueue itself is also off the critical path).  The single
worker preserves submission order; ``synchronize()`` first drains the
worker (re-raising any submit-side error), then waits the engine
futures.

Input pipeline: pair this optimizer with ``horovod_tpu.data`` for
per-rank sharded, worker-pool-decoded, prefetched host batches —
``DataLoader(..., device_put=False)`` yields numpy arrays that
``torch.from_numpy`` wraps zero-copy, and the loader's prefetch thread
overlaps the next batch's decode with this step's backward (the
``torch.utils.data.DataLoader(num_workers=N)`` analog; example:
examples/pytorch/pytorch_synthetic_benchmark.py ``--data npy``,
guide: docs/DATA.md).
"""

from __future__ import annotations

import contextlib
import os
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable, Optional, Tuple

import torch

from ..metrics import instruments as _metrics
from ..ops.reduce_ops import Average, ReduceOp
from . import mpi_ops
from .compression import Compression

_STEP_TIME = _metrics.STEP_DURATION.labels("torch")
_GRAD_NORM = _metrics.GRAD_NORM.labels("torch")


class _DistributedOptimizer(torch.optim.Optimizer):
    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step, op, gradient_predivide_factor,
                 process_set):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self._op = op
        self._process_set = process_set
        self.backward_passes_per_step = backward_passes_per_step
        self._gradient_predivide_factor = gradient_predivide_factor

        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = []
            for i, group in enumerate(self.param_groups):
                for j, p in enumerate(group["params"]):
                    named.append((f"allreduce.noname.{i}.{j}", p))
        self._param_names = {p: name for name, p in named}

        self._handles = {}  # param -> (handle, ctx)
        self._passes = {}  # param -> local accumulation count
        self._bucket_of = None  # param -> bucket launch slot (lazy)
        self._synchronized = False
        self._should_synchronize = True
        self._hook_handles = []
        # one worker: keeps per-process submission order deterministic
        # while taking the bridge+enqueue off the autograd thread
        self._submit_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="hvd_torch_submit")
        self._pending_submits = []
        # grads whose hooks fired but which no worker drain has picked up
        # yet; appended on the autograd thread, drained on the worker
        self._ready_params = deque()
        self._t_last_step = None
        self._metrics_grad_norm = os.environ.get(
            "HVD_TPU_METRICS_GRAD_NORM", "1") != "0"
        self._register_hooks()

    # -- hooks --------------------------------------------------------------

    def _register_hooks(self):
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._passes[p] = 0
                    self._hook_handles.append(
                        p.register_post_accumulate_grad_hook(
                            self._make_hook()
                        )
                    )

    def _make_hook(self):
        def hook(p):
            self._passes[p] += 1
            if self._passes[p] == self.backward_passes_per_step:
                self._passes[p] = 0
                # post-and-return: backward continues while the worker
                # compresses, bridges and enqueues this grad.  While the
                # worker is busy with one drain, later hooks pile their
                # params here and the NEXT drain submits them as one
                # batched native call (micro-batching by readiness).
                self._ready_params.append(p)
                pool = self._submit_pool
                if pool is not None:  # close() may race a late backward
                    try:
                        self._pending_submits.append(
                            pool.submit(self._drain_ready))
                    except RuntimeError:
                        # close() shut the pool down between the check
                        # and the submit; the grad simply stays local
                        pass
        return hook

    def _bucket_schedule(self):
        """param -> launch-order bucket slot, from a BucketSchedule over
        the registered parameters (ops/fusion.py): production order is
        REVERSE registration order — autograd produces gradients roughly
        back-to-front — and the layout is a pure function of the
        parameter specs, so every rank buckets identically even though
        each rank's hooks fire in their own timing-dependent order (the
        determinism the reference's Controller negotiates for its fusion
        buffer).  Bucket size: ``HVD_TPU_OVERLAP_BUCKET_BYTES``."""
        if self._bucket_of is None:
            from ..common import basics
            from ..ops.fusion import BucketSchedule

            cfg = basics._state.config
            bucket_bytes = (
                cfg.overlap_bucket_bytes if cfg is not None
                else 4 * 1024 * 1024
            )
            params = [p for p in self._passes]
            specs = [
                (tuple(p.shape), str(p.dtype).replace("torch.", ""))
                for p in params
            ]
            sched = BucketSchedule.from_specs(specs, bucket_bytes)
            self._bucket_of = {}
            for slot, (_, idxs) in enumerate(sched.buckets):
                for i in idxs:
                    self._bucket_of[params[i]] = slot
        return self._bucket_of

    def _drain_ready(self):
        """Worker-side: submit every gradient that became ready, grouped
        by the deterministic BucketSchedule and submitted in bucket
        launch order (earliest-produced first), so each bucket's
        allreduce negotiation starts while the rest of backward still
        runs.  Batch composition is timing-dependent and rank-local,
        which is safe because the entries negotiate under their own
        per-param names (NOT as an atomic group — group membership must
        be rank-symmetric); the batching only shaves submission latency.

        A short coalescing window (HVD_TPU_TORCH_BATCH_WINDOW_MS,
        default 1 ms ≈ one negotiation cycle) lets the hooks of a fast
        backward land in ONE batched submission instead of one
        negotiation round each — measured 4 rounds -> 1-2 at np=2 on a
        4-param model.  For large models backward dwarfs the window and
        the per-burst overlap is unaffected.  Set 0 to submit
        immediately."""
        batch = []
        try:
            batch.append(self._ready_params.popleft())
        except IndexError:
            return  # an earlier drain already took this task's param
        from ..common.retry import env_float

        window_s = env_float("HVD_TPU_TORCH_BATCH_WINDOW_MS", 1.0) * 1e-3
        from ..common import basics
        state = basics._state
        if (window_s > 0 and state.topology is not None
                and state.topology.num_processes > 1):
            # single-process execs are ~instant, so the window would be
            # pure added latency there; it only pays when a negotiation
            # round costs multiple ms (cross-process)
            time.sleep(window_s)
        while True:
            try:
                batch.append(self._ready_params.popleft())
            except IndexError:
                break
        # bucket-ordered submission: group the drained params by their
        # schedule bucket and submit buckets earliest-launch first — one
        # batched native call per bucket, so a bucket full of late-layer
        # grads never queues behind an early-layer straggler
        bucket_of = self._bucket_schedule()
        by_bucket = {}
        for p in batch:
            by_bucket.setdefault(bucket_of.get(p, -1), []).append(p)
        pending_total = sum(
            1 for q in self._passes if q not in self._handles
        ) - len(batch)
        for slot in sorted(by_bucket):
            members = by_bucket[slot]
            tensors, names, ctxs = [], [], []
            for p in members:
                name = self._param_names.get(p, "allreduce.noname")
                grad = p.grad
                if self.backward_passes_per_step > 1:
                    grad = grad / self.backward_passes_per_step
                if self._gradient_predivide_factor != 1.0:
                    grad = grad / self._gradient_predivide_factor
                compressed, ctx = self._compression.compress(grad)
                tensors.append(compressed)
                names.append(name)
                ctxs.append(ctx)
            from .. import trace as _trace

            with _trace.span("overlap.bucket", bucket=slot,
                             params=len(members)):
                handles = mpi_ops.allreduce_multi_async(
                    tensors, names, op=self._op,
                    process_set=self._process_set,
                )
            # launch lead: params still awaiting gradients when this
            # bucket's collective was submitted (0 = it trailed backward)
            _metrics.OVERLAP_LAUNCH_LEAD.observe(max(pending_total, 0))
            for p, handle, ctx in zip(members, handles, ctxs):
                self._handles[p] = (handle, ctx)

    # -- synchronization ----------------------------------------------------

    def synchronize(self):
        """Wait for all outstanding allreduces and install averaged grads
        (reference: _DistributedOptimizer.synchronize)."""
        pending, self._pending_submits = self._pending_submits, []
        for f in pending:
            f.result()  # re-raises a submit-side error on the caller
        sq_norm = None
        for p, (handle, ctx) in list(self._handles.items()):
            output = mpi_ops.synchronize(handle)
            grad = self._compression.decompress(output, ctx)
            if self._gradient_predivide_factor != 1.0:
                grad = grad * self._gradient_predivide_factor
            p.grad = grad.to(p.grad.dtype)
            if self._metrics_grad_norm:
                # accumulate ON DEVICE (fp32 accumulation: an fp16 norm
                # of a large grad overflows); one host sync below
                n = torch.linalg.vector_norm(
                    p.grad.detach(), dtype=torch.float32) ** 2
                sq_norm = n if sq_norm is None else sq_norm + n
        if sq_norm is not None:
            _GRAD_NORM.set(float(sq_norm) ** 0.5)
        self._handles.clear()
        self._synchronized = True

    @contextlib.contextmanager
    def skip_synchronize(self):
        """Reference: optimizer.skip_synchronize() for manual
        ``optimizer.synchronize()`` + gradient clipping patterns."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        if self._should_synchronize:
            if self._synchronized:
                import warnings

                warnings.warn(
                    "optimizer.step() called after optimizer.synchronize(); "
                    "use optimizer.skip_synchronize() to avoid reducing "
                    "gradients twice (reference warning text)"
                )
            self.synchronize()
        self._synchronized = False
        result = super(self.__class__, self).step(closure)
        # step-to-step wall time — the operator's iterations/sec view
        # (covers forward + backward + allreduce wait + update)
        now = time.perf_counter()
        if self._t_last_step is not None:
            _STEP_TIME.observe(now - self._t_last_step)
        self._t_last_step = now
        return result

    def zero_grad(self, *args, **kwargs):
        if self._handles or self._pending_submits:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() but "
                "before optimizer.step() or optimizer.synchronize()"
            )
        return super(self.__class__, self).zero_grad(*args, **kwargs)

    def close(self):
        """Detach from the model: remove the gradient hooks and shut the
        submission worker down (its thread otherwise outlives the
        optimizer — one leaked thread per DistributedOptimizer).  The
        wrapped optimizer keeps working as a plain local optimizer."""
        for h in self._hook_handles:
            h.remove()
        self._hook_handles.clear()
        pool = getattr(self, "_submit_pool", None)
        if pool is not None:
            self._submit_pool = None
            pool.shutdown(wait=True)  # drains in-flight submits first

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass  # interpreter teardown: modules may already be gone


def DistributedOptimizer(
    optimizer: torch.optim.Optimizer,
    named_parameters: Optional[Iterable[Tuple[str, torch.nn.Parameter]]] = None,
    compression=Compression.none,
    backward_passes_per_step: int = 1,
    op: ReduceOp = Average,
    gradient_predivide_factor: float = 1.0,
    process_set=None,
):
    """Wrap a torch optimizer with distributed gradient averaging
    (reference: horovod/torch/optimizer.py DistributedOptimizer — same
    dynamic-subclass trick so isinstance checks keep working)."""
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step, op, gradient_predivide_factor,
               process_set)
