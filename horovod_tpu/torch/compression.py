"""Gradient compression for the torch adapter.

Reference parity: horovod/torch/compression.py — ``Compression.none`` and
``Compression.fp16``, applied to gradients before the wire and undone
after.  On TPU the same fp16-on-the-wire trick matters for DCN-bound
multislice traffic; the JAX-side equivalent lives in
``horovod_tpu.compression``.
"""

from __future__ import annotations

import torch


class Compressor:
    @staticmethod
    def compress(tensor: torch.Tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference: NoneCompressor)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast fp32/fp64 to fp16 on the wire (reference: FP16Compressor)."""

    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating_point:
            return tensor.to(torch.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.to(ctx) if ctx is not None else tensor


class Compression:
    """Namespace matching the reference's ``hvd.Compression`` surface."""

    none = NoneCompressor
    fp16 = FP16Compressor
