"""Torch tensor collectives over the XLA engine — dlpack zero-copy bridge.

Reference parity: horovod/torch/mpi_ops.py + the C++ binding it fronts
(torch/mpi_ops_v2.cc, adapter_v2.cc, handle_manager.cc — SURVEY.md §2.3).
The reference wraps ``at::Tensor`` into ``common::Tensor`` without copying
and enqueues to the background thread; here a CPU torch tensor crosses
into the engine via **dlpack** (``jnp.from_dlpack`` — zero-copy aliasing
on the CPU backend, the exact analog of the reference's TensorAdapter
wrapping the at::Tensor's storage), is negotiated/fused/executed by the
same engine the JAX API uses, and the result crosses back as a dlpack
view of the XLA output buffer.  There is no numpy round-trip on the hot
path.  Handles mirror the reference's int-keyed HandleManager:
``*_async`` returns a handle consumed by ``synchronize`` / ``poll``.

Aliasing contracts (both are the reference's own semantics):
  * input: the engine reads the torch storage when the collective
    executes, not at call time — mutating the tensor between ``*_async``
    and ``synchronize`` is a race, exactly as with the reference's NCCL
    path reading the grad buffer at launch time;
  * output: XLA result buffers are immutable, so out-of-place ops hand
    the user a one-memcpy clone they own, and in-place ops ``copy_`` into
    the caller's buffer (what the reference's memcpyOutOfFusionBuffer
    does).  The dlpack *view* itself is never exposed writable.

On a non-CPU default backend (running this bridge against the TPU chip)
dlpack import would pin the array to the CPU platform, so the bridge
falls back to the host-copy path there — torch has no TPU storage to
alias; the TPU compute path is the JAX API.

In-place variants (``allreduce_`` etc.) write the result back into the
input tensor, matching reference semantics.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import torch

from ..common.process_sets import ProcessSet
from ..ops import collective_ops as _ops
from ..ops.reduce_ops import ReduceOp


def _to_jax(t: torch.Tensor) -> jax.Array:
    """Torch -> engine, zero-copy when possible (reference: adapter_v2.cc
    wrapping at::Tensor storage into common::Tensor without a copy)."""
    if t.device.type != "cpu":
        raise ValueError(
            "horovod_tpu.torch bridges CPU tensors; move the tensor to CPU "
            "first (the TPU compute path is the JAX API)"
        )
    t = t.detach()
    if not t.is_contiguous():
        t = t.contiguous()
    if jax.default_backend() == "cpu":
        try:
            return jnp.from_dlpack(t)
        except Exception:
            pass  # exotic dtype/layout: host-copy fallback below
    return jnp.asarray(t.numpy())


def _result_view(a) -> torch.Tensor:
    """Zero-copy torch view of an engine result.  The XLA buffer is
    immutable — callers must never write through this view; they either
    ``.to(copy=True)`` it (out-of-place ops) or ``copy_`` FROM it
    (in-place ops)."""
    try:
        return torch.from_dlpack(a)
    except Exception:
        return torch.from_numpy(np.array(a, copy=True))


def _from_engine(a, like: torch.Tensor) -> torch.Tensor:
    # exactly one memcpy: the dlpack view aliases the immutable XLA
    # buffer; the clone is the user-owned, freely mutable result tensor
    return _result_view(a).to(like.dtype, copy=True)


class _HandleManager:
    """Int-keyed handle table (reference: torch/handle_manager.cc)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._next = 0
        self._entries: Dict[int, Tuple[_ops.Handle, callable]] = {}

    def allocate(self, inner: _ops.Handle, finalize) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._entries[h] = (inner, finalize)
            return h

    def pop(self, handle: int):
        with self._lock:
            return self._entries.pop(handle)

    def peek(self, handle: int):
        with self._lock:
            return self._entries.get(handle)


_handles = _HandleManager()


def synchronize(handle: int) -> torch.Tensor:
    """Wait for an async op and return its output (reference:
    horovod/torch/mpi_ops.py synchronize)."""
    inner, finalize = _handles.pop(handle)
    return finalize(inner.wait())


def poll(handle: int) -> bool:
    """Reference: horovod/torch/mpi_ops.py poll."""
    entry = _handles.peek(handle)
    return entry is None or entry[0].done()


# -- allreduce ---------------------------------------------------------------


def allreduce_async(tensor: torch.Tensor, average: Optional[bool] = None,
                    name: Optional[str] = None, op: Optional[ReduceOp] = None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    process_set: Optional[ProcessSet] = None) -> int:
    inner = _ops.allreduce_async(
        _to_jax(tensor), average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set,
    )
    return _handles.allocate(inner, lambda out: _from_engine(out, tensor))


def allreduce(tensor: torch.Tensor, **kwargs) -> torch.Tensor:
    return synchronize(allreduce_async(tensor, **kwargs))


def allreduce_multi_async(tensors: Sequence[torch.Tensor],
                          names: Sequence[str], **kwargs) -> List[int]:
    """N independent named allreduces, one batched native submission,
    one handle per tensor (the DistributedOptimizer backward-burst path;
    see ops.collective_ops.allreduce_multi_async)."""
    inners = _ops.allreduce_multi_async(
        [_to_jax(t) for t in tensors], names, **kwargs
    )
    return [
        _handles.allocate(inner, (lambda t: lambda out: _from_engine(out, t))(t))
        for inner, t in zip(inners, tensors)
    ]


def allreduce_async_(tensor: torch.Tensor, **kwargs) -> int:
    """In-place async allreduce (reference: allreduce_async_)."""
    inner = _ops.allreduce_async(_to_jax(tensor), **kwargs)

    def finalize(out):
        tensor.copy_(_result_view(out))
        return tensor

    return _handles.allocate(inner, finalize)


def allreduce_(tensor: torch.Tensor, **kwargs) -> torch.Tensor:
    return synchronize(allreduce_async_(tensor, **kwargs))


def grouped_allreduce_async(tensors: Sequence[torch.Tensor],
                            **kwargs) -> int:
    inner = _ops.grouped_allreduce_async(
        [_to_jax(t) for t in tensors], **kwargs
    )

    def finalize(outs):
        return [_from_engine(o, t) for o, t in zip(outs, tensors)]

    return _handles.allocate(inner, finalize)


def grouped_allreduce(tensors: Sequence[torch.Tensor], **kwargs) -> list:
    return synchronize(grouped_allreduce_async(tensors, **kwargs))


def grouped_allreduce_async_(tensors: Sequence[torch.Tensor],
                             **kwargs) -> int:
    inner = _ops.grouped_allreduce_async(
        [_to_jax(t) for t in tensors], **kwargs
    )

    def finalize(outs):
        for o, t in zip(outs, tensors):
            t.copy_(_result_view(o))
        return list(tensors)

    return _handles.allocate(inner, finalize)


def grouped_allreduce_(tensors: Sequence[torch.Tensor], **kwargs) -> list:
    return synchronize(grouped_allreduce_async_(tensors, **kwargs))


# -- allgather ---------------------------------------------------------------


def allgather_async(tensor: torch.Tensor, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    inner = _ops.allgather_async(_to_jax(tensor), name=name,
                                 process_set=process_set)
    return _handles.allocate(inner, lambda out: _from_engine(out, tensor))


def allgather(tensor: torch.Tensor, **kwargs) -> torch.Tensor:
    return synchronize(allgather_async(tensor, **kwargs))


def grouped_allgather(tensors: Sequence[torch.Tensor],
                      name: Optional[str] = None,
                      process_set: Optional[ProcessSet] = None) -> list:
    """Reference: torch grouped_allgather — one fused dim0-table
    exchange + per-dtype-bucket gather (ops/collective_ops.py)."""
    outs = _ops.grouped_allgather(
        [_to_jax(t) for t in tensors], name=name, process_set=process_set
    )
    return [_from_engine(o, t) for o, t in zip(outs, tensors)]


# -- broadcast ---------------------------------------------------------------


def broadcast_async(tensor: torch.Tensor, root_rank: int,
                    name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    inner = _ops.broadcast_async(_to_jax(tensor), root_rank, name=name,
                                 process_set=process_set)
    return _handles.allocate(inner, lambda out: _from_engine(out, tensor))


def broadcast(tensor: torch.Tensor, root_rank: int, **kwargs) -> torch.Tensor:
    return synchronize(broadcast_async(tensor, root_rank, **kwargs))


def broadcast_async_(tensor: torch.Tensor, root_rank: int,
                     **kwargs) -> int:
    inner = _ops.broadcast_async(_to_jax(tensor), root_rank, **kwargs)

    def finalize(out):
        tensor.copy_(_result_view(out))
        return tensor

    return _handles.allocate(inner, finalize)


def broadcast_(tensor: torch.Tensor, root_rank: int, **kwargs) -> torch.Tensor:
    return synchronize(broadcast_async_(tensor, root_rank, **kwargs))


# -- alltoall / reducescatter ------------------------------------------------


def alltoall_async(tensor: torch.Tensor,
                   splits: Optional[torch.Tensor] = None,
                   name: Optional[str] = None,
                   process_set: Optional[ProcessSet] = None) -> int:
    np_splits = None if splits is None else _to_jax(splits)
    inner = _ops.alltoall_async(_to_jax(tensor), splits=np_splits, name=name,
                                process_set=process_set)

    def finalize(out):
        received, recv_splits = out
        # np.array(copy=True): recv_splits can arrive as a read-only
        # buffer view, and from_numpy on one yields a tensor whose
        # in-place writes are undefined behavior (ADVICE round 3)
        return (_from_engine(received, tensor),
                torch.from_numpy(
                    np.array(recv_splits, copy=True)).to(torch.int32))

    return _handles.allocate(inner, finalize)


def alltoall(tensor: torch.Tensor, **kwargs):
    return synchronize(alltoall_async(tensor, **kwargs))


def reducescatter_async(tensor: torch.Tensor, op: Optional[ReduceOp] = None,
                        name: Optional[str] = None,
                        process_set: Optional[ProcessSet] = None) -> int:
    inner = _ops.reducescatter_async(_to_jax(tensor), op=op, name=name,
                                     process_set=process_set)
    return _handles.allocate(inner, lambda out: _from_engine(out, tensor))


def reducescatter(tensor: torch.Tensor, **kwargs) -> torch.Tensor:
    return synchronize(reducescatter_async(tensor, **kwargs))


def grouped_reducescatter_async(tensors: Sequence[torch.Tensor],
                                **kwargs) -> int:
    """Reference: torch grouped_reducescatter — atomic group release via
    the native GroupTable id."""
    inner = _ops.grouped_reducescatter_async(
        [_to_jax(t) for t in tensors], **kwargs
    )

    def finalize(outs):
        return [_from_engine(o, t) for o, t in zip(outs, tensors)]

    return _handles.allocate(inner, finalize)


def grouped_reducescatter(tensors: Sequence[torch.Tensor],
                          **kwargs) -> list:
    return synchronize(grouped_reducescatter_async(tensors, **kwargs))


def barrier(process_set: Optional[ProcessSet] = None) -> None:
    _ops.barrier(process_set=process_set)


def join() -> int:
    return _ops.join()
