"""Torch tensor collectives over the XLA engine.

Reference parity: horovod/torch/mpi_ops.py + the C++ binding it fronts
(torch/mpi_ops_v2.cc, adapter_v2.cc, handle_manager.cc — SURVEY.md §2.3).
The reference wraps ``at::Tensor`` into ``common::Tensor`` and enqueues to
the background thread; here a CPU torch tensor is viewed as numpy
(zero-copy), routed through the same eager engine the JAX API uses, and
the result copied back.  Handles mirror the reference's int-keyed
HandleManager: ``*_async`` returns a handle consumed by ``synchronize`` /
``poll``.

In-place variants (``allreduce_`` etc.) write the result back into the
input tensor, matching reference semantics.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import torch

from ..common.process_sets import ProcessSet
from ..ops import collective_ops as _ops
from ..ops.reduce_ops import ReduceOp


def _to_np(t: torch.Tensor) -> np.ndarray:
    if t.device.type != "cpu":
        raise ValueError(
            "horovod_tpu.torch bridges CPU tensors; move the tensor to CPU "
            "first (the TPU compute path is the JAX API)"
        )
    return t.detach().contiguous().numpy()


def _from_np(a, like: torch.Tensor) -> torch.Tensor:
    # copy: the source is an immutable XLA buffer view; handing torch a
    # writable alias of it would be undefined behavior
    return torch.from_numpy(np.array(a, copy=True)).to(like.dtype)


class _HandleManager:
    """Int-keyed handle table (reference: torch/handle_manager.cc)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._next = 0
        self._entries: Dict[int, Tuple[_ops.Handle, callable]] = {}

    def allocate(self, inner: _ops.Handle, finalize) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._entries[h] = (inner, finalize)
            return h

    def pop(self, handle: int):
        with self._lock:
            return self._entries.pop(handle)

    def peek(self, handle: int):
        with self._lock:
            return self._entries.get(handle)


_handles = _HandleManager()


def synchronize(handle: int) -> torch.Tensor:
    """Wait for an async op and return its output (reference:
    horovod/torch/mpi_ops.py synchronize)."""
    inner, finalize = _handles.pop(handle)
    return finalize(inner.wait())


def poll(handle: int) -> bool:
    """Reference: horovod/torch/mpi_ops.py poll."""
    entry = _handles.peek(handle)
    return entry is None or entry[0].done()


# -- allreduce ---------------------------------------------------------------


def allreduce_async(tensor: torch.Tensor, average: Optional[bool] = None,
                    name: Optional[str] = None, op: Optional[ReduceOp] = None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    process_set: Optional[ProcessSet] = None) -> int:
    inner = _ops.allreduce_async(
        _to_np(tensor), average=average, name=name, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set,
    )
    return _handles.allocate(inner, lambda out: _from_np(out, tensor))


def allreduce(tensor: torch.Tensor, **kwargs) -> torch.Tensor:
    return synchronize(allreduce_async(tensor, **kwargs))


def allreduce_async_(tensor: torch.Tensor, **kwargs) -> int:
    """In-place async allreduce (reference: allreduce_async_)."""
    inner = _ops.allreduce_async(_to_np(tensor), **kwargs)

    def finalize(out):
        tensor.copy_(_from_np(out, tensor))
        return tensor

    return _handles.allocate(inner, finalize)


def allreduce_(tensor: torch.Tensor, **kwargs) -> torch.Tensor:
    return synchronize(allreduce_async_(tensor, **kwargs))


def grouped_allreduce_async(tensors: Sequence[torch.Tensor],
                            **kwargs) -> int:
    inner = _ops.grouped_allreduce_async(
        [_to_np(t) for t in tensors], **kwargs
    )

    def finalize(outs):
        return [_from_np(o, t) for o, t in zip(outs, tensors)]

    return _handles.allocate(inner, finalize)


def grouped_allreduce(tensors: Sequence[torch.Tensor], **kwargs) -> list:
    return synchronize(grouped_allreduce_async(tensors, **kwargs))


def grouped_allreduce_async_(tensors: Sequence[torch.Tensor],
                             **kwargs) -> int:
    inner = _ops.grouped_allreduce_async(
        [_to_np(t) for t in tensors], **kwargs
    )

    def finalize(outs):
        for o, t in zip(outs, tensors):
            t.copy_(_from_np(o, t))
        return list(tensors)

    return _handles.allocate(inner, finalize)


def grouped_allreduce_(tensors: Sequence[torch.Tensor], **kwargs) -> list:
    return synchronize(grouped_allreduce_async_(tensors, **kwargs))


# -- allgather ---------------------------------------------------------------


def allgather_async(tensor: torch.Tensor, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    inner = _ops.allgather_async(_to_np(tensor), name=name,
                                 process_set=process_set)
    return _handles.allocate(inner, lambda out: _from_np(out, tensor))


def allgather(tensor: torch.Tensor, **kwargs) -> torch.Tensor:
    return synchronize(allgather_async(tensor, **kwargs))


def grouped_allgather(tensors: Sequence[torch.Tensor],
                      name: Optional[str] = None,
                      process_set: Optional[ProcessSet] = None) -> list:
    """Reference: torch grouped_allgather — one fused dim0-table
    exchange + per-dtype-bucket gather (ops/collective_ops.py)."""
    outs = _ops.grouped_allgather(
        [_to_np(t) for t in tensors], name=name, process_set=process_set
    )
    return [_from_np(o, t) for o, t in zip(outs, tensors)]


# -- broadcast ---------------------------------------------------------------


def broadcast_async(tensor: torch.Tensor, root_rank: int,
                    name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    inner = _ops.broadcast_async(_to_np(tensor), root_rank, name=name,
                                 process_set=process_set)
    return _handles.allocate(inner, lambda out: _from_np(out, tensor))


def broadcast(tensor: torch.Tensor, root_rank: int, **kwargs) -> torch.Tensor:
    return synchronize(broadcast_async(tensor, root_rank, **kwargs))


def broadcast_async_(tensor: torch.Tensor, root_rank: int,
                     **kwargs) -> int:
    inner = _ops.broadcast_async(_to_np(tensor), root_rank, **kwargs)

    def finalize(out):
        tensor.copy_(_from_np(out, tensor))
        return tensor

    return _handles.allocate(inner, finalize)


def broadcast_(tensor: torch.Tensor, root_rank: int, **kwargs) -> torch.Tensor:
    return synchronize(broadcast_async_(tensor, root_rank, **kwargs))


# -- alltoall / reducescatter ------------------------------------------------


def alltoall_async(tensor: torch.Tensor,
                   splits: Optional[torch.Tensor] = None,
                   name: Optional[str] = None,
                   process_set: Optional[ProcessSet] = None) -> int:
    np_splits = None if splits is None else _to_np(splits)
    inner = _ops.alltoall_async(_to_np(tensor), splits=np_splits, name=name,
                                process_set=process_set)

    def finalize(out):
        received, recv_splits = out
        # np.array(copy=True): recv_splits can arrive as a read-only
        # buffer view, and from_numpy on one yields a tensor whose
        # in-place writes are undefined behavior (ADVICE round 3)
        return (_from_np(received, tensor),
                torch.from_numpy(
                    np.array(recv_splits, copy=True)).to(torch.int32))

    return _handles.allocate(inner, finalize)


def alltoall(tensor: torch.Tensor, **kwargs):
    return synchronize(alltoall_async(tensor, **kwargs))


def reducescatter_async(tensor: torch.Tensor, op: Optional[ReduceOp] = None,
                        name: Optional[str] = None,
                        process_set: Optional[ProcessSet] = None) -> int:
    inner = _ops.reducescatter_async(_to_np(tensor), op=op, name=name,
                                     process_set=process_set)
    return _handles.allocate(inner, lambda out: _from_np(out, tensor))


def reducescatter(tensor: torch.Tensor, **kwargs) -> torch.Tensor:
    return synchronize(reducescatter_async(tensor, **kwargs))


def grouped_reducescatter_async(tensors: Sequence[torch.Tensor],
                                **kwargs) -> int:
    """Reference: torch grouped_reducescatter — atomic group release via
    the native GroupTable id."""
    inner = _ops.grouped_reducescatter_async(
        [_to_np(t) for t in tensors], **kwargs
    )

    def finalize(outs):
        return [_from_np(o, t) for o, t in zip(outs, tensors)]

    return _handles.allocate(inner, finalize)


def grouped_reducescatter(tensors: Sequence[torch.Tensor],
                          **kwargs) -> list:
    return synchronize(grouped_reducescatter_async(tensors, **kwargs))


def barrier(process_set: Optional[ProcessSet] = None) -> None:
    _ops.barrier(process_set=process_set)


def join() -> int:
    return _ops.join()
