"""SyncBatchNorm for torch models.

Reference parity: horovod/torch/sync_batch_norm.py — batch-norm whose
statistics are reduced across all workers each forward pass, with the
matching allreduce in backward.  Differentiable collectives are expressed
as ``torch.autograd.Function``s over the adapter's allreduce (the
reference calls its C++ ops the same way).
"""

from __future__ import annotations

import torch
from torch.nn.modules.batchnorm import _BatchNorm

from ..ops.reduce_ops import Sum
from . import mpi_ops
from ..common import basics


class _SyncSum(torch.autograd.Function):
    """Differentiable cross-worker sum: backward of a sum-allreduce is a
    sum-allreduce of the gradient."""

    @staticmethod
    def forward(ctx, x):
        return mpi_ops.allreduce(x, op=Sum)

    @staticmethod
    def backward(ctx, grad):
        return mpi_ops.allreduce(grad.contiguous(), op=Sum)


class SyncBatchNorm(_BatchNorm):
    """Drop-in replacement for ``nn.BatchNorm*d`` with cross-worker stats
    (reference: hvd.SyncBatchNorm).  Statistics are computed from global
    sum / sum-of-squares / count, exactly the reference's formulation."""

    def _check_input_dim(self, input):
        if input.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {input.dim()}D)"
            )

    def forward(self, input):
        if not (self.training and basics.is_initialized()
                and basics.cross_size() > 1):
            return super().forward(input)

        self._check_input_dim(input)
        dims = [0] + list(range(2, input.dim()))
        count = torch.tensor(
            [float(input.numel() // input.size(1))], dtype=input.dtype
        )
        local_sum = input.sum(dims)
        local_sq = (input * input).sum(dims)

        packed = torch.cat([count, local_sum, local_sq])
        packed = _SyncSum.apply(packed)
        global_count = packed[0]
        mean = packed[1:1 + input.size(1)] / global_count
        sq = packed[1 + input.size(1):] / global_count
        var = sq - mean * mean

        if self.track_running_stats and self.running_mean is not None:
            with torch.no_grad():
                m = self.momentum if self.momentum is not None else 0.1
                n = global_count
                unbiased = var * (n / (n - 1)) if n > 1 else var
                self.running_mean.mul_(1 - m).add_(mean.detach() * m)
                self.running_var.mul_(1 - m).add_(unbiased.detach() * m)
                if self.num_batches_tracked is not None:
                    self.num_batches_tracked.add_(1)

        shape = [1, -1] + [1] * (input.dim() - 2)
        out = (input - mean.reshape(shape)) / torch.sqrt(
            var.reshape(shape) + self.eps
        )
        if self.affine:
            out = out * self.weight.reshape(shape) + \
                self.bias.reshape(shape)
        return out
