"""Elastic state for torch models.

Reference parity: horovod/torch/elastic/state.py (TorchState) +
torch/elastic/sampler.py (ElasticSampler — the shared implementation in
``horovod_tpu.elastic.sampler`` already satisfies torch's Sampler
protocol: ``__iter__`` over indices + ``__len__``).
"""

from __future__ import annotations

from ..elastic import ObjectState, run  # noqa: F401 (re-export)
from ..elastic.sampler import ElasticSampler  # noqa: F401 (re-export)


class TorchState(ObjectState):
    """Elastic state holding torch modules/optimizers (reference:
    TorchState(model=..., optimizer=..., epoch=0, batch=0)).  Modules and
    optimizers expose ``state_dict``/``load_state_dict``, which the base
    ObjectState snapshots and syncs through — matching the reference's
    capture→broadcast design."""
