"""horovod_tpu.torch: the PyTorch framework adapter.

Reference parity: the ``horovod.torch`` surface (horovod/torch/__init__.py,
mpi_ops.py, optimizer.py, functions.py, sync_batch_norm.py,
compression.py, elastic/ — SURVEY.md §2.3).  A reference training script
needs only its import changed::

    import horovod_tpu.torch as hvd
    hvd.init()
    optimizer = hvd.DistributedOptimizer(optimizer,
                                         named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

Design: torch stays the model/autograd frontend; collectives execute as
compiled XLA programs through the shared eager engine (CPU tensors bridge
zero-copy via numpy).  The TPU compute path for new code is the JAX API;
this adapter exists for reference-script parity and CPU-hosted torch
training.
"""

from __future__ import annotations

# lifecycle + topology (shared with the JAX surface)
from ..common.basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, local_rank, size, local_size,
    cross_rank, cross_size, is_homogeneous, xla_built, nccl_built,
    mpi_enabled, mpi_built, mpi_threads_supported, gloo_built,
    gloo_enabled, ccl_built, cuda_built, rocm_built, ddl_built,
    native_built, start_timeline, stop_timeline,
)
from ..common.exceptions import (  # noqa: F401
    HorovodInternalError, HostsUpdatedInterrupt,
)
from ..common.process_sets import ProcessSet, global_process_set  # noqa: F401
from .. import add_process_set, remove_process_set  # noqa: F401
from ..ops.reduce_ops import (  # noqa: F401
    Adasum, Average, Max, Min, Product, ReduceOp, Sum,
)
from .compression import Compression  # noqa: F401
from .functions import (  # noqa: F401
    allgather_object, broadcast_object, broadcast_optimizer_state,
    broadcast_parameters,
)
from .mpi_ops import (  # noqa: F401
    allgather, allgather_async, allreduce, allreduce_, allreduce_async,
    allreduce_async_, alltoall, alltoall_async, barrier, broadcast,
    broadcast_, broadcast_async, broadcast_async_, grouped_allgather,
    grouped_allreduce, grouped_allreduce_, grouped_allreduce_async,
    grouped_allreduce_async_, grouped_reducescatter,
    grouped_reducescatter_async, join, poll, reducescatter,
    reducescatter_async, synchronize,
)
from .optimizer import DistributedOptimizer  # noqa: F401
from .sync_batch_norm import SyncBatchNorm  # noqa: F401
from . import elastic  # noqa: F401
