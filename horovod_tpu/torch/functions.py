"""State broadcast helpers for torch models.

Reference parity: horovod/torch/functions.py — broadcast_parameters,
broadcast_optimizer_state, broadcast_object (SURVEY.md §2.3), used at
train start so all workers leave rank 0's initialization identically.
"""

from __future__ import annotations

import collections
from typing import Any

import torch

from .. import functions as _jax_functions
from . import mpi_ops


def broadcast_parameters(params, root_rank: int = 0,
                         process_set=None) -> None:
    """Broadcast a ``model.state_dict()`` or ``named_parameters``
    (reference: horovod/torch/functions.py broadcast_parameters)."""
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    handles = []
    for name, p in items:
        if isinstance(p, torch.Tensor):
            handles.append(
                mpi_ops.broadcast_async_(p.data if hasattr(p, "data") else p,
                                         root_rank, name=name,
                                         process_set=process_set)
            )
    for h in handles:
        mpi_ops.synchronize(h)


def broadcast_object(obj: Any, root_rank: int = 0, name: str = None,
                     process_set=None) -> Any:
    """Reference: horovod/torch/mpi_ops.py broadcast_object (pickle +
    size/payload broadcast); delegates to the shared implementation."""
    return _jax_functions.broadcast_object(obj, root_rank=root_rank,
                                           process_set=process_set)


def allgather_object(obj: Any, name: str = None, process_set=None) -> list:
    """Reference: horovod/torch/mpi_ops.py allgather_object — per-rank
    pickled payloads gathered to every rank; delegates to the shared
    implementation."""
    return _jax_functions.allgather_object(obj, process_set=process_set)


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0, process_set=None) -> None:
    """Broadcast optimizer state dict from root (reference:
    horovod/torch/functions.py broadcast_optimizer_state — which walks the
    state dict broadcasting tensors and pickling scalars; the same split
    here: tensors via broadcast_, the structure via broadcast_object)."""
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError(
            "cannot broadcast torch.optim.LBFGS state (reference limitation)"
        )
    state_dict = optimizer.state_dict()

    # split tensors out of the state dict so they ride the tensor path
    tensors = {}

    def strip(prefix, value):
        if isinstance(value, torch.Tensor):
            tensors[prefix] = value
            return ("__tensor__", prefix, value.dtype, tuple(value.shape))
        if isinstance(value, dict):
            return {k: strip(f"{prefix}.{k}", v) for k, v in value.items()}
        if isinstance(value, (list, tuple)):
            out = [strip(f"{prefix}.{i}", v) for i, v in enumerate(value)]
            return type(value)(out) if isinstance(value, tuple) else out
        return value

    skeleton = strip("state", state_dict)
    skeleton = broadcast_object(skeleton, root_rank=root_rank,
                                process_set=process_set)

    # workers whose optimizer hasn't stepped yet have no state tensors:
    # materialize zeros from the broadcast metadata so the tensor broadcast
    # has a landing buffer (reference handles this by pre-initializing the
    # optimizer state before broadcasting)
    def collect_markers(value):
        if isinstance(value, tuple) and len(value) == 4 and \
                value[0] == "__tensor__":
            if value[1] not in tensors:
                tensors[value[1]] = torch.zeros(value[3], dtype=value[2])
        elif isinstance(value, dict):
            for v in value.values():
                collect_markers(v)
        elif isinstance(value, list):
            for v in value:
                collect_markers(v)

    collect_markers(skeleton)

    handles = [
        mpi_ops.broadcast_async_(t, root_rank, name=f"opt.{k}",
                                 process_set=process_set)
        for k, t in sorted(tensors.items())
    ]
    for h in handles:
        mpi_ops.synchronize(h)

    def rebuild(value):
        if isinstance(value, tuple) and len(value) == 4 and \
                value[0] == "__tensor__":
            return tensors[value[1]]
        if isinstance(value, dict):
            return {k: rebuild(v) for k, v in value.items()}
        if isinstance(value, list):
            return [rebuild(v) for v in value]
        return value

    optimizer.load_state_dict(rebuild(skeleton))
