"""Data-parallel training loop building blocks.

Reference analog: the training-loop pattern repeated across the reference's
examples/ (hvd.init → broadcast_parameters → DistributedOptimizer step —
SURVEY.md §3.2) packaged as a library: a ``TrainState`` and a compiled
SPMD train step over the world mesh.  One call produces the whole hot
path — forward, backward, fused gradient allreduce over ICI, optimizer
update — as a single XLA program, which is the TPU-native replacement for
the reference's background-thread overlap machinery.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .common import basics
from .common.retry import env_int
from .common.topology import WORLD_AXIS
from .ops import spmd_ops
from .ops.reduce_ops import Average, ReduceOp


def _resolve_guard(guard: Optional[bool]) -> bool:
    """``guard=None`` defers to ``HVD_TPU_GUARD`` (docs/running.md) —
    the env spelling the ``HVD_TPU_GUARD=0`` zero-added-collectives
    contract is stated against (tools/guard_bench.py pins it)."""
    if guard is None:
        return bool(env_int("HVD_TPU_GUARD", 0))
    return bool(guard)


class TrainState(flax.struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    batch_stats: Any = None


def softmax_cross_entropy(logits, labels):
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels
    ).mean()


def create_train_state(
    model, optimizer: optax.GradientTransformation, rng, sample_input
) -> TrainState:
    variables = model.init(rng, sample_input)
    params = variables["params"]
    batch_stats = variables.get("batch_stats")
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=optimizer.init(params),
        batch_stats=batch_stats,
    )


def _resolve_segmenter(model, segmenter):
    """The overlap segment-chain builder for ``model``:
    ``segmenter(model, inputs, labels, loss_fn) -> [Segment]``.  The two
    flagship transformers ship theirs; any other model must pass one
    explicitly (docs/tensor-fusion.md describes the chain contract)."""
    if segmenter is not None:
        return segmenter
    from .models.transformer import Transformer, overlap_segments

    if isinstance(model, Transformer):
        return overlap_segments
    raise ValueError(
        f"overlap=True needs a segment chain for {type(model).__name__}; "
        "pass segmenter=(model, inputs, labels, loss_fn) -> [Segment] "
        "(models.transformer / parallel.sharded ship theirs)"
    )


def _overlap_bucket_reduce(axis, op, world):
    """Per-bucket reduction of the overlapped data-parallel backward —
    the SAME arithmetic as ``spmd_ops.allreduce`` applied leaf-wise
    (psum, then divide for Average), so overlapped and unoverlapped
    steps stay bit-equal."""

    def bucket_reduce(buf):
        return spmd_ops.allreduce(buf, op=op, axis=axis)

    return bucket_reduce


def data_parallel_train_step(
    model,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    axis: str = WORLD_AXIS,
    loss_fn: Callable = softmax_cross_entropy,
    op: ReduceOp = Average,
    overlap: bool = False,
    segmenter: Optional[Callable] = None,
    bucket_bytes: Optional[int] = None,
    guard: Optional[bool] = None,
) -> Callable:
    """Build the compiled data-parallel train step.

    Returns ``step(state, images, labels) -> (state, loss)`` where the
    batch is sharded over ``axis`` and gradients are reduced with ``op``
    across it.  Everything the reference does per-step in §3.2 (ready-event
    waits, fusion memcpys, NCCL ring, handle sync) is this one program.

    ``optimizer`` should be the *inner* optax optimizer — the gradient
    allreduce is inserted here (equivalent to wrapping with
    DistributedOptimizer; don't do both or gradients reduce twice).

    ``overlap=True`` stages the backward at bucket boundaries
    (``ops/overlap.py``): each :class:`~horovod_tpu.ops.fusion.
    BucketSchedule` bucket's allreduce launches while earlier segments'
    gradients are still computing, instead of the whole reduction
    trailing the backward.  Gradients and updates stay bit-equal to the
    unoverlapped step at fp32.  Requires a segment-chain model
    (:func:`models.transformer.overlap_segments` is used for the
    flagship ``Transformer``; pass ``segmenter`` otherwise) and no
    ``batch_stats``; ``bucket_bytes`` overrides
    ``HVD_TPU_OVERLAP_BUCKET_BYTES``.

    ``guard=True`` (``None`` = the ``HVD_TPU_GUARD`` env flag) makes
    the step ALSO return the silent-corruption diagnostics
    (:func:`horovod_tpu.guard.step_diag` over the POST-allreduce
    gradients): ``step(state, x, y) -> (state, loss, diag)``.  The
    detectors are pure extra outputs over the same dataflow — state
    and loss stay BIT-identical to the unguarded step, and no
    collective is added (the digest exchange runs host-side at
    cadence; see :func:`fit_epoch` and docs/FAULT_TOLERANCE.md).
    """
    guard = _resolve_guard(guard)
    if mesh is None:
        mesh = basics._require_init().process_set_registry.get(0).mesh
    if overlap:
        segmenter = _resolve_segmenter(model, segmenter)
        if op not in (ReduceOp.AVERAGE, ReduceOp.SUM):
            raise ValueError(
                f"overlap supports Sum/Average gradient reduction, got "
                f"{op!r}"
            )
        world = int(mesh.shape[axis])

    def _step(state: TrainState, images, labels):
        if overlap:
            from .ops.overlap import overlapped_value_and_grad

            if state.batch_stats is not None:
                raise ValueError(
                    "overlap=True does not support batch_stats models"
                )
            loss, grads, _ = overlapped_value_and_grad(
                segmenter(model, images, labels, loss_fn),
                state.params, images,
                bucket_reduce=_overlap_bucket_reduce(axis, op, world),
                bucket_bytes=bucket_bytes,
            )
            new_stats = None
            loss = spmd_ops.allreduce(loss, axis=axis)
            updates, new_opt_state = optimizer.update(
                grads, state.opt_state, state.params
            )
            new_params = optax.apply_updates(state.params, updates)
            new_state = TrainState(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt_state,
                batch_stats=new_stats,
            )
            if guard:
                from .guard import step_diag

                return new_state, loss, step_diag(loss, grads)
            return new_state, loss

        def compute_loss(params):
            variables = {"params": params}
            if state.batch_stats is not None:
                variables["batch_stats"] = state.batch_stats
                out, updates = model.apply(
                    variables, images, mutable=["batch_stats"]
                )
                logits = out
                new_stats = updates["batch_stats"]
            else:
                logits = model.apply(variables, images)
                new_stats = None
            return loss_fn(logits, labels), new_stats

        (loss, new_stats), grads = jax.value_and_grad(
            compute_loss, has_aux=True
        )(state.params)
        grads = spmd_ops.allreduce(grads, op=op, axis=axis)
        loss = spmd_ops.allreduce(loss, axis=axis)
        if new_stats is not None:
            # replicas see different batches -> average the running stats
            # (sync-BN semantics; reference: torch/sync_batch_norm.py)
            new_stats = spmd_ops.allreduce(new_stats, axis=axis)
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            step=state.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            batch_stats=new_stats,
        )
        if guard:
            from .guard import step_diag

            return new_state, loss, step_diag(loss, grads)
        return new_state, loss

    sharded = jax.shard_map(
        _step,
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis)),
        out_specs=(P(), P(), P()) if guard else (P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def zero_train_setup(
    model,
    inner_optimizer: optax.GradientTransformation,
    rng,
    sample_input,
    mesh: Optional[Mesh] = None,
    axis: str = WORLD_AXIS,
    loss_fn: Callable = softmax_cross_entropy,
    op: ReduceOp = Average,
    hierarchical: bool = False,
    dcn_compression=None,
    overlap: bool = False,
    segmenter: Optional[Callable] = None,
    bucket_bytes: Optional[int] = None,
    guard: Optional[bool] = None,
):
    """Build a ZeRO-sharded data-parallel trainer over the world mesh.

    The sharded sibling of ``create_train_state`` +
    ``data_parallel_train_step``: the optimizer state is partitioned
    across ``axis`` (``optim.ZeroSpmdOptimizer`` — reduce-scatter →
    local shard update → allgather inside the one compiled step), so
    each chip holds ~1/world of Adam's m/v instead of a full replica —
    the ZeRO stage-1 memory attack on PERF.md's large-batch limiter.

    ``hierarchical=True`` lays the same program over the topology's
    2-D ``hierarchical_mesh()`` instead: the ZeRO exchange runs
    ICI-first and only the 1/n_ici piece crosses DCN — optionally in
    ``dcn_compression``'s wire dtype (docs/COLLECTIVES.md byte model);
    ``mesh`` then defaults to ``topology.hierarchical_mesh()`` and
    ``axis`` is ignored in favor of the ``(dcn, ici)`` fabric axes.

    Returns ``(state, step, opt_state_specs)``: ``state.opt_state``
    leaves that mirror shard buffers are laid out ``P(axis)`` on the
    mesh (``opt_state_specs`` says which — also what per-rank memory
    accounting divides by world), and ``step(state, inputs, labels) ->
    (state, loss)`` matches ``data_parallel_train_step``'s contract.
    Pass the INNER optax optimizer; do not wrap it in a Zero/Distributed
    wrapper yourself.

    ``overlap=True`` composes the bucket-boundary backward
    (``ops/overlap.py``) with ZeRO: the gradient exchange IS the
    collective the buckets launch, so each bucket's reduction rides an
    earlier segment's backward and the wrapper slices its pre-reduced
    shard locally (``ZeroSpmdOptimizer(pre_reduced=True)``).  Exactness
    vs the unoverlapped ZeRO step at fp32: gradients bit-equal; updates
    bit-equal for elementwise-exact inners (sgd); fma-bearing inners
    (adam's ``g²`` moment) may drift ≤2 ulp/step from XLA contracting
    the fma differently across the two program shapes —
    tests/test_overlap.py pins both, docs/OPTIM.md documents the
    caveat.  Error-feedback DCN compression needs the reduce-scatter
    hop the overlapped exchange folds into the buckets, so it does not
    compose (stateless wire compression does).

    ``guard=True`` (``None`` = ``HVD_TPU_GUARD``) adds the silent-
    corruption diagnostics as a third step output, composing with
    every mode above.  Both detectors read only REPLICATED values —
    the mean loss and the POST-allgather update deltas (the cross-rank
    agreement object): per-chip intermediates (local grads, the
    reduce-scattered shards) differ across devices by design and
    cannot ride the diag's ``P()`` output spec; a non-finite shard is
    still caught the SAME cadence because the inner update propagates
    it into the allgathered deltas.  State and loss stay bit-identical
    to the unguarded step; zero collectives are added.
    """
    from .common.topology import DCN_AXIS, ICI_AXIS
    from .optim import ZeroSpmdOptimizer, zero_opt_state_specs

    guard = _resolve_guard(guard)

    if overlap and dcn_compression is not None and getattr(
        dcn_compression, "error_feedback", False
    ):
        raise ValueError(
            "overlap=True folds the gradient reduce-scatter into the "
            "bucket collectives — error_feedback compression (which "
            "rides that hop's residual) does not compose; use stateless "
            "DcnCompression or overlap=False"
        )
    if overlap:
        segmenter = _resolve_segmenter(model, segmenter)
    if hierarchical:
        if mesh is None:
            mesh = basics._require_init().topology.hierarchical_mesh()
        axis = (DCN_AXIS, ICI_AXIS)
        world = int(mesh.shape[DCN_AXIS] * mesh.shape[ICI_AXIS])
        zopt = ZeroSpmdOptimizer(
            inner_optimizer, op=op, hierarchical=True,
            ici_axis=ICI_AXIS, dcn_axis=DCN_AXIS,
            dcn_compression=dcn_compression,
            pre_reduced=overlap,
        )
    else:
        if mesh is None:
            mesh = basics._require_init().process_set_registry.get(0).mesh
        world = int(mesh.shape[axis])
        zopt = ZeroSpmdOptimizer(inner_optimizer, axis=axis, op=op,
                                 pre_reduced=overlap)

    variables = model.init(rng, sample_input)
    params = variables["params"]
    batch_stats = variables.get("batch_stats")
    ospecs = zero_opt_state_specs(
        inner_optimizer, params, world, axis,
        dcn_compression=dcn_compression if hierarchical else None,
    )
    opt_state = jax.jit(jax.shard_map(
        zopt.init, mesh=mesh, in_specs=(P(),), out_specs=ospecs,
        check_vma=False,
    ))(params)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=opt_state,
        batch_stats=batch_stats,
    )
    state_specs = TrainState(
        step=P(),
        params=P(),
        opt_state=ospecs,
        batch_stats=P() if batch_stats is not None else None,
    )

    def _mean(x):
        # a tuple axis (the hierarchical fabric mesh) means over both
        if isinstance(axis, tuple):
            return jax.tree_util.tree_map(
                # contract-ok: collectives -- unconditional scalar loss mean over BOTH fabric axes; the single-axis public API cannot spell a tuple-axis psum
                lambda t: jax.lax.psum(t, axis)
                / jnp.asarray(world, t.dtype),
                x,
            )
        return spmd_ops.allreduce(x, axis=axis)

    def _overlap_zero_reduce(buf):
        """Full (pre-ZeRO) reduction of one bucket, run as the SAME
        reduce-scatter (+ allgather) primitives the wrapper's own
        exchange uses — ZeRO's reduce-scatter IS the bucket collective,
        just launched at the bucket boundary.  Using psum here instead
        was measured to drift 1 ulp against the unoverlapped step (XLA
        lowers all-reduce and reduce-scatter with different reduction
        association); the scatter/gather pair keeps every element's
        reduction order identical, so GRADIENTS are bit-equal
        (tests/test_overlap.py pins it; see the overlap docstring above
        for the fma-inner update caveat)."""
        pad = (-buf.size) % world
        padded = (
            jnp.concatenate([buf, jnp.zeros((pad,), buf.dtype)])
            if pad else buf
        )
        if hierarchical:
            shard, _ = spmd_ops._two_level_reduce_scatter_flat(
                padded, ICI_AXIS, DCN_AXIS, dcn_compression, None
            )
        else:
            shard = spmd_ops.reducescatter(padded, axis=axis)
        if op == ReduceOp.AVERAGE:
            shard = shard / jnp.asarray(world, shard.dtype)
        if hierarchical:
            # gather the reduced GRADIENTS at full precision: this
            # gather only exists because of the overlap composition (the
            # unoverlapped path feeds the reduce-scatter output straight
            # to the update), so compressing it would quantize the
            # gradients the optimizer sees — a divergence the
            # unoverlapped step never has.  Wire compression stays where
            # it always was: the reduce-scatter's DCN hop above and the
            # update-delta allgather inside ZeroSpmdOptimizer.
            red = spmd_ops._two_level_all_gather_flat(
                shard, ICI_AXIS, DCN_AXIS, None
            )
        else:
            red = spmd_ops.allgather(shard, axis=axis)
        return red[: buf.size] if pad else red

    def _zero_diag(loss, updates):
        """Guard diagnostics for the ZeRO step, from REPLICATED values
        only: digest + finite sentinel over the POST-exchange update
        deltas (identical on every chip after the allgather — the
        cross-rank agreement object) and the mean loss.  Per-chip
        intermediates (local grads, reduce-scattered shards) differ
        across devices by design: feeding them to a ``P()``-spec'd
        output would surface ONE device's flag and silently drop the
        rest (check_vma=False) — and a non-finite shard reaches these
        deltas through the inner update the same step anyway."""
        from .guard import device_allfinite, device_digest

        return {"finite": device_allfinite((loss, updates)),
                "digest": device_digest(updates)}

    def _step(state: TrainState, images, labels):
        if overlap:
            from .ops.overlap import overlapped_value_and_grad

            if state.batch_stats is not None:
                raise ValueError(
                    "overlap=True does not support batch_stats models"
                )
            loss, grads, _ = overlapped_value_and_grad(
                segmenter(model, images, labels, loss_fn),
                state.params, images,
                bucket_reduce=_overlap_zero_reduce,
                bucket_bytes=bucket_bytes,
            )
            new_stats = None
            loss = _mean(loss)
            updates, new_opt_state = zopt.update(
                grads, state.opt_state, state.params
            )
            new_params = optax.apply_updates(state.params, updates)
            new_state = TrainState(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt_state,
                batch_stats=new_stats,
            )
            if guard:
                return new_state, loss, _zero_diag(loss, updates)
            return new_state, loss

        def compute_loss(params):
            variables = {"params": params}
            if state.batch_stats is not None:
                variables["batch_stats"] = state.batch_stats
                out, updates = model.apply(
                    variables, images, mutable=["batch_stats"]
                )
                return loss_fn(out, labels), updates["batch_stats"]
            return loss_fn(model.apply(variables, images), labels), None

        (loss, new_stats), grads = jax.value_and_grad(
            compute_loss, has_aux=True
        )(state.params)

        # no separate gradient allreduce: the ZeRO update IS the
        # reduction (reduce-scatter + allgather = the split allreduce)
        loss = _mean(loss)
        if new_stats is not None:
            new_stats = _mean(new_stats)
        updates, new_opt_state = zopt.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            step=state.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            batch_stats=new_stats,
        )
        if guard:
            return new_state, loss, _zero_diag(loss, updates)
        return new_state, loss

    data_spec = P(axis)
    sharded = jax.shard_map(
        _step,
        mesh=mesh,
        in_specs=(state_specs, data_spec, data_spec),
        out_specs=(state_specs, P(), P()) if guard else (state_specs, P()),
        check_vma=False,
    )
    return state, jax.jit(sharded, donate_argnums=(0,)), ospecs


def fit_epoch(step: Callable, state: TrainState, loader,
              epoch: Optional[int] = None, *,
              checkpoint_dir: Optional[str] = None,
              checkpoint_every: int = 0,
              checkpoint_keep: Optional[int] = None,
              guard=None):
    """Drive one epoch of a compiled train step from a
    :class:`horovod_tpu.data.DataLoader` (or any iterable of
    ``(inputs, labels)`` batches).

    The drop-in loop for the ``horovod_tpu.data`` pipeline: the loader
    stages batch N+1 on device while the step computes batch N, so this
    is already overlapped — do NOT add ``block_until_ready`` per step
    (the chained-dependency dispatch queue is the pipeline).

        loader = hvd.data.DataLoader(source, batch_size=128)
        for epoch in range(epochs):
            state, loss = training.fit_epoch(step, state, loader, epoch)

    With ``checkpoint_dir`` + ``checkpoint_every`` set, rank 0 writes a
    crash-atomic checkpoint every N batches (``checkpoint.save_checkpoint``
    keyed by ``state.step``) — pair with ``checkpoint.restore_checkpoint``
    before training so a restarted job resumes instead of starting over
    (docs/FAULT_TOLERANCE.md).  The ``int(state.step)`` read is the only
    device sync this adds, and only on checkpoint batches.

    ``guard`` takes an armed :class:`horovod_tpu.guard.IntegrityGuard`
    when ``step`` was built with ``guard=True``: each step's on-device
    diagnostics feed the guard without a host sync, and on cadence
    steps the guard performs its ONE bounded sync (window + loss +
    param fingerprint), the cross-rank agreement check, and the
    response — :class:`~horovod_tpu.guard.IntegrityError` on detected
    corruption in non-elastic runs (reload a verified checkpoint), the
    quarantine/rollback restart path under the elastic driver
    (docs/FAULT_TOLERANCE.md, silent corruption).  ``checkpoint_keep``
    sizes the ring (default 3; with a guard armed it defaults to
    ``2 * guard.cadence`` — rollback discards every checkpoint newer
    than the last verified step, so a ring shallower than the cadence
    could be emptied entirely, degrading resume to step 0).

    Returns ``(state, last_loss)`` with the loss fetched to host — the
    end-of-epoch sync point.  ``last_loss`` is None for an empty shard.
    """
    from . import chaos as _chaos
    from . import checkpoint as _checkpoint
    from . import trace
    from .utils.logging import set_log_context

    if epoch is not None and hasattr(loader, "set_epoch"):
        loader.set_epoch(epoch)
    if checkpoint_keep is None:
        checkpoint_keep = (max(3, 2 * guard.cadence)
                           if guard is not None
                           and getattr(guard, "enabled", False) else 3)
    loss = None
    batches = 0
    guard_base = None
    # the trace anchors steps GLOBALLY (cross-rank merge aligns on the
    # step number): one int(state.step) host sync per fit_epoch call,
    # and only while recording — the untraced loop stays sync-free.
    # The structured-log step field is stamped from the same base, so
    # it is only stamped while recording too (an epoch-relative number
    # would MISLABEL records against ckpt-N/guard step numbers).
    tracing = trace.enabled()
    trace_base = int(state.step) if tracing else 0
    for inputs, labels in loader:
        if _chaos.active:
            _chaos.raise_point("training.step")
        if tracing:
            step_no = trace_base + batches + 1
            set_log_context(step=step_no)
            with trace.span("train.step", step=step_no,
                            epoch=-1 if epoch is None else epoch):
                out = step(state, inputs, labels)
        else:
            out = step(state, inputs, labels)
        if len(out) == 3:
            state, loss, diag = out
            if guard is not None:
                if guard_base is None:
                    # the guard numbers steps GLOBALLY (state.step):
                    # checkpoints are keyed by it, so rollback's
                    # discard_newer_than and the exchange keys must
                    # share the numbering across epochs and resumes.
                    # One host sync per fit_epoch call, not per step.
                    guard_base = int(state.step) - batches - 1
                guard.on_train_step(guard_base + batches + 1, loss,
                                    diag, params=state.params)
        else:
            state, loss = out
        batches += 1
        if (checkpoint_dir and checkpoint_every
                and batches % checkpoint_every == 0):
            _checkpoint.save_checkpoint(
                checkpoint_dir, state, int(state.step),
                keep=checkpoint_keep,
            )
    if loss is not None:
        loss = float(loss)  # the only sync some remote backends honor
    return state, loss


def replicate_state(state: TrainState, mesh: Optional[Mesh] = None) -> TrainState:
    """Place the state replicated over the mesh (the moral equivalent of
    the reference's broadcast_parameters at train start: every chip holds
    identical weights)."""
    if mesh is None:
        mesh = basics._require_init().process_set_registry.get(0).mesh
    sharding = NamedSharding(mesh, P())
    return jax.device_put(state, sharding)
