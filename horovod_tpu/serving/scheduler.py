"""Iteration-level (continuous-batching) request scheduler.

Orca (OSDI '22) made the case: autoregressive serving must schedule at
*iteration* granularity, not request granularity.  A static batch holds
every slot hostage to its slowest member — finished sequences keep
padding the batch, waiting requests queue behind the whole batch's
maximum length.  Continuous batching re-decides the batch every step:
finished sequences leave immediately, waiting requests join as soon as
a slot and KV blocks are free, so the decode batch stays full and
throughput tracks the token budget instead of the worst tail.

The policy here (documented in docs/SERVING.md):

* **Prefill-prioritized**: when admissible requests are waiting, the
  next step is a prefill — time-to-first-token is the latency SLO,
  and a full batch is the throughput SLO; both want admission early.
* **Admission gates**: the prompt-token sum of one prefill batch is
  capped by ``token_budget`` (bounds the prefill step's cost so decode
  latency can't spike arbitrarily), the decode batch by the largest
  padding tier, and block allocation must leave ``watermark`` free
  blocks (headroom so running sequences can keep growing without
  immediate eviction thrash).
* **LIFO eviction (recompute-style)**: when a growing sequence needs a
  block and the pool is empty, the most recently admitted sequence is
  preempted — its blocks are freed and it re-queues *with the tokens it
  already generated* (vLLM's recompute preemption), so its re-prefill
  reproduces the exact cache state and generation continues token-for-
  token identically (greedy decode is deterministic; the oracle test
  pins this across evict boundaries).

Everything here is host-side bookkeeping over the
:class:`~horovod_tpu.serving.kv_cache.BlockAllocator`; the device work
happens in :mod:`horovod_tpu.serving.engine`.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Deque, List, Optional, Tuple

import numpy as np

from ..metrics import instruments as _instr
from .kv_cache import BlockAllocator, blocks_for


@dataclasses.dataclass
class Request:
    """One generation request as submitted by the client."""

    id: int
    prompt: np.ndarray  # int32 token ids, 1-D
    max_new_tokens: int
    eos_id: Optional[int] = None
    arrival: float = 0.0  # open-loop load injection timestamp (bench)


@dataclasses.dataclass
class Sequence:
    """A request's live serving state.

    ``context`` is what the next prefill must write: the prompt, plus —
    after an eviction — the tokens already generated (recompute
    preemption re-prefills prompt+generated and resumes decoding).
    """

    req: Request
    context: np.ndarray
    generated: List[int] = dataclasses.field(default_factory=list)
    blocks: List[int] = dataclasses.field(default_factory=list)
    staged: object = None  # device-resident padded prompt row (staging queue)
    first_token_at: Optional[float] = None
    last_token_at: Optional[float] = None

    @property
    def length(self) -> int:
        """Tokens currently in the KV cache once prefill has run."""
        return len(self.context) + len(self.generated)

    @property
    def done(self) -> bool:
        n = len(self.generated) + (len(self.context) - len(self.req.prompt))
        if n >= self.req.max_new_tokens:
            return True
        eos = self.req.eos_id
        return eos is not None and len(self.generated) > 0 \
            and self.generated[-1] == eos


class ContinuousBatchingScheduler:
    """Admit/evict sequences against a token budget and a block pool."""

    def __init__(self, allocator: BlockAllocator, *, token_budget: int,
                 watermark: int, max_decode_batch: int,
                 max_seq_len: int):
        if token_budget < 1:
            raise ValueError(f"token_budget must be >= 1, got {token_budget}")
        if watermark < 0:
            raise ValueError(f"watermark must be >= 0, got {watermark}")
        need_one = blocks_for(max_seq_len, allocator.block_size)
        if need_one > allocator.capacity:
            raise ValueError(
                f"pool of {allocator.capacity} blocks cannot hold one "
                f"max_seq_len={max_seq_len} sequence ({need_one} blocks) — "
                f"a lone sequence could deadlock growth")
        self.allocator = allocator
        self.token_budget = int(token_budget)
        self.watermark = int(watermark)
        self.max_decode_batch = int(max_decode_batch)
        self.max_seq_len = int(max_seq_len)
        self.pending: Deque[Sequence] = collections.deque()
        self.running: List[Sequence] = []
        self.evictions = 0
        #: extra waiting requests not yet in ``pending`` (the engine
        #: points this at its device-staging queue so the queue-depth
        #: gauge counts staged + pending, as documented)
        self.staged_depth = lambda: 0

    # -- bookkeeping ---------------------------------------------------------

    def submit(self, seq: Sequence) -> None:
        self.pending.append(seq)
        self._book()

    def _book(self) -> None:
        _instr.SERVE_QUEUE_DEPTH.set(len(self.pending) + self.staged_depth())
        _instr.SERVE_KV_OCCUPANCY.set(self.allocator.occupancy())

    def finish(self, seq: Sequence) -> None:
        """Release a completed sequence's blocks and batch slot."""
        self.running.remove(seq)
        self.allocator.free(seq.blocks)
        seq.blocks = []
        self._book()

    def _evict_one(self) -> bool:
        """Preempt the most recently admitted sequence (LIFO recompute)."""
        if len(self.running) <= 1:
            return False
        victim = self.running.pop()
        self.allocator.free(victim.blocks)
        victim.blocks = []
        # recompute preemption: re-prefill prompt + generated so far
        victim.context = np.concatenate([
            victim.context, np.asarray(victim.generated, np.int32)])
        victim.generated = []
        victim.staged = None  # host re-pads/re-stages at re-admission
        self.pending.appendleft(victim)
        self.evictions += 1
        _instr.SERVE_EVICTIONS.inc()
        self._book()
        return True

    # -- the per-step decision ----------------------------------------------

    def grow_running(self) -> None:
        """Before a decode step: every running sequence is about to gain
        one token; allocate tail blocks, evicting LIFO when the pool is
        dry.  A sequence evicted here simply re-queues — the decode step
        then runs over whoever is left."""
        for seq in list(self.running):
            if seq not in self.running:
                continue  # evicted by an earlier iteration
            while True:
                need = blocks_for(seq.length + 1, self.allocator.block_size)
                if need <= len(seq.blocks):
                    break
                got = self.allocator.alloc(need - len(seq.blocks))
                if got is not None:
                    seq.blocks.extend(got)
                    break
                if not self._evict_one() or seq not in self.running:
                    break
        self._book()

    def admit(self) -> List[Sequence]:
        """Admit pending sequences for one prefill batch: token budget,
        decode-batch slots, and block watermark all permitting.  The
        admitted sequences get their context's blocks allocated here and
        join ``running``; returns them (empty = no prefill this step)."""
        batch: List[Sequence] = []
        tokens = 0
        while self.pending:
            seq = self.pending[0]
            ctx = len(seq.context)  # <= max_seq_len: engine validates at
            # submit and caps generation at max_seq_len
            if batch and tokens + ctx > self.token_budget:
                break
            if len(self.running) + len(batch) + 1 > self.max_decode_batch:
                break
            need = blocks_for(ctx + 1, self.allocator.block_size)
            # the watermark bypass exists ONLY for the progress
            # guarantee (an idle engine must admit SOMETHING); with
            # sequences already running, draining below the watermark
            # just sets up the admit→grow→evict thrash it prevents
            if self.allocator.free_blocks - need < self.watermark and (
                    batch or self.running):
                break
            got = self.allocator.alloc(need)
            if got is None:
                break
            seq.blocks = got
            batch.append(self.pending.popleft())
            tokens += ctx
        self.running.extend(batch)
        self._book()
        return batch
