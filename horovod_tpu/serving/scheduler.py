"""Iteration-level (continuous-batching) request scheduler.

Orca (OSDI '22) made the case: autoregressive serving must schedule at
*iteration* granularity, not request granularity.  A static batch holds
every slot hostage to its slowest member — finished sequences keep
padding the batch, waiting requests queue behind the whole batch's
maximum length.  Continuous batching re-decides the batch every step:
finished sequences leave immediately, waiting requests join as soon as
a slot and KV blocks are free, so the decode batch stays full and
throughput tracks the token budget instead of the worst tail.

The policy here (documented in docs/SERVING.md):

* **Prefill-prioritized**: when admissible requests are waiting, the
  next step is a prefill — time-to-first-token is the latency SLO,
  and a full batch is the throughput SLO; both want admission early.
  With chunked prefill the engine packs prefill chunks INTO the decode
  step (one mixed program), so prioritizing prefill no longer stalls
  running decodes.
* **Prefix cache on admit**: the longest cached block-aligned prefix
  of each prompt is mapped straight into the new sequence's block
  table with refcount bumps (:meth:`BlockAllocator.match_prefix`) —
  zero prefill compute and zero pool writes for the shared span; only
  the uncached tail is booked against the token budget and prefilled.
  The match is capped one block short of the prompt so the prefill
  step always has a token to compute (it must emit the first token),
  and the partially-filled last block is always private — CoW by
  construction.  LIFO recompute eviction re-admits through this same
  match, so a recomputed sequence reuses whatever of its blocks
  survived in the cache instead of re-prefilling from token 0.
* **Admission gates**: the *uncached* prompt-token sum of one prefill
  batch is capped by ``token_budget`` (bounds outstanding prefill work
  so decode latency can't spike arbitrarily), the decode batch by the
  largest padding tier, and block allocation must leave ``watermark``
  free blocks (headroom so running sequences can keep growing without
  immediate eviction thrash).
* **LIFO eviction (recompute-style)**: when a growing sequence needs a
  block and the pool is empty, the most recently admitted sequence is
  preempted — its blocks are freed and it re-queues *with the tokens it
  already generated* (vLLM's recompute preemption), so its re-prefill
  reproduces the exact cache state and generation continues token-for-
  token identically (greedy decode is deterministic; the oracle test
  pins this across evict boundaries).

Everything here is host-side bookkeeping over the
:class:`~horovod_tpu.serving.kv_cache.BlockAllocator`; the device work
happens in :mod:`horovod_tpu.serving.engine`.

Tensor sharding never reaches this module BY DESIGN (docs/SERVING.md
sharding section): every decision here — admission, prefix matching,
CoW publication, eviction — is a pure function of token ids and pool
geometry (block count/size), and kv-head sharding changes neither, so
one unsharded scheduler loop drives any shard factor and the block
tables it emits replicate bit-for-bit across chips.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Deque, List, Optional, Tuple

import numpy as np

from .. import trace
from ..metrics import instruments as _instr
from .kv_cache import PREFIX_HASH_ROOT, BlockAllocator, blocks_for


@dataclasses.dataclass
class Request:
    """One generation request as submitted by the client."""

    id: int
    prompt: np.ndarray  # int32 token ids, 1-D
    max_new_tokens: int
    eos_id: Optional[int] = None
    arrival: float = 0.0  # open-loop load injection timestamp (bench)
    #: latency budget in seconds from ``arrival`` (None/0 = none):
    #: once spent, the request is shed pre-admission or cancelled
    #: in flight — tokens the client stopped waiting for are never
    #: computed (``HVD_TPU_SERVE_DEADLINE`` sets the engine default)
    deadline_s: Optional[float] = None
    #: propagated trace context (fleet router -> replica -> engine ->
    #: scheduler): rides every span this request touches so one id
    #: follows it across components (docs/TRACING.md)
    trace_id: Optional[str] = None
    #: per-request speculative lookahead override: None = the engine's
    #: configured ``spec_k``, 0 = speculation off for this request, k>0
    #: = draft up to k tokens per decode step (docs/SERVING.md)
    spec_k: Optional[int] = None


@dataclasses.dataclass
class Sequence:
    """A request's live serving state.

    ``context`` is what the next prefill must write: the prompt, plus —
    after an eviction — the tokens already generated (recompute
    preemption re-prefills prompt+generated and resumes decoding).
    """

    req: Request
    context: np.ndarray
    generated: List[int] = dataclasses.field(default_factory=list)
    blocks: List[int] = dataclasses.field(default_factory=list)
    staged: object = None  # device-resident padded prompt row (staging queue)
    first_token_at: Optional[float] = None
    last_token_at: Optional[float] = None
    #: context tokens whose K/V are already in the cache (prefix-cache
    #: hits at admit + chunks computed since); == len(context) once
    #: prefill is complete and the sequence is decoding
    prefilled: int = 0
    #: of ``prefilled``, how many came from prefix-cache hits at admit
    cached_len: int = 0
    #: chain hashes of this stream's full blocks (hashes depend only on
    #: token ids, so the list survives eviction/readmission unchanged)
    block_hashes: List[int] = dataclasses.field(default_factory=list)
    #: how many of ``blocks`` are published in the prefix index
    published: int = 0
    #: pending speculative draft for the NEXT decode step (proposed by
    #: the engine's drafter; empty = plain one-token decode).  Never
    #: part of ``generated`` — draft tokens only join the stream after
    #: greedy verification accepts them.
    draft: List[int] = dataclasses.field(default_factory=list)
    #: lifetime speculative counters (per-request accept-rate
    #: histogram at finish; bench columns)
    spec_drafted: int = 0
    spec_accepted: int = 0

    @property
    def length(self) -> int:
        """Tokens currently in the KV cache once prefill has run."""
        return len(self.context) + len(self.generated)

    @property
    def in_decode(self) -> bool:
        """Prefill complete — the sequence decodes one token per step."""
        return self.prefilled >= len(self.context)

    @property
    def tokens_in_cache(self) -> int:
        """Tokens whose K/V are physically written (full blocks up to
        here are immutable and publishable): during prefill that is
        ``prefilled``; during decode it is ``length - 1`` — the
        *newest* generated token's K/V lands only on the NEXT step.
        This lags-one invariant survives speculative decode unchanged,
        for any number of tokens accepted per step: a verify step
        feeds [last token, k drafts] and writes their K/V at positions
        ``length-1 .. length-1+k``, but the LAST emitted token is
        always the verifier's own bonus/correction token, whose K/V
        the step never fed — it is written by the next step, exactly
        like plain decode's newest token (positions beyond the accept
        point hold rejected-draft garbage, masked by ``lens`` and
        trimmed by rollback before they could ever publish)."""
        if not self.in_decode:
            return self.prefilled
        return len(self.context) + max(len(self.generated) - 1, 0)

    @property
    def done(self) -> bool:
        n = len(self.generated) + (len(self.context) - len(self.req.prompt))
        if n >= self.req.max_new_tokens:
            return True
        eos = self.req.eos_id
        return eos is not None and len(self.generated) > 0 \
            and self.generated[-1] == eos

    def expired(self, now: float) -> bool:
        """Deadline budget spent (measured from ``arrival``)."""
        d = self.req.deadline_s
        return bool(d) and d > 0 and (now - self.req.arrival) > d


class ContinuousBatchingScheduler:
    """Admit/evict sequences against a token budget and a block pool."""

    def __init__(self, allocator: BlockAllocator, *, token_budget: int,
                 watermark: int, max_decode_batch: int,
                 max_seq_len: int):
        if token_budget < 1:
            raise ValueError(f"token_budget must be >= 1, got {token_budget}")
        if watermark < 0:
            raise ValueError(f"watermark must be >= 0, got {watermark}")
        need_one = blocks_for(max_seq_len, allocator.block_size)
        if need_one > allocator.capacity:
            raise ValueError(
                f"pool of {allocator.capacity} blocks cannot hold one "
                f"max_seq_len={max_seq_len} sequence ({need_one} blocks) — "
                f"a lone sequence could deadlock growth")
        self.allocator = allocator
        self.token_budget = int(token_budget)
        self.watermark = int(watermark)
        self.max_decode_batch = int(max_decode_batch)
        self.max_seq_len = int(max_seq_len)
        self.pending: Deque[Sequence] = collections.deque()
        self.running: List[Sequence] = []
        #: deadline-shed/cancelled sequences awaiting caller
        #: finalization (the engine publishes their partial results and
        #: drains this list every step)
        self.shed: List[Sequence] = []
        self.evictions = 0
        #: prefix-cache admit statistics (bench hit-rate columns)
        self.prefix_hit_blocks = 0
        self.prefix_lookup_blocks = 0
        #: extra waiting requests not yet in ``pending`` (the engine
        #: points this at its device-staging queue so the queue-depth
        #: gauge counts staged + pending, as documented; a standalone
        #: scheduler has no staging queue, hence 0)
        self.staged_depth = lambda: 0

    # -- bookkeeping ---------------------------------------------------------

    def queue_depth(self) -> int:
        """Requests waiting for admission: scheduler-pending plus
        device-staged-but-undrained.  THE number behind the
        ``hvd_tpu_serve_queue_depth`` gauge and the fleet router's
        least-queue-depth fallback — both must see the same sum, so
        both read it here (pinned by tests/test_serving.py)."""
        return len(self.pending) + self.staged_depth()

    def submit(self, seq: Sequence) -> None:
        self.pending.append(seq)
        self._book()

    def _book(self) -> None:
        _instr.SERVE_QUEUE_DEPTH.set(self.queue_depth())
        _instr.SERVE_KV_OCCUPANCY.set(self.allocator.occupancy())
        _instr.SERVE_KV_CACHED.set(
            self.allocator.cached_blocks / self.allocator.capacity)

    def resort_pending_by_arrival(self) -> None:
        """Re-establish arrival-order fairness in the pending queue —
        the fleet router calls this after re-dispatching an ejected
        replica's requests: the survivors' queues just absorbed
        requests that may have arrived EARLIER than ones already
        waiting, and appending them at the tail would charge the
        crash's victims the whole queue again.  Stable sort: equal
        arrivals (and the 0.0 default of bare submits) keep their
        submission order, so a no-crash workload is a no-op."""
        if len(self.pending) > 1:
            self.pending = collections.deque(
                sorted(self.pending, key=lambda s: s.req.arrival))

    def finish(self, seq: Sequence) -> None:
        """Release a completed sequence's blocks and batch slot (one
        reference each — shared prefix blocks stay alive for their
        other holders, and cached blocks park on the allocator's LRU,
        still matchable)."""
        self.running.remove(seq)
        self.allocator.free(seq.blocks)
        seq.blocks = []
        self._book()

    def _evict_one(self) -> bool:
        """Preempt the most recently admitted sequence (LIFO recompute)."""
        if len(self.running) <= 1:
            return False
        victim = self.running.pop()
        self.allocator.free(victim.blocks)
        victim.blocks = []
        # recompute preemption: re-prefill prompt + generated so far.
        # Re-admission goes through the same prefix match as any other
        # request, so whatever full blocks survived in the cache (this
        # victim's own, freshly parked, included) are remapped instead
        # of recomputed — and only the uncached tail is re-booked
        # against the token budget.
        victim.context = np.concatenate([
            victim.context, np.asarray(victim.generated, np.int32)])
        victim.generated = []
        victim.prefilled = 0
        victim.cached_len = 0
        victim.published = 0
        victim.staged = None  # host re-pads/re-stages at re-admission
        victim.draft = []  # re-drafted (identically) after re-prefill
        self.pending.appendleft(victim)
        self.evictions += 1
        _instr.SERVE_EVICTIONS.inc()
        self._book()
        return True

    # -- prefix-cache publication --------------------------------------------

    def publish_full_blocks(self, seq: Sequence) -> None:
        """Register ``seq``'s newly-FULL blocks in the prefix index
        (the engine calls this after every step).  Only blocks all
        ``block_size`` positions of which are written are published —
        the partial tail stays private (CoW) — and generated tokens
        publish too, so an evicted sequence's re-admission can match
        its own surviving blocks."""
        if not self.allocator.prefix_cache:
            return
        bs = self.allocator.block_size
        n_full = min(seq.tokens_in_cache // bs, len(seq.blocks))
        if seq.published >= n_full:
            return
        stream = seq.context if not seq.generated else np.concatenate(
            [seq.context, np.asarray(seq.generated, np.int32)])
        while seq.published < n_full:
            i = seq.published
            parent = seq.block_hashes[i - 1] if i else PREFIX_HASH_ROOT
            h = self.allocator.register(
                seq.blocks[i], parent, stream[i * bs:(i + 1) * bs])
            if len(seq.block_hashes) > i:
                seq.block_hashes[i] = h
            else:
                seq.block_hashes.append(h)
            seq.published += 1

    # -- deadlines ----------------------------------------------------------

    def _shed(self, seq: Sequence) -> None:
        self.shed.append(seq)
        _instr.SERVE_DEADLINE_EXCEEDED.inc()

    def cancel_expired(self, now: float) -> List[Sequence]:
        """Shed pending requests already past their deadline and cancel
        expired in-flight sequences — blocks release through the normal
        refcount path (shared prefix blocks survive for their other
        holders), the batch slot frees immediately.  Returns the newly
        shed sequences (also queued on :attr:`shed` for the engine's
        finalization pass)."""
        out: List[Sequence] = []
        for seq in [s for s in self.pending if s.expired(now)]:
            self.pending.remove(seq)
            self._shed(seq)
            out.append(seq)
        for seq in [s for s in self.running if s.expired(now)]:
            self.finish(seq)  # the one teardown path: slot + blocks
            self._shed(seq)
            out.append(seq)
        if out:
            self._book()
        return out

    # -- the per-step decision ----------------------------------------------

    def grow_running(self) -> None:
        """Before a decode step: every running sequence is about to gain
        at least one token — plus up to ``len(seq.draft)`` more when a
        speculative draft is pending (the verify step writes draft K/V
        at positions ``length-1 .. length-1+k`` and may emit k+1
        tokens).  Allocate tail blocks, evicting LIFO when the pool is
        dry — but speculation is strictly best-effort: a sequence whose
        *draft* is what needs the extra blocks drops the draft (that
        step decodes one token, plain) before anyone is evicted, so
        speculative lookahead can never cause an eviction that plain
        decode wouldn't have."""
        for seq in list(self.running):
            if seq not in self.running:
                continue  # evicted by an earlier iteration
            while True:
                need = blocks_for(seq.length + 1 + len(seq.draft),
                                  self.allocator.block_size)
                if need <= len(seq.blocks):
                    break
                got = self.allocator.alloc(need - len(seq.blocks))
                if got is not None:
                    seq.blocks.extend(got)
                    break
                if seq.draft:
                    seq.draft = []  # shed the speculation, not a peer
                    continue
                if not self._evict_one() or seq not in self.running:
                    break
        self._book()

    def admit(self, now: Optional[float] = None) -> List[Sequence]:
        """Admit pending sequences: token budget, decode-batch slots,
        and block watermark all permitting.  Each admitted sequence
        first matches the longest cached block-aligned prefix of its
        context — those blocks map into its table with refcount bumps
        (zero prefill compute for the span) — then allocates only the
        uncached tail's blocks, and only the *uncached* tail tokens are
        booked against the token budget (an evicted-then-readmitted
        sequence whose prefix blocks survived is NOT re-booked at full
        length).  Admitted sequences join ``running`` with
        ``prefilled = cached_len``; the engine prefills the tail in
        chunks.  With ``now``, requests already past their deadline are
        SHED instead of admitted (their prefill would compute tokens
        nobody is waiting for).  Returns the admitted batch (empty =
        nothing admitted)."""
        batch: List[Sequence] = []
        tokens = 0
        bs = self.allocator.block_size
        while self.pending:
            seq = self.pending[0]
            if now is not None and seq.expired(now):
                self.pending.popleft()
                self._shed(seq)
                continue
            ctx = len(seq.context)  # <= max_seq_len: engine validates at
            # submit and caps generation at max_seq_len
            if len(self.running) + len(batch) + 1 > self.max_decode_batch:
                break
            # longest cached prefix, capped one block short of the
            # context: the prefill step must have >= 1 token to compute
            # (it emits the first token), and the cap also keeps the
            # last, partially-filled block private — CoW by construction
            matched, hashes = self.allocator.match_prefix(
                seq.context, max_blocks=(ctx - 1) // bs)
            cached = len(matched) * bs
            tail = ctx - cached
            if batch and tokens + tail > self.token_budget:
                self.allocator.free(matched)  # undo the match's refs
                break
            need = blocks_for(ctx + 1, bs) - len(matched)
            # the watermark bypass exists ONLY for the progress
            # guarantee (an idle engine must admit SOMETHING); with
            # sequences already running, draining below the watermark
            # just sets up the admit→grow→evict thrash it prevents
            if self.allocator.free_blocks - need < self.watermark and (
                    batch or self.running):
                self.allocator.free(matched)
                break
            got = self.allocator.alloc(need)
            if got is None:
                self.allocator.free(matched)
                break
            # CoW invariant: everything the prefill will write (positions
            # >= cached) lands in freshly-allocated private blocks
            assert all(self.allocator.ref(b) == 1 for b in got)
            if self.allocator.prefix_cache:
                # booked only on successful admission (a gated-out
                # sequence re-matches next step — counting its lookups
                # every retry would skew the hit rate)
                lookup = (ctx - 1) // bs
                self.prefix_lookup_blocks += lookup
                self.prefix_hit_blocks += len(matched)
                _instr.SERVE_PREFIX_HITS.inc(len(matched))
                _instr.SERVE_PREFIX_MISSES.inc(lookup - len(matched))
            seq.blocks = matched + got
            seq.cached_len = cached
            seq.prefilled = cached
            seq.published = len(matched)
            seq.block_hashes[:len(hashes)] = hashes
            if seq.req.arrival > 0 and trace.enabled():
                # the queue phase of the request's TTFT decomposition:
                # arrival -> this admission.  The arrival rides the
                # engine clock (perf_counter in production), so the
                # duration is computed on that clock and anchored to
                # the trace clock's "now"; a bare-Sequence caller with
                # no arrival stamp records nothing
                t1 = trace.now()
                waited = max(0.0, (now if now is not None else t1)
                             - seq.req.arrival)
                trace.add_span("serve.queued", t1 - waited, t1,
                               rid=seq.req.id, cached_blocks=len(matched),
                               trace=seq.req.trace_id)
            batch.append(self.pending.popleft())
            tokens += tail
        self.running.extend(batch)
        self._book()
        return batch
