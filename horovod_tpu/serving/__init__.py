"""Continuous-batching inference serving on the paged KV cache.

The millions-of-users workload (ROADMAP item 2): iteration-level
request scheduling (Orca, OSDI '22) over block-granular KV paging
(vLLM's PagedAttention, SOSP '23), hash-indexed prefix caching over
the same blocks (shared system prompts prefill once, copy-on-write by
construction), Sarathi-style chunked prefill (prompt bursts stream
in beside the decode batch), and tensor-sharded multi-chip serving
(one model across an ICI mesh: kv heads + the paged pool
head-sharded, Megatron FFN, per-chip decode reads cut by the shard
factor — ``ServingEngine(mesh=...)`` / ``HVD_TPU_SERVE_SHARDS``),
with decode/chunk attention driven through the repo's own flash
kernels' ``kv_offset``/block-skip machinery, and speculative decoding
on that same chunk machinery (multi-token decode steps: a prompt-lookup
drafter proposes k tokens, one chunk row verifies them exactly —
``HVD_TPU_SERVE_SPEC``) — see docs/SERVING.md for the policy, tuning
and exactness contract.

Not imported by ``import horovod_tpu`` (training jobs shouldn't pay the
model-stack import); use ``from horovod_tpu import serving``.
"""

from .engine import Request, ServeConfig, ServingEngine
from .kv_cache import (
    BlockAllocator,
    PagedKVState,
    blocks_for,
    modeled_decode_read_bytes,
    pool_bytes,
)
from .scheduler import ContinuousBatchingScheduler, Sequence
from .speculative import (
    Drafter,
    ModelDrafter,
    PromptLookupDrafter,
    accept_greedy,
    make_drafter,
)

__all__ = [
    "BlockAllocator",
    "ContinuousBatchingScheduler",
    "Drafter",
    "ModelDrafter",
    "PagedKVState",
    "PromptLookupDrafter",
    "Request",
    "Sequence",
    "ServeConfig",
    "ServingEngine",
    "accept_greedy",
    "blocks_for",
    "make_drafter",
    "modeled_decode_read_bytes",
    "pool_bytes",
]
