"""Paged KV cache: fixed-size KV blocks in preallocated device pools.

The serving memory problem (vLLM's PagedAttention, SOSP '23): contiguous
per-sequence KV buffers sized for ``max_seq_len`` waste most of HBM on
reservations, and the waste is what caps the batch — the batch is what
throughput lives on.  Paging fixes it the way virtual memory did: the
pools hold ``num_blocks`` fixed-size blocks of K/V per layer, a
per-sequence *block table* maps logical token positions to physical
blocks, and a sequence owns exactly ``ceil(len / block_size)`` blocks at
any moment.

Two cooperating pieces:

* :class:`BlockAllocator` — the host-side free list, now *refcounted*
  with a **prefix cache**: a content-hash index over full, immutable
  blocks (hash chained over token ids per block, vLLM's scheme).  A
  block freed to refcount 0 while its content is cached parks on an
  LRU instead of the free list; a later request whose prompt shares
  the block-aligned prefix re-maps it with a refcount bump — zero
  prefill compute, zero pool writes for the shared span.  The last,
  partially-filled block of any sequence is never cached and never
  shared, so it stays writable by its one owner: copy-on-write by
  construction (writes only ever land at positions ≥ the sequence's
  cached length, and full blocks are immutable).  Block 0 is reserved
  as the *trash block*: every padded/unused block-table slot points at
  it, so scatter writes from padded positions land somewhere harmless
  and gathers from padded slots read garbage that the decode kernel's
  per-sequence causal mask never attends
  (``ops.flash_attention.flash_decode_attention``).
* :class:`PagedKVState` — the device-side pytree carried through the
  jitted chunk/decode step: the pools, the step batch's block tables
  and lengths.  The transformer's attention layers call its
  ``write_chunk`` / ``write_decode`` / ``gather`` from inside the
  traced step; the updated pools come back out through the step's
  return value (functional update, ``.at[].set``).

The decode read path is where the paged + GQA + window savings stack:
the decode KERNEL reads only the blocks holding a sequence's live
positions (block-table gather + ``_kb_range`` skip), once per KV head
(GQA BlockSpecs), and only the trailing window's worth when ``window``
is set — where the gather itself is also truncated to the last pages
(without a window the gather copy stays ``max_blocks`` wide; static
shapes) — see :func:`modeled_decode_read_bytes`, which models both
terms, and the columns ``tools/serve_bench.py`` emits.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def blocks_for(length: int, block_size: int) -> int:
    """Blocks a sequence of ``length`` tokens occupies (ceil division)."""
    return -(-int(length) // int(block_size))


#: Root of every sequence's hash chain (the "parent" of block 0).
PREFIX_HASH_ROOT = 0


def chain_hash(parent_hash: int, tokens: Tuple[int, ...]) -> int:
    """Content hash of one full block, chained over its prefix: the
    hash covers (parent chain hash, this block's token ids), so equal
    hashes along a chain imply equal *prefixes* block by block — the
    vLLM prefix-caching scheme.  Process-local (python ``hash`` over
    int tuples is deterministic within a process, which is all the
    in-memory index needs); collisions are SAFE regardless because
    every index hit is confirmed with a full token-id + parent compare
    (tests monkeypatch this to a constant to prove it)."""
    return hash((parent_hash, tokens))


def snap_origin(snap: dict) -> str:
    """`` (from replica <source>)`` when the snapshot carries its
    optional ``source`` tag, else an empty string — the suffix every
    import rejection appends so a bad wire names its sender."""
    src = snap.get("source")
    return f" (from replica {src})" if src else ""


class BlockAllocator:
    """Refcounted allocator over the pool's block ids, with a prefix
    cache (host side).

    Block 0 is never handed out — it is the shared trash block padded
    block-table slots point at (see module docstring).  Allocation is
    all-or-nothing: a partial grab would strand blocks the caller can't
    use (the scheduler admits against :meth:`free_blocks` first).

    Every handed-out block carries a refcount; a shared prefix block is
    mapped into several sequences' block tables at once and only
    becomes reclaimable when the count hits 0.  A block whose *content*
    is registered in the prefix index (:meth:`register`) is not freed
    at refcount 0 — it parks on an LRU of cached-but-unreferenced
    blocks, still matchable by :meth:`match_prefix`, and is reclaimed
    (cache entry dropped) only when a fresh allocation drains the plain
    free list: refcount-aware LRU eviction.  Eviction can never touch a
    block with live references — the LRU only ever holds refcount-0
    blocks.
    """

    def __init__(self, num_blocks: int, block_size: int = 16,
                 prefix_cache: bool = True):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (one is the trash block), got {num_blocks}"
            )
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        #: prefix caching on/off (off: register/match are no-ops and
        #: refcount-0 blocks always return to the plain free list)
        self.prefix_cache = bool(prefix_cache)
        #: injectable for collision tests (see chain_hash)
        self.hash_fn = chain_hash
        self._ref: List[int] = [0] * self.num_blocks
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        #: cached blocks with refcount 0, oldest first (the evictables)
        self._lru: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        #: chain hash -> block id, for every block with cached content
        #: (referenced or parked — a hot shared prefix stays matchable)
        self._index: Dict[int, int] = {}
        #: block id -> (chain_hash, parent_hash, token ids) for the
        #: full-compare on every index hit (collision safety)
        self._meta: Dict[int, Tuple[int, int, Tuple[int, ...]]] = {}
        self.peak_occupancy = 0.0  # high-water mark (bench column)

    @property
    def free_blocks(self) -> int:
        """Allocatable blocks: the plain free list plus the
        cached-but-unreferenced LRU (reclaimable on demand)."""
        return len(self._free) + len(self._lru)

    @property
    def capacity(self) -> int:
        """Allocatable blocks (pool size minus the trash block)."""
        return self.num_blocks - 1

    @property
    def cached_blocks(self) -> int:
        """Blocks currently holding prefix-cache content (referenced
        or parked on the LRU) — the occupancy gauge's numerator."""
        return len(self._index)

    def ref(self, block: int) -> int:
        """Live reference count of ``block`` (0 = free or parked)."""
        return self._ref[block]

    def occupancy(self) -> float:
        """Fraction of allocatable blocks currently owned by sequences."""
        return 1.0 - self.free_blocks / self.capacity

    def _drop_cache_entry(self, b: int) -> None:
        h, _parent, _tokens = self._meta.pop(b)
        if self._index.get(h) == b:
            del self._index[h]

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh block ids at refcount 1, or None if the pool
        can't satisfy all of them.  Drains the plain free list first,
        then reclaims cached-but-unreferenced blocks in LRU order
        (their cache entries are dropped — this is the eviction)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > self.free_blocks:
            return None
        take = min(n, len(self._free))
        taken = list(reversed(self._free[-take:])) if take else []
        del self._free[len(self._free) - take:]
        while len(taken) < n:
            b, _ = self._lru.popitem(last=False)  # oldest cached first
            self._drop_cache_entry(b)
            taken.append(b)
        for b in taken:
            self._ref[b] = 1
        self.peak_occupancy = max(self.peak_occupancy, self.occupancy())
        return taken

    def free(self, blocks: Sequence[int]) -> None:
        """Drop one reference per listed block.  At refcount 0 a block
        returns to the free list — or, when its content is cached, parks
        on the LRU tail, still matchable until reclaimed."""
        for b in blocks:
            if not 0 < b < self.num_blocks:
                raise ValueError(f"block id {b} out of range")
            if self._ref[b] <= 0:
                raise ValueError(f"double free of block {b}")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                if self.prefix_cache and b in self._meta:
                    self._lru[b] = None  # most-recently-freed at the tail
                else:
                    if b in self._meta:  # cache disabled mid-flight
                        self._drop_cache_entry(b)
                    self._free.append(b)

    def truncate_tail(self, blocks: List[int], keep_tokens: int
                      ) -> List[int]:
        """Trim a sequence's block table down to the blocks its first
        ``keep_tokens`` tokens occupy, releasing the tail references —
        the speculative-decode rollback primitive (docs/SERVING.md).

        Block-aligned by construction: a partially-filled surviving
        block stays mapped (its stale positions ≥ ``keep_tokens`` are
        masked by ``lens`` and overwritten before they are ever
        attended).  Tail blocks go through :meth:`free`, so the
        refcount/CoW rules hold unchanged — a shared or
        prefix-registered tail block loses this sequence's one
        reference and survives under any live ref (or parks on the
        LRU), never a double-free; a block id of 0 in the tail (the
        trash block) raises like any other out-of-range free.  Returns
        the surviving prefix of ``blocks`` (a new list)."""
        keep = blocks_for(keep_tokens, self.block_size) if keep_tokens > 0 \
            else 0
        if keep >= len(blocks):
            return list(blocks)
        self.free(blocks[keep:])
        return list(blocks[:keep])

    # -- the prefix cache ----------------------------------------------------

    def register(self, block: int, parent_hash: int,
                 tokens: Sequence[int]) -> Optional[int]:
        """Publish a FULL, immutable block's content into the prefix
        index; returns its chain hash (or None when caching is off).
        First registration of a hash wins — a second block with
        identical content simply stays private (no device-side dedup:
        re-pointing live block tables mid-sequence is not worth the
        churn).  Only ever call this for blocks all ``block_size``
        positions of which are written and will never be written again
        (the CoW invariant: a cached block is immutable)."""
        if not self.prefix_cache:
            return None
        if len(tokens) != self.block_size:
            raise ValueError(
                f"register() takes exactly one full block "
                f"({self.block_size} tokens), got {len(tokens)}")
        if self._ref[block] <= 0 and block not in self._meta:
            # a block registered after release could be handed out by
            # the free list while the index still points at it — the
            # scheduler publishes BEFORE emission/release for this
            # reason, and this guard turns the misuse into a loud error
            raise ValueError(
                f"register of unreferenced block {block} — publish "
                f"full blocks before releasing the sequence")
        toks = tuple(int(t) for t in tokens)
        h = self.hash_fn(parent_hash, toks)
        if h not in self._index:
            self._index[h] = block
            self._meta[block] = (h, parent_hash, toks)
        return h

    def _walk_prefix(self, tokens: Sequence[int],
                     max_blocks: Optional[int]):
        """Yield ``(block, chain_hash)`` per verified cached block of
        ``tokens``' block-aligned prefix, in order: ONE definition of
        the chain rules (hash chaining from :data:`PREFIX_HASH_ROOT`,
        index lookup, full parent + token-id compare so collisions are
        rejected) shared by the side-effecting :meth:`match_prefix`
        and the read-only :meth:`peek_prefix` — the router's placement
        score must agree with what admission will actually match."""
        bs = self.block_size
        n_full = len(tokens) // bs
        if max_blocks is not None:
            n_full = min(n_full, max_blocks)
        parent = PREFIX_HASH_ROOT
        for i in range(n_full):
            toks = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
            h = self.hash_fn(parent, toks)
            b = self._index.get(h)
            if b is None:
                return
            _h, m_parent, m_tokens = self._meta[b]
            if m_parent != parent or m_tokens != toks:
                return  # hash collision — the full compare rejects it
            yield b, h
            parent = h

    def match_prefix(self, tokens: Sequence[int],
                     max_blocks: Optional[int] = None
                     ) -> Tuple[List[int], List[int]]:
        """Longest cached block-aligned prefix of ``tokens``: walks the
        hash chain over full blocks (:meth:`_walk_prefix`) and bumps
        the refcount of each matched block (un-parking it from the
        LRU) — the caller now owns one reference and releases it
        through :meth:`free` like any other block.  ``max_blocks`` caps
        the match (the scheduler passes ``(len(prompt) - 1) //
        block_size`` so at least one prompt token is always left to
        compute — the prefill step must emit a first token).  Returns
        (block ids, chain hashes), both possibly empty."""
        if not self.prefix_cache:
            return [], []
        blocks: List[int] = []
        hashes: List[int] = []
        for b, h in self._walk_prefix(tokens, max_blocks):
            if self._ref[b] == 0:
                self._lru.pop(b, None)
            self._ref[b] += 1
            blocks.append(b)
            hashes.append(h)
        self.peak_occupancy = max(self.peak_occupancy, self.occupancy())
        return blocks, hashes

    def peek_prefix(self, tokens: Sequence[int],
                    max_blocks: Optional[int] = None) -> int:
        """How many leading full blocks of ``tokens`` the index holds —
        :meth:`match_prefix` minus every side effect (no refcount
        bumps, no LRU un-parking, no peak-occupancy update).  This is
        the published prefix index the fleet router scores replicas by
        (prefix-affinity placement, docs/FLEET.md): the probe must be
        free to run against N replicas per request, and only the
        winning replica's admission may take references."""
        if not self.prefix_cache:
            return 0
        return sum(1 for _ in self._walk_prefix(tokens, max_blocks))

    def clear_cache(self) -> None:
        """Drop every prefix-cache entry (bench A/B legs): parked
        blocks return to the plain free list; referenced blocks lose
        their index entries and free normally when released."""
        for b in list(self._lru):
            self._free.append(b)
        self._lru.clear()
        for b in list(self._meta):
            self._drop_cache_entry(b)

    # -- block migration (serving fault tolerance, docs/SERVING.md) ----------

    #: snapshot wire format tag — refuse anything else on import
    SNAP_FORMAT = "horovod_tpu.serve.kvsnap/1"

    def export_blocks(self, blocks: Sequence[int], tokens: Sequence[int],
                      pages: Optional[list] = None,
                      source: Optional[str] = None) -> dict:
        """Serialize a sequence's FULL-block chain for migration: the
        covered token ids, the chain hashes recomputed from
        :data:`PREFIX_HASH_ROOT` (the importer re-verifies them — the
        end-to-end integrity check a corrupt ``serve.migrate`` wire must
        fail), and optionally the per-block K/V pages.  ``tokens`` must
        cover exactly ``len(blocks) * block_size`` positions — only
        written, verified positions belong in a snapshot (the caller
        excludes the partial tail and any unsettled draft tokens).
        ``source`` optionally names the exporting replica; importers
        fold it into their rejection errors so a corrupt or foreign
        snapshot names where it came from (a snapshot without the key
        imports exactly as before — the format stays ``kvsnap/1``).
        Returns a plain dict (host data only, process-portable given
        the same ``hash_fn``)."""
        bs = self.block_size
        toks = [int(t) for t in tokens]
        if len(toks) != len(blocks) * bs:
            raise ValueError(
                f"export_blocks: {len(blocks)} blocks need exactly "
                f"{len(blocks) * bs} tokens, got {len(toks)}")
        hashes: List[int] = []
        parent = PREFIX_HASH_ROOT
        for i in range(len(blocks)):
            parent = self.hash_fn(parent, tuple(toks[i * bs:(i + 1) * bs]))
            hashes.append(parent)
        snap = {
            "format": self.SNAP_FORMAT,
            "block_size": bs,
            "tokens": toks,
            "hashes": hashes,
            "pages": list(pages) if pages is not None else None,
        }
        if source is not None:
            snap["source"] = str(source)
        return snap

    def import_blocks(self, snap: dict
                      ) -> Tuple[List[int], List[Tuple[int, int]]]:
        """Re-register an exported block chain in THIS allocator.

        Verifies the snapshot first — the chain hashes are recomputed
        from the carried tokens and compared to the carried hashes, so
        a corrupted wire (one flipped token byte anywhere) raises
        ``ValueError`` before any allocator state changes: the
        ``serve.migrate`` corrupt-detection contract.  Then, per block
        in chain order: an index hit (same chain hash, full parent +
        token compare) takes a reference on the existing block — its
        pages are already correct, nothing to write; a miss allocates a
        fresh block and registers it under the chain hash.  Returns
        ``(blocks, fresh)`` where ``fresh`` lists ``(chain_index,
        block)`` pairs whose pages the caller must fill from
        ``snap["pages"]`` BEFORE the blocks can serve a gather.  All
        returned blocks carry one reference owned by the caller (park
        them via :meth:`free` once pages are written, or hand them to a
        sequence).  All-or-nothing: a pool too small mid-chain rolls
        back every reference and registration taken so far.

        Rejection errors name the exporting replica when the snapshot
        carries a ``source`` tag (a two-tier fleet's handoff wire can
        cross any prefill→decode pair — "corrupt snapshot" without a
        sender is undebuggable)."""
        who = snap_origin(snap) if isinstance(snap, dict) else ""
        if snap.get("format") != self.SNAP_FORMAT:
            raise ValueError(
                f"unknown KV snapshot format {snap.get('format')!r}{who}")
        if int(snap.get("block_size", -1)) != self.block_size:
            raise ValueError(
                f"snapshot block_size {snap.get('block_size')} != "
                f"allocator block_size {self.block_size}{who}")
        if not self.prefix_cache:
            raise ValueError(
                "import_blocks needs the prefix cache (registered blocks "
                "are what makes a migrated chain matchable)")
        bs = self.block_size
        toks = [int(t) for t in snap["tokens"]]
        carried = list(snap["hashes"])
        if len(toks) != len(carried) * bs:
            raise ValueError(
                f"snapshot carries {len(carried)} hashes but "
                f"{len(toks)} tokens (need {len(carried) * bs}){who}")
        # integrity gate: recompute the whole chain BEFORE touching state
        parent = PREFIX_HASH_ROOT
        parents: List[int] = []
        for i, h in enumerate(carried):
            parents.append(parent)
            want = self.hash_fn(parent, tuple(toks[i * bs:(i + 1) * bs]))
            if want != h:
                raise ValueError(
                    f"KV snapshot chain-hash mismatch at block {i}: "
                    f"corrupt or foreign snapshot rejected{who}")
            parent = h
        blocks: List[int] = []
        fresh: List[Tuple[int, int]] = []
        try:
            for i, h in enumerate(carried):
                b = self._index.get(h)
                if b is not None:
                    _h, m_parent, m_tokens = self._meta[b]
                    if (m_parent == parents[i]
                            and m_tokens == tuple(toks[i * bs:(i + 1) * bs])):
                        if self._ref[b] == 0:
                            self._lru.pop(b, None)
                        self._ref[b] += 1
                        blocks.append(b)
                        continue
                    # hash collision with different content — the fresh
                    # block stays private (register() first-wins), which
                    # is safe but unmatchable; still correct pages.
                got = self.alloc(1)
                if got is None:
                    raise ValueError(
                        f"pool exhausted importing block {i} of "
                        f"{len(carried)}{who}")
                nb = got[0]
                if h not in self._index:
                    self._index[h] = nb
                    self._meta[nb] = (h, parents[i],
                                      tuple(toks[i * bs:(i + 1) * bs]))
                blocks.append(nb)
                fresh.append((i, nb))
            self.peak_occupancy = max(self.peak_occupancy, self.occupancy())
            return blocks, fresh
        except Exception:
            # roll back: never leave a registered-but-pages-unwritten
            # block matchable, never leak references
            for _i, nb in fresh:
                if nb in self._meta:
                    self._drop_cache_entry(nb)
            for b in blocks:
                self._ref[b] -= 1
                if self._ref[b] == 0:
                    if self.prefix_cache and b in self._meta:
                        self._lru[b] = None
                    else:
                        self._free.append(b)
            raise


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class PagedKVState:
    """Device-side paged-cache state for ONE engine step (a pytree).

    ``k``/``v``: (num_layers, num_blocks, block_size, H_kv, D) pools.
    ``tables``: (B, max_blocks) int32 — the step batch's block tables,
    rows padded with 0 (the trash block).
    ``lens``: (B,) int32 — tokens already written for each sequence
    BEFORE this step's token(s); pad slots carry 0.
    ``mode``: 'decode' | 'chunk' (static — selects the write/attend
    shape inside the traced step).  'chunk' is the mixed
    prefill+decode step: each row writes/attends ``chunk_lens[i]`` new
    tokens starting at its own global offset ``lens[i]`` — a decode row
    is simply a chunk of length 1, a prefill chunk at offset k is just
    another batch row, and whole-prompt prefill is the offset-0 case
    (docs/SERVING.md).
    ``chunk_lens``: (B,) int32, chunk mode only — valid new tokens per
    row within the padded chunk width; pad rows carry 0.
    ``gather_pages``: static page bound for the unwindowed
    :meth:`gather` copy — the engine passes the batch's live
    max-context *page tier* so the copy is O(live context), not
    ``max_blocks``, while shapes stay static per tier.
    """

    k: jax.Array
    v: jax.Array
    tables: jax.Array
    lens: jax.Array
    mode: str = "decode"
    chunk_lens: Optional[jax.Array] = None
    gather_pages: Optional[int] = None

    def tree_flatten(self):
        return ((self.k, self.v, self.tables, self.lens, self.chunk_lens),
                (self.mode, self.gather_pages))

    @classmethod
    def tree_unflatten(cls, aux, children):
        k, v, tables, lens, chunk_lens = children
        return cls(k=k, v=v, tables=tables, lens=lens,
                   chunk_lens=chunk_lens, mode=aux[0],
                   gather_pages=aux[1])

    # -- static geometry -----------------------------------------------------

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    @property
    def max_blocks(self) -> int:
        return self.tables.shape[1]

    # -- traced cache ops (called from inside the model's attention) ---------

    def write_decode(self, layer: int, k_new: jax.Array,
                     v_new: jax.Array) -> None:
        """Scatter one decode token's K/V — (B, 1, H_kv, D) at position
        ``lens`` — into each sequence's tail block."""
        blk = jnp.take_along_axis(
            self.tables, (self.lens[:, None] // self.block_size), axis=1
        )[:, 0]  # (B,)
        off = self.lens % self.block_size
        self.k = self.k.at[layer, blk, off].set(k_new[:, 0])
        self.v = self.v.at[layer, blk, off].set(v_new[:, 0])

    def write_chunk(self, layer: int, k_new: jax.Array,
                    v_new: jax.Array) -> None:
        """Scatter one mixed-step chunk's K/V — (B, C, H_kv, D), row i's
        tokens at global positions ``lens[i] .. lens[i]+chunk_lens[i]-1``
        — through the block tables.  Columns beyond a row's
        ``chunk_lens`` land in the trash block (their table lookup is
        clamped first so a pad position past ``max_blocks`` can never
        alias a real tail block — the oversize-tier hazard the engine
        documents).  Writes only ever touch positions ≥ ``lens``, i.e.
        each row's PRIVATE tail — never a shared prefix block (the CoW
        invariant; the scheduler asserts refcounts on the host side)."""
        b, c = k_new.shape[0], k_new.shape[1]
        rel = jnp.arange(c, dtype=jnp.int32)[None]  # (1, C)
        pos = self.lens[:, None] + rel  # (B, C) global positions
        valid = rel < self.chunk_lens[:, None]
        col = jnp.minimum(pos // self.block_size, self.max_blocks - 1)
        blk = jnp.take_along_axis(self.tables, col, axis=1)
        blk = jnp.where(valid, blk, 0)  # pad columns -> trash block
        off = pos % self.block_size
        self.k = self.k.at[layer, blk, off].set(k_new)
        self.v = self.v.at[layer, blk, off].set(v_new)

    def gather(self, layer: int, window: Optional[int] = None,
               q_span: int = 1):
        """Gather each sequence's pages contiguous for the decode/chunk
        kernel: returns (k, v, kv_start) with k/v (B, n_blocks*
        block_size, H_kv, D) and kv_start (B,) the global position of
        each gathered row 0.

        With ``window`` set only the trailing pages that can hold the
        window are gathered — the static gather width drops from
        ``max_blocks`` to ~``window/block_size`` pages, which with the
        in-kernel block skip is the O(window) decode read.  ``q_span``
        widens that reach for chunk steps (the chunk's last query sits
        ``q_span - 1`` positions past ``lens``).

        Without a window, ``gather_pages`` (static, set per step by the
        engine from the batch's live max-context PAGE TIER) bounds the
        copy: pages ``[0, gather_pages)`` instead of the full
        ``max_blocks`` width — the tier-bounded gather that recovers
        the paging savings on the copy while keeping shapes static per
        tier (PERF.md round 8's honest second term)."""
        bs = self.block_size
        if window is None:
            n = self.gather_pages or self.max_blocks
            tbl = self.tables[:, :n] if n < self.max_blocks else self.tables
            kv_start = jnp.zeros((self.tables.shape[0],), jnp.int32)
        else:
            # pages covering positions [lens - window + 1, lens + q_span
            # - 1]: the window, the in-flight chunk, one page of
            # alignment slack
            n_win = min(self.max_blocks, (window + q_span - 1) // bs + 2)
            first = jnp.clip(
                (self.lens + 1 - window) // bs, 0, self.max_blocks - n_win)
            idx = first[:, None] + jnp.arange(n_win, dtype=jnp.int32)[None]
            tbl = jnp.take_along_axis(self.tables, idx, axis=1)
            kv_start = first * bs
        gk = self.k[layer][tbl]  # (B, n, bs, H_kv, D)
        gv = self.v[layer][tbl]
        b, n = tbl.shape
        h_kv, d = self.k.shape[3], self.k.shape[4]
        return (gk.reshape(b, n * bs, h_kv, d),
                gv.reshape(b, n * bs, h_kv, d), kv_start)


def make_pools(num_layers: int, num_blocks: int, block_size: int,
               num_kv_heads: int, head_dim: int, dtype) -> tuple:
    """Zeroed (k, v) pools: (L, N, block_size, H_kv, D) each."""
    shape = (num_layers, num_blocks, block_size, num_kv_heads, head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def pool_bytes(num_layers: int, num_blocks: int, block_size: int,
               num_kv_heads: int, head_dim: int, dtype,
               shards: int = 1) -> int:
    """Bytes of one K+V pool pair; ``shards`` > 1 gives the PER-CHIP
    slice under kv-head tensor sharding (each chip holds every block's
    ``num_kv_heads/shards`` heads — docs/SERVING.md)."""
    if shards < 1 or num_kv_heads % shards:
        raise ValueError(
            f"shards ({shards}) must divide num_kv_heads ({num_kv_heads})")
    per = (num_layers * num_blocks * block_size
           * (num_kv_heads // shards) * head_dim)
    return 2 * per * jnp.dtype(dtype).itemsize


def modeled_decode_read_bytes(context_len: int, *, block_size: int,
                              num_heads: int, num_kv_heads: int,
                              head_dim: int, num_layers: int,
                              window: Optional[int] = None,
                              dtype_bytes: int = 2,
                              max_seq_len: Optional[int] = None,
                              gather_pages: Optional[int] = None,
                              shards: int = 1) -> dict:
    """Modeled K/V bytes ONE sequence's decode step reads, paged vs the
    dense full-context baseline — the serve_bench column pinning the
    paged + GQA + window read reduction (CPU-measurable: it is pure
    block arithmetic, the same ``blocks_for`` the allocator uses).

    Two paged terms, because this engine's decode path has two stages:

    * ``paged_bytes`` — what the KERNEL reads: the owned pages holding
      live positions (window-truncated when set), once per KV head
      (``_kb_range`` skips the rest of the gathered buffer).
    * ``gathered_bytes`` — what :meth:`PagedKVState.gather` materializes
      first: with ``window`` set, ~``window/block_size`` trailing pages
      (the O(window) claim); with ``window=None``, the live-context
      PAGE TIER the engine bounds the copy by (``gather_pages`` — pass
      the tier the engine would pick, i.e. the smallest page tier
      covering the batch's max context; omit it for the pre-tier
      ``max_blocks``-wide copy, the honest cost PERF.md round 8 named
      and this bound removes).

    baseline ``full_bytes``: a contiguous ``max_seq_len`` MHA buffer —
    what a non-paged, non-GQA cache re-reads every step.

    ``shards`` > 1 models kv-head tensor sharding (docs/SERVING.md):
    each chip's pool slice holds ``num_kv_heads/shards`` heads of every
    block, so the PER-CHIP ``paged_bytes``/``gathered_bytes`` — the
    dominant decode read stream Pope et al. show is the bottleneck —
    drop by exactly the shard factor (pages/page geometry unchanged:
    tables replicate).  ``full_bytes`` stays the single-chip dense
    baseline so reduction ratios compose across the A/B.
    """
    if shards < 1 or num_kv_heads % shards:
        raise ValueError(
            f"shards ({shards}) must divide num_kv_heads ({num_kv_heads})")
    max_pages = blocks_for(max_seq_len or context_len, block_size)
    span = context_len if window is None else min(context_len, window + 1)
    pages = blocks_for(span, block_size) + (
        0 if window is None else 1)  # alignment slack page
    pages = min(pages, max_pages)
    if window is not None:
        gathered = min(max_pages, window // block_size + 2)
    elif gather_pages is not None:
        gathered = min(max_pages, max(gather_pages, pages))
    else:
        gathered = max_pages
    # K+V, one page, THIS CHIP's kv-head slice
    per_kv_page = 2 * block_size * (num_kv_heads // shards) * head_dim
    full = max_seq_len if max_seq_len is not None else context_len
    per_layer_full = 2 * full * num_heads * head_dim
    return {
        "paged_bytes": num_layers * pages * per_kv_page * dtype_bytes,
        "gathered_bytes": num_layers * gathered * per_kv_page * dtype_bytes,
        "full_bytes": num_layers * per_layer_full * dtype_bytes,
        "pages_read": pages,
        "pages_gathered": gathered,
    }
