"""Paged KV cache: fixed-size KV blocks in preallocated device pools.

The serving memory problem (vLLM's PagedAttention, SOSP '23): contiguous
per-sequence KV buffers sized for ``max_seq_len`` waste most of HBM on
reservations, and the waste is what caps the batch — the batch is what
throughput lives on.  Paging fixes it the way virtual memory did: the
pools hold ``num_blocks`` fixed-size blocks of K/V per layer, a
per-sequence *block table* maps logical token positions to physical
blocks, and a sequence owns exactly ``ceil(len / block_size)`` blocks at
any moment.

Two cooperating pieces:

* :class:`BlockAllocator` — the host-side free list.  Block 0 is
  reserved as the *trash block*: every padded/unused block-table slot
  points at it, so scatter writes from padded positions land somewhere
  harmless and gathers from padded slots read garbage that the decode
  kernel's per-sequence causal mask never attends
  (``ops.flash_attention.flash_decode_attention``).
* :class:`PagedKVState` — the device-side pytree carried through the
  jitted prefill/decode step: the pools, the step batch's block tables
  and lengths.  The transformer's attention layers call its
  ``write_prefill`` / ``write_decode`` / ``gather`` from inside the
  traced step; the updated pools come back out through the step's
  return value (functional update, ``.at[].set``).

The decode read path is where the paged + GQA + window savings stack:
the decode KERNEL reads only the blocks holding a sequence's live
positions (block-table gather + ``_kb_range`` skip), once per KV head
(GQA BlockSpecs), and only the trailing window's worth when ``window``
is set — where the gather itself is also truncated to the last pages
(without a window the gather copy stays ``max_blocks`` wide; static
shapes) — see :func:`modeled_decode_read_bytes`, which models both
terms, and the columns ``tools/serve_bench.py`` emits.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp


def blocks_for(length: int, block_size: int) -> int:
    """Blocks a sequence of ``length`` tokens occupies (ceil division)."""
    return -(-int(length) // int(block_size))


class BlockAllocator:
    """Free-list allocator over the pool's block ids (host side).

    Block 0 is never handed out — it is the shared trash block padded
    block-table slots point at (see module docstring).  Allocation is
    all-or-nothing: a partial grab would strand blocks the caller can't
    use (the scheduler admits against :meth:`free_blocks` first).
    """

    def __init__(self, num_blocks: int, block_size: int = 16):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (one is the trash block), got {num_blocks}"
            )
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self.peak_occupancy = 0.0  # high-water mark (bench column)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def capacity(self) -> int:
        """Allocatable blocks (pool size minus the trash block)."""
        return self.num_blocks - 1

    def occupancy(self) -> float:
        """Fraction of allocatable blocks currently owned by sequences."""
        return 1.0 - len(self._free) / self.capacity

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` block ids, or None if the pool can't satisfy all of them."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        taken = self._free[-n:] if n else []
        del self._free[len(self._free) - n:]
        self.peak_occupancy = max(self.peak_occupancy, self.occupancy())
        return list(reversed(taken))

    def free(self, blocks: Sequence[int]) -> None:
        seen = set(self._free)
        for b in blocks:
            if not 0 < b < self.num_blocks:
                raise ValueError(f"block id {b} out of range")
            if b in seen:
                raise ValueError(f"double free of block {b}")
            seen.add(b)
        self._free.extend(blocks)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class PagedKVState:
    """Device-side paged-cache state for ONE engine step (a pytree).

    ``k``/``v``: (num_layers, num_blocks, block_size, H_kv, D) pools.
    ``tables``: (B, max_blocks) int32 — the step batch's block tables,
    rows padded with 0 (the trash block).
    ``lens``: (B,) int32 — tokens already written for each sequence
    BEFORE this step's token(s); pad slots carry 0.
    ``mode``: 'prefill' | 'decode' (static — selects the write/attend
    shape inside the traced step).
    """

    k: jax.Array
    v: jax.Array
    tables: jax.Array
    lens: jax.Array
    mode: str = "decode"

    def tree_flatten(self):
        return (self.k, self.v, self.tables, self.lens), (self.mode,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        k, v, tables, lens = children
        return cls(k=k, v=v, tables=tables, lens=lens, mode=aux[0])

    # -- static geometry -----------------------------------------------------

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    @property
    def max_blocks(self) -> int:
        return self.tables.shape[1]

    # -- traced cache ops (called from inside the model's attention) ---------

    def write_prefill(self, layer: int, k_new: jax.Array,
                      v_new: jax.Array) -> None:
        """Scatter a prefill batch's K/V — (B, P, H_kv, D), positions
        0..P-1 — into the pools through the block tables.  Rows beyond a
        sequence's true length land in the trash block (padded table
        slots) or in the owned tail block at not-yet-attendable offsets
        (overwritten by the decode write before they become visible)."""
        b, p = k_new.shape[0], k_new.shape[1]
        pos = jnp.arange(p, dtype=jnp.int32)
        blk = jnp.take_along_axis(
            self.tables, pos[None, :] // self.block_size, axis=1)  # (B, P)
        off = jnp.broadcast_to(pos[None, :] % self.block_size, (b, p))
        self.k = self.k.at[layer, blk, off].set(k_new)
        self.v = self.v.at[layer, blk, off].set(v_new)

    def write_decode(self, layer: int, k_new: jax.Array,
                     v_new: jax.Array) -> None:
        """Scatter one decode token's K/V — (B, 1, H_kv, D) at position
        ``lens`` — into each sequence's tail block."""
        blk = jnp.take_along_axis(
            self.tables, (self.lens[:, None] // self.block_size), axis=1
        )[:, 0]  # (B,)
        off = self.lens % self.block_size
        self.k = self.k.at[layer, blk, off].set(k_new[:, 0])
        self.v = self.v.at[layer, blk, off].set(v_new[:, 0])

    def gather(self, layer: int, window: Optional[int] = None):
        """Gather each sequence's pages contiguous for the decode kernel:
        returns (k, v, kv_start) with k/v (B, n_blocks*block_size, H_kv,
        D) and kv_start (B,) the global position of each gathered row 0.

        With ``window`` set only the trailing pages that can hold the
        window are gathered — the static gather width drops from
        ``max_blocks`` to ~``window/block_size`` pages, which with the
        in-kernel block skip is the O(window) decode read."""
        bs = self.block_size
        if window is None:
            tbl = self.tables
            kv_start = jnp.zeros((self.tables.shape[0],), jnp.int32)
        else:
            # pages covering positions [lens - window, lens]: the window
            # plus the in-flight token, plus one page of alignment slack
            n_win = min(self.max_blocks, window // bs + 2)
            first = jnp.clip(
                (self.lens + 1 - window) // bs, 0, self.max_blocks - n_win)
            idx = first[:, None] + jnp.arange(n_win, dtype=jnp.int32)[None]
            tbl = jnp.take_along_axis(self.tables, idx, axis=1)
            kv_start = first * bs
        gk = self.k[layer][tbl]  # (B, n, bs, H_kv, D)
        gv = self.v[layer][tbl]
        b, n = tbl.shape
        h_kv, d = self.k.shape[3], self.k.shape[4]
        return (gk.reshape(b, n * bs, h_kv, d),
                gv.reshape(b, n * bs, h_kv, d), kv_start)


def make_pools(num_layers: int, num_blocks: int, block_size: int,
               num_kv_heads: int, head_dim: int, dtype) -> tuple:
    """Zeroed (k, v) pools: (L, N, block_size, H_kv, D) each."""
    shape = (num_layers, num_blocks, block_size, num_kv_heads, head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def pool_bytes(num_layers: int, num_blocks: int, block_size: int,
               num_kv_heads: int, head_dim: int, dtype) -> int:
    """Total bytes of one K+V pool pair."""
    per = num_layers * num_blocks * block_size * num_kv_heads * head_dim
    return 2 * per * jnp.dtype(dtype).itemsize


def modeled_decode_read_bytes(context_len: int, *, block_size: int,
                              num_heads: int, num_kv_heads: int,
                              head_dim: int, num_layers: int,
                              window: Optional[int] = None,
                              dtype_bytes: int = 2,
                              max_seq_len: Optional[int] = None) -> dict:
    """Modeled K/V bytes ONE sequence's decode step reads, paged vs the
    dense full-context baseline — the serve_bench column pinning the
    paged + GQA + window read reduction (CPU-measurable: it is pure
    block arithmetic, the same ``blocks_for`` the allocator uses).

    Two paged terms, because this engine's decode path has two stages:

    * ``paged_bytes`` — what the KERNEL reads: the owned pages holding
      live positions (window-truncated when set), once per KV head
      (``_kb_range`` skips the rest of the gathered buffer).
    * ``gathered_bytes`` — what :meth:`PagedKVState.gather` materializes
      first: with ``window`` set, ~``window/block_size`` trailing pages
      (the O(window) claim); with ``window=None`` the gather is
      ``max_blocks`` wide regardless of context (static shapes — the
      honest cost of this engine's gather-then-attend layout, and why
      windowed configs are the production recommendation).

    baseline ``full_bytes``: a contiguous ``max_seq_len`` MHA buffer —
    what a non-paged, non-GQA cache re-reads every step.
    """
    max_pages = blocks_for(max_seq_len or context_len, block_size)
    span = context_len if window is None else min(context_len, window + 1)
    pages = blocks_for(span, block_size) + (
        0 if window is None else 1)  # alignment slack page
    pages = min(pages, max_pages)
    gathered = max_pages if window is None else min(
        max_pages, window // block_size + 2)
    per_kv_page = 2 * block_size * num_kv_heads * head_dim  # K+V, one page
    full = max_seq_len if max_seq_len is not None else context_len
    per_layer_full = 2 * full * num_heads * head_dim
    return {
        "paged_bytes": num_layers * pages * per_kv_page * dtype_bytes,
        "gathered_bytes": num_layers * gathered * per_kv_page * dtype_bytes,
        "full_bytes": num_layers * per_layer_full * dtype_bytes,
        "pages_read": pages,
        "pages_gathered": gathered,
    }
