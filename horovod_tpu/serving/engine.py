"""The serving engine: continuous batching over the paged KV cache.

Ties the pieces together (docs/SERVING.md):

* the **paged KV cache** (`kv_cache.py`) holds every running sequence's
  K/V in fixed-size device blocks, with a refcounted **prefix cache**:
  prompts sharing a block-aligned prefix with anything previously
  served map the cached blocks straight into their tables and prefill
  only the tail;
* the **scheduler** (`scheduler.py`) re-decides the batch every
  iteration — prefix-match + admit against the token budget and block
  watermark, LIFO-evict (recompute) when the pool runs dry;
* **mixed and decode steps** are two jitted program families over
  *padding tiers*: a MIXED step packs the running decode batch plus
  prefill chunks (Sarathi-style chunked prefill — a chunk at offset k
  is just another batch row of the per-row-offset kernel, so a long
  prompt streams in without stalling decodes) and is keyed by (batch
  tier, chunk tier); a DECODE step is keyed by (batch tier, PAGE tier)
  — the unwindowed gather copy is bounded by the batch's live
  max-context page tier instead of ``max_blocks``.  Every step's
  shapes pad up to a tier from a small static menu, so a lifetime of
  arbitrary request shapes compiles a BOUNDED set of programs —
  ``|decode_tiers| × (|chunk_tiers| + |page_tiers| +
  spec·|page_tiers|)``, the last term the speculative verify programs
  at ONE static chunk width (the k axis; docs/SERVING.md) — (the same
  executable-cache discipline as the ops engine's ``max_signatures``;
  hits/misses are mirrored into the PR-1
  ``hvd_tpu_executable_cache_total`` counters so the bound is
  observable);
* the **staging queue** (`data.prefetch.DevicePrefetcher` in its
  restartable role) device-stages tokenized prompts while the current
  step computes, so admission never waits on PCIe.

Decoding is greedy (argmax, fp32 logits) — deterministic, which is what
makes the continuous batch *oracle-exact*: batched decode over the
paged cache emits token-for-token what one-at-a-time full-context
decode emits, across admit/evict boundaries (tests/test_serving.py).

``run_static`` is the pre-Orca baseline the bench A/Bs against: fixed
request batches held until every member finishes, contiguous
max-length KV reservations — both kinds of waste continuous batching
and paging exist to remove.
"""

from __future__ import annotations

import bisect
import collections
import dataclasses
import functools
import os
import time
from typing import Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import trace
from ..common.retry import env_float, env_int
from ..data.prefetch import DevicePrefetcher
from ..metrics import instruments as _instr
from ..models.transformer import Transformer, TransformerConfig
from ..ops.comm_model import modeled_serve_psum_bytes
from ..utils.logging import get_logger
from .kv_cache import (
    BlockAllocator, PagedKVState, blocks_for, make_pools, pool_bytes,
    snap_origin,
)
from .scheduler import ContinuousBatchingScheduler, Request, Sequence
from .speculative import Drafter, accept_greedy, make_drafter

_CACHE_HIT = _instr.EXEC_CACHE.labels("hit")
_CACHE_MISS = _instr.EXEC_CACHE.labels("miss")
_LAT_FIRST = _instr.SERVE_TOKEN_LATENCY.labels("first")
_LAT_INTER = _instr.SERVE_TOKEN_LATENCY.labels("inter")
_STEP_MIXED = _instr.SERVE_STEPS.labels("mixed")
_STEP_DECODE = _instr.SERVE_STEPS.labels("decode")
_STEP_SPEC = _instr.SERVE_STEPS.labels("spec")
_REQ_SUBMITTED = _instr.SERVE_REQUESTS.labels("submitted")
_REQ_COMPLETED = _instr.SERVE_REQUESTS.labels("completed")


# name constants so the analysis env pass sees the reads (the tier
# parser receives the name indirectly)
_PREFILL_TIERS_ENV = "HVD_TPU_SERVE_PREFILL_TIERS"
_DECODE_TIERS_ENV = "HVD_TPU_SERVE_DECODE_TIERS"

#: Mesh axis name of an engine-built serving shard mesh (an explicit
#: ``mesh=`` may use any name; the engine reads it off the mesh).
SHARD_AXIS = "tp"


def _env_tiers(name: str, default: Tuple[int, ...]) -> Tuple[int, ...]:
    """Comma-separated tier menu from the environment, validated at
    PARSE time: entries must be positive powers of two in strictly
    ascending order, or a clear ValueError names the variable and the
    rule.  Strict rather than warn-and-fall-back: a malformed tier list
    used to surface only at warmup as a confusing menu-size/program-key
    mismatch (tiers are the program-menu axis — _tier_for bisects an
    ascending list, and the page/chunk menus assume power-of-two
    growth), and silently serving the default menu instead of the
    operator's intended one is a capacity misconfiguration, not a
    tolerable degradation."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        tiers = tuple(int(x) for x in raw.split(",") if x.strip())
        if not tiers:
            raise ValueError("empty")
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not a comma-separated int list") from None
    bad = [t for t in tiers if t < 1 or t & (t - 1)]
    if bad:
        raise ValueError(
            f"{name}={raw!r}: tiers must be powers of two >= 1 "
            f"(got {bad}) — tiers key compiled step programs and the "
            f"menus assume power-of-two growth.  (A non-power-of-two "
            f"max_seq_len needs no entry: the engine appends it to the "
            f"prefill menu itself for post-evict re-prefills.)")
    if any(b <= a for a, b in zip(tiers, tiers[1:])):
        raise ValueError(
            f"{name}={raw!r}: tiers must be strictly ascending "
            f"(_tier_for bisects the menu)")
    return tiers


def _pow2_tiers(lo: int, hi: int) -> Tuple[int, ...]:
    tiers = []
    t = lo
    while t < hi:
        tiers.append(t)
        t *= 2
    tiers.append(hi)
    return tuple(tiers)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving knobs (every field has an ``HVD_TPU_SERVE_*`` env
    spelling resolved by :meth:`from_env`; docs/running.md).

    ``prefill_tiers`` / ``decode_tiers`` are the padding menus: prompt
    lengths pad up to a prefill tier, batch sizes to a decode tier, so
    the compiled-program count is bounded by the product of the menus,
    not by the request distribution.

    ``prefill_chunk`` > 0 bounds per-step prefill work: prompt tails
    stream in as chunks of at most this many tokens, each packed into
    a mixed step alongside the running decode batch, so decode p99
    stays flat under prompt bursts (0 = a tail prefills in one chunk).
    ``prefix_cache`` toggles prompt prefix caching (docs/SERVING.md);
    greedy outputs are bit-identical either way — the cache moves
    compute, never values."""

    block_size: int = 16
    num_blocks: int = 0  # 0 = auto: full residency for the largest batch
    token_budget: int = 2048
    watermark: int = 4
    prefill_tiers: Tuple[int, ...] = ()
    decode_tiers: Tuple[int, ...] = (1, 2, 4, 8)
    prefill_chunk: int = 0
    prefix_cache: bool = True
    #: default per-request latency budget in seconds from arrival
    #: (``HVD_TPU_SERVE_DEADLINE``; 0 = none): requests past it are
    #: shed pre-admission and cancelled in flight — compute never goes
    #: to tokens the client has stopped waiting for.  Per-request
    #: ``submit(deadline_s=...)`` overrides.
    deadline_s: float = 0.0
    #: tensor-shard the engine over this many chips of one ICI slice
    #: (kv heads + paged pool head-sharded, Megatron FFN; must divide
    #: num_kv_heads/num_heads/d_model*mlp_ratio — docs/SERVING.md).
    #: 1 = single-device; ignored when an explicit mesh is passed.
    shards: int = 1
    #: speculative decoding (docs/SERVING.md speculative section):
    #: draft up to ``spec_k`` tokens per decode step with
    #: ``spec_drafter`` and verify them in ONE chunk-mode step — greedy
    #: outputs stay BIT-IDENTICAL to plain decode (verification is
    #: exact); acceptance rate moves throughput only.  k is a static
    #: menu axis: pure-speculative steps always pad to one chunk width
    #: (the next power of two >= spec_k + 1), so the compiled-program
    #: set stays bounded.  Per-request ``submit(spec_k=...)`` clamps
    #: below the engine's spec_k (0 = off for that request).
    spec: bool = False
    spec_k: int = 4
    spec_drafter: str = "prompt_lookup"

    @classmethod
    def from_env(cls, **overrides) -> "ServeConfig":
        base = cls(**overrides)
        fields = dataclasses.asdict(base)
        if "block_size" not in overrides:
            fields["block_size"] = env_int("HVD_TPU_SERVE_BLOCK_SIZE",
                                           base.block_size)
        if "num_blocks" not in overrides:
            fields["num_blocks"] = env_int("HVD_TPU_SERVE_NUM_BLOCKS",
                                           base.num_blocks)
        if "token_budget" not in overrides:
            fields["token_budget"] = env_int("HVD_TPU_SERVE_TOKEN_BUDGET",
                                             base.token_budget)
        if "watermark" not in overrides:
            fields["watermark"] = env_int("HVD_TPU_SERVE_WATERMARK",
                                          base.watermark)
        if "prefill_tiers" not in overrides:
            fields["prefill_tiers"] = _env_tiers(
                _PREFILL_TIERS_ENV, base.prefill_tiers)
        if "decode_tiers" not in overrides:
            fields["decode_tiers"] = _env_tiers(
                _DECODE_TIERS_ENV, base.decode_tiers)
        if "prefill_chunk" not in overrides:
            fields["prefill_chunk"] = env_int("HVD_TPU_SERVE_PREFILL_CHUNK",
                                              base.prefill_chunk)
        if "prefix_cache" not in overrides:
            fields["prefix_cache"] = bool(env_int(
                "HVD_TPU_SERVE_PREFIX_CACHE", int(base.prefix_cache)))
        if "deadline_s" not in overrides:
            fields["deadline_s"] = env_float("HVD_TPU_SERVE_DEADLINE",
                                             base.deadline_s)
        if "shards" not in overrides:
            fields["shards"] = env_int("HVD_TPU_SERVE_SHARDS", base.shards)
        if "spec" not in overrides:
            fields["spec"] = bool(env_int("HVD_TPU_SERVE_SPEC",
                                          int(base.spec)))
        if "spec_k" not in overrides:
            fields["spec_k"] = env_int("HVD_TPU_SERVE_SPEC_K", base.spec_k)
        if "spec_drafter" not in overrides:
            fields["spec_drafter"] = os.environ.get(
                "HVD_TPU_SERVE_SPEC_DRAFTER", base.spec_drafter)
        return cls(**fields)


def _tier_for(tiers: Tuple[int, ...], n: int) -> int:
    """Smallest tier >= n (tiers ascending)."""
    i = bisect.bisect_left(tiers, n)
    if i == len(tiers):
        raise ValueError(f"{n} exceeds the largest tier {tiers[-1]}")
    return tiers[i]


class ServingEngine:
    """Continuous-batching inference over one :class:`Transformer`.

    ``params`` is the flax params pytree (as from ``model.init``).  The
    model config must be causal with attention_impl 'dot' or 'flash';
    GQA (``num_kv_heads``) and sliding windows (``window``) both shrink
    the cache and the decode reads natively.

    ``mesh`` (or ``ServeConfig.shards`` > 1) tensor-shards the engine
    over one ICI slice's chips (docs/SERVING.md sharding section):
    attention kv heads + the paged pool head-shard, the FFN runs
    Megatron column/row-parallel, and each step is ONE ``shard_map``
    program with two psums per decoder layer.  Per-chip HBM decode
    reads — the stream decode throughput is bound by — drop by the
    shard factor; block tables, the allocator and this scheduler loop
    replicate bit-for-bit and run once on the host.  Greedy outputs
    stay token-identical to the single-device engine (the psums move
    fp32 reduction order only), and the warmup menu/compile-freedom
    contract is unchanged.
    """

    def __init__(self, cfg: TransformerConfig, params, *,
                 serve: Optional[ServeConfig] = None,
                 mesh: Optional[Mesh] = None,
                 drafter: Optional[Drafter] = None,
                 role: str = "both",
                 clock=time.perf_counter):
        if cfg.attention_impl not in ("dot", "flash") or not cfg.causal:
            raise ValueError(
                "serving requires a causal 'dot' or 'flash' config, got "
                f"attention_impl={cfg.attention_impl!r} causal={cfg.causal}")
        if role not in ("both", "prefill"):
            raise ValueError(
                f"role must be 'both' or 'prefill', got {role!r}")
        self.cfg = cfg
        self.serve_cfg = serve = serve or ServeConfig.from_env()
        self._clock = clock
        #: "both" (default) runs the full prefill+decode loop.
        #: "prefill" is the disaggregated fleet's prefill tier
        #: (docs/SERVING.md): the engine stops each request at the
        #: HANDOFF BOUNDARY — the step its prompt completes and the
        #: first token emits — parking an exported ``kvsnap/1`` record
        #: in :attr:`handoffs` instead of ever dispatching a decode (or
        #: speculative) program.  Warmup therefore compiles the mixed
        #: chunk menu ONLY, so decode programs do not merely go unused
        #: on this tier: they never exist.
        self.role = role
        #: rid -> (stream, snap, arrival) records parked at the handoff
        #: boundary for the fleet router to carry to a decode-tier
        #: replica (prefill role only; empty on "both" engines)
        self.handoffs: Dict[int, tuple] = {}
        #: replica name stamped into every kvsnap export's ``source``
        #: tag (the fleet replica sets it at spawn) so a rejecting
        #: importer names the sender; None = untagged
        self.snap_source: Optional[str] = None
        # -- tensor sharding (docs/SERVING.md): one model over the ICI
        # mesh — kv heads + the paged pool head-sharded, Megatron FFN,
        # scheduler/allocator untouched (their decisions are a pure
        # function of token ids and pool geometry, which replicate)
        if mesh is None and serve.shards > 1:
            from ..parallel._mesh_utils import tensor_shard_mesh

            mesh = tensor_shard_mesh(SHARD_AXIS, serve.shards)
        if mesh is not None and mesh.devices.ndim != 1:
            raise ValueError(
                f"serving mesh must be 1-D (the tensor shard axis), got "
                f"shape {mesh.devices.shape} — pass one ICI row; DCN "
                f"tiers stay out of the token loop (docs/SERVING.md)")
        self.mesh = mesh
        self.shards = int(mesh.devices.size) if mesh is not None else 1
        self.shard_axis = mesh.axis_names[0] if mesh is not None else None
        kv_heads = cfg.num_kv_heads or cfg.num_heads
        if self.shards > 1:
            hidden = cfg.d_model * cfg.mlp_ratio
            if (cfg.num_heads % self.shards or kv_heads % self.shards
                    or hidden % self.shards):
                raise ValueError(
                    f"shards ({self.shards}) must divide num_heads "
                    f"({cfg.num_heads}), num_kv_heads ({kv_heads}) and "
                    f"d_model*mlp_ratio ({hidden}) — kv heads are the "
                    f"pool's shard seam")
            cfg = dataclasses.replace(cfg, shard_axis=self.shard_axis)
        self._model = Transformer(cfg)
        if mesh is not None:
            from ..parallel.tensor_parallel import transformer_shard_specs

            # computed ONCE on the incoming tree: _place_params lays
            # leaves out by it and the shard_map in_specs below reuse it
            self._pspecs = transformer_shard_specs(params, self.shard_axis)
        else:
            self._pspecs = None
        self.params = self._place_params(params)
        bs = serve.block_size
        self.max_blocks_per_seq = blocks_for(cfg.max_seq_len, bs)
        max_batch = max(serve.decode_tiers)
        num_blocks = serve.num_blocks
        if num_blocks <= 0:
            num_blocks = 1 + self.max_blocks_per_seq * max_batch
        prefill_tiers = serve.prefill_tiers or _pow2_tiers(
            min(32, cfg.max_seq_len), cfg.max_seq_len)
        over = [t for t in prefill_tiers if t > cfg.max_seq_len]
        if over:
            # an oversize tier is not just waste: pad positions past
            # max_seq_len index block-table columns past max_blocks,
            # and the clamped gather would scatter pad garbage into the
            # sequence's REAL tail block — silent KV corruption
            get_logger().warning(
                "dropping prefill tiers %s > max_seq_len %d", over,
                cfg.max_seq_len)
            prefill_tiers = tuple(
                t for t in prefill_tiers if t <= cfg.max_seq_len)
        if not prefill_tiers or prefill_tiers[-1] < cfg.max_seq_len:
            # evicted contexts re-prefill at up to max_seq_len
            prefill_tiers = prefill_tiers + (cfg.max_seq_len,)
        self.prefill_tiers = prefill_tiers
        self.decode_tiers = serve.decode_tiers
        # chunk-width menu for the mixed step's q axis: the prefill
        # tiers capped at prefill_chunk (chunks never exceed the cap,
        # so larger tiers would never be exercised — and the cap itself
        # is a tier so a maximal chunk pads to exactly the cap)
        if serve.prefill_chunk > 0:
            cap = min(serve.prefill_chunk, cfg.max_seq_len)
            self.chunk_tiers = tuple(
                t for t in prefill_tiers if t < cap) + (cap,)
        else:
            self.chunk_tiers = prefill_tiers
        # page-tier menu for the unwindowed decode gather: the copy is
        # bounded by the batch's live max-context page tier instead of
        # max_blocks (windowed configs already truncate the gather to a
        # single static width, so the menu collapses to one entry)
        if cfg.window is None:
            self.page_tiers = _pow2_tiers(1, self.max_blocks_per_seq)
        else:
            self.page_tiers = (self.max_blocks_per_seq,)
        # -- speculative decoding (docs/SERVING.md): a drafter makes
        # decode steps multi-token — k drafted tokens verify as ONE
        # chunk row of width k+1 at the sequence tail.  k is a static
        # menu axis: every pure-speculative step pads its q width to
        # spec_w (next pow2 >= spec_k + 1), adding |page_tiers| mixed
        # programs per batch tier to the warmup menu, nothing more.
        self._drafter: Optional[Drafter] = drafter
        if self._drafter is None and serve.spec:
            self._drafter = make_drafter(serve.spec_drafter)
        if self.role == "prefill":
            # speculation is a decode accelerator; the prefill tier
            # never decodes (requests leave at the handoff boundary)
            self._drafter = None
        self.spec_w = 0
        if self._drafter is not None:
            if serve.spec_k < 1:
                raise ValueError(
                    f"spec_k must be >= 1 with speculation on, got "
                    f"{serve.spec_k}")
            self.spec_w = 1 << int(serve.spec_k).bit_length()  # >= k+1
        #: lifetime speculative counters (bench leg columns; the
        #: registry counters carry the production series)
        self.spec_drafted_tokens = 0
        self.spec_accepted_tokens = 0
        self.spec_rolled_back_tokens = 0
        self.spec_steps = 0
        self.spec_verified_rows = 0
        self.k_pool, self.v_pool = make_pools(
            cfg.num_layers, num_blocks, bs, kv_heads, cfg.head_dim,
            cfg.dtype)
        if self.mesh is not None:
            # each chip owns its kv heads' slice of EVERY block —
            # tables, refcounts and eviction state replicate, so the
            # host-side scheduler runs once, unsharded
            pool_sharding = NamedSharding(
                self.mesh, P(None, None, None, self.shard_axis, None))
            self.k_pool = jax.device_put(self.k_pool, pool_sharding)
            self.v_pool = jax.device_put(self.v_pool, pool_sharding)
        self.pool_bytes = pool_bytes(
            cfg.num_layers, num_blocks, bs, kv_heads, cfg.head_dim,
            cfg.dtype)
        #: HBM a single chip dedicates to the K+V pools — the resident
        #: footprint the shard factor divides (bench column)
        self.pool_bytes_per_shard = pool_bytes(
            cfg.num_layers, num_blocks, bs, kv_heads, cfg.head_dim,
            cfg.dtype, shards=self.shards)
        _instr.SERVE_KV_BLOCKS_PER_SHARD.set(num_blocks)
        self.allocator = BlockAllocator(
            num_blocks, bs, prefix_cache=serve.prefix_cache)
        self.scheduler = ContinuousBatchingScheduler(
            self.allocator, token_budget=serve.token_budget,
            watermark=serve.watermark, max_decode_batch=max_batch,
            max_seq_len=cfg.max_seq_len)
        # queue depth = scheduler pending + device-staged-but-undrained
        # (the satellite-pinned honesty contract: the gauge and the
        # fleet router's least-queue fallback read the same sum —
        # scheduler.queue_depth() — and _drain_staging re-books it
        # every step so staged rows are never invisible between
        # scheduler events)
        self.scheduler.staged_depth = lambda: len(self._staging_meta)
        #: intake gate: False = draining (fleet replica teardown) —
        #: submit/attach_source reject, in-flight work keeps stepping
        self.accepting = True
        self.results: Dict[int, np.ndarray] = {}
        self._ids_seen: set = set()
        #: True once any request carried a deadline — gates the per-step
        #: expiry scans off the no-deadline hot path
        self._any_deadline = serve.deadline_s > 0
        #: set to a list to record (request_id, emit_time, arrival) per
        #: token — the bench's raw latency trace (off by default: the
        #: registry histograms carry production quantiles)
        self.token_log: Optional[list] = None
        self._next_id = 0
        #: (kind, t0, t1) of the newest step program run — the extent
        #: first-token emission anchors its serve.first_decode span to
        self._last_step: Optional[tuple] = None
        self._progs: Dict[tuple, bool] = {}
        self._staging: Optional[DevicePrefetcher] = None
        self._staging_meta: collections.deque = collections.deque()
        self._source_done = True
        #: chunk tokens actually computed by prefill (prefix-cache hits
        #: and pad columns excluded) — the bench's
        #: ``prefill_tokens_computed`` column
        self.prefill_tokens_computed = 0
        #: per-chip ICI bytes the sharded steps' psums streamed so far
        #: (modeled, == the lowered inventory; 0 unsharded)
        self.shard_psum_bytes = 0
        if self.mesh is None:
            self._mixed_fn = jax.jit(self._mixed_step,
                                     static_argnames=("pages",))
            self._decode_fn = jax.jit(self._decode_step,
                                      static_argnames=("pages",))
        else:
            # ONE shard_map program per tier: params enter pre-sliced
            # (Megatron specs), pools on their kv-head shard, tables/
            # lens/tokens replicated; the traced body is the SAME
            # _mixed_step/_decode_step the single-device engine jits —
            # cfg.shard_axis inside makes the model run its local
            # slice with one psum per sublayer.  Outputs: next tokens
            # replicated (identical on every chip after the psums),
            # pools back on their shard.
            pspecs = self._pspecs
            pool = P(None, None, None, self.shard_axis, None)
            rep = P()

            def _mixed_sharded(params, k, v, tables, lens, chunk_lens,
                               tokens, pages):
                return jax.shard_map(
                    functools.partial(self._mixed_step, pages=pages),
                    mesh=self.mesh,
                    in_specs=(pspecs, pool, pool, rep, rep, rep, rep),
                    out_specs=(rep, pool, pool), check_vma=False,
                )(params, k, v, tables, lens, chunk_lens, tokens)

            self._mixed_fn = jax.jit(_mixed_sharded,
                                     static_argnames=("pages",))

            def _decode_sharded(params, k, v, tables, lens, last, pages):
                return jax.shard_map(
                    functools.partial(self._decode_step, pages=pages),
                    mesh=self.mesh,
                    in_specs=(pspecs, pool, pool, rep, rep, rep),
                    out_specs=(rep, pool, pool), check_vma=False,
                )(params, k, v, tables, lens, last)

            self._decode_fn = jax.jit(_decode_sharded,
                                      static_argnames=("pages",))

    def _place_params(self, params):
        """Lay the param pytree out for the engine's programs: under a
        mesh, each leaf is device_put to its Megatron spec
        (``self._pspecs``, shared with the step programs' in_specs) so
        the per-chip HBM param footprint drops by ~the shard factor
        alongside the pool slice; unsharded, params pass through."""
        if self.mesh is None:
            return params
        flat, treedef = jax.tree_util.tree_flatten(params)
        flat_specs = treedef.flatten_up_to(self._pspecs)
        return jax.tree_util.tree_unflatten(treedef, [
            jax.device_put(x, NamedSharding(self.mesh, s))
            for x, s in zip(flat, flat_specs)])

    def _book_psum_bytes(self, batch_tier: int, q_len: int) -> None:
        """Book one sharded step's modeled per-chip psum stream into
        the PR-1 counter (the comm model the MULTICHIP bench asserts
        == the lowered program's all_reduce inventory)."""
        if self.shards <= 1:
            return
        m = modeled_serve_psum_bytes(
            batch_tier, q_len, self.cfg.d_model, self.cfg.num_layers,
            self.shards, dtype=str(jnp.dtype(self.cfg.dtype)))
        self.shard_psum_bytes += m["stream_bytes"]
        _instr.SERVE_SHARD_PSUM_BYTES.inc(m["stream_bytes"])

    # -- the two tiered program families ------------------------------------

    def _mixed_step(self, params, k, v, tables, lens, chunk_lens, tokens,
                    pages=None):
        """One mixed chunked-prefill + decode step: row i writes and
        attends ``chunk_lens[i]`` new tokens at global offset
        ``lens[i]`` — decode rows are chunks of length 1, prefill
        chunks of any tail fill the rest of the batch, and a
        SPECULATIVE verification row is a chunk of length k+1 at the
        sequence tail (no new kernel — docs/SERVING.md).  Emits the
        greedy token at EVERY position, (B, C): position j of a row is
        the argmax after its tokens[:j+1] — a decode row reads column
        0, a completing prefill chunk its last valid column, a
        verification row all k+1 columns (the accept/reject inputs).
        ``pages`` (static) bounds the unwindowed gather copy like the
        decode step's page tier; None = the ``max_blocks``-wide copy
        (the prefill-mixed default, whose offsets span the whole
        table)."""
        state = PagedKVState(k=k, v=v, tables=tables, lens=lens,
                             mode="chunk", chunk_lens=chunk_lens,
                             gather_pages=pages)
        c = tokens.shape[1]
        positions = lens[:, None] + jnp.arange(c, dtype=jnp.int32)[None]
        logits, state = self._model.apply(
            {"params": params}, tokens, positions=positions, train=False,
            paged=state)
        next_tok = jnp.argmax(logits.astype(jnp.float32), axis=-1)
        return next_tok.astype(jnp.int32), state.k, state.v

    def _decode_step(self, params, k, v, tables, lens, last_tok, pages):
        state = PagedKVState(k=k, v=v, tables=tables, lens=lens,
                             mode="decode", gather_pages=pages)
        logits, state = self._model.apply(
            {"params": params}, last_tok[:, None], positions=lens[:, None],
            train=False, paged=state)
        next_tok = jnp.argmax(logits[:, 0].astype(jnp.float32), axis=-1)
        return next_tok.astype(jnp.int32), state.k, state.v

    def _book_program(self, kind: str, *dims: int) -> None:
        """Mirror the jit executable cache into the PR-1 hit/miss
        counters: the padding tiers make ``dims`` a bounded set, so
        steady state must be all hits (the acceptance assert)."""
        key = (kind,) + dims
        if key in self._progs:
            _CACHE_HIT.inc()
        else:
            _CACHE_MISS.inc()
            self._progs[key] = True

    @property
    def program_count(self) -> int:
        """Distinct (kind, tier...) step programs compiled so far."""
        return len(self._progs)

    def lowered_decode_text(self, batch_tier: Optional[int] = None,
                            pages: Optional[int] = None) -> str:
        """StableHLO text of ONE decode-step program (smallest tiers by
        default) — the input to the ``ops.comm_model`` inventories
        (``measured_tier_bytes`` for the sharded psums,
        ``serve_gather_read_bytes`` for the page-gather copies), so
        "modeled == measured" is asserted against the program the
        engine actually dispatches, per the PR-7 idiom.  Under a mesh
        the lowering carries per-chip (local) shapes, so the inventory
        reads the per-chip stream directly."""
        bt = batch_tier or self.decode_tiers[0]
        pt = pages or self.page_tiers[0]
        tables = jnp.zeros((bt, self.max_blocks_per_seq), jnp.int32)
        return self._decode_fn.lower(
            self.params, self.k_pool, self.v_pool, tables,
            jnp.ones((bt,), jnp.int32), jnp.zeros((bt,), jnp.int32),
            pages=pt).as_text()

    def lowered_mixed_text(self, batch_tier: Optional[int] = None,
                           chunk_tier: Optional[int] = None,
                           pages: Optional[int] = None) -> str:
        """StableHLO text of ONE mixed-step program (smallest batch and
        chunk tiers by default; ``pages=None`` = the prefill-mixed
        ``max_blocks``-wide gather).  The mixed/speculative twin of
        :meth:`lowered_decode_text` — the ``programs`` contract pass
        runs the same collective inventories over every program FAMILY
        the engine dispatches, not just plain decode."""
        bt = batch_tier or self.decode_tiers[0]
        c = chunk_tier or self.chunk_tiers[0]
        tables = jnp.zeros((bt, self.max_blocks_per_seq), jnp.int32)
        return self._mixed_fn.lower(
            self.params, self.k_pool, self.v_pool, tables,
            jnp.zeros((bt,), jnp.int32), jnp.ones((bt,), jnp.int32),
            jnp.zeros((bt, c), jnp.int32), pages=pages).as_text()

    def warmup(self) -> int:
        """Compile the WHOLE tier menu up front — every (batch tier,
        chunk tier) mixed program, every (batch tier, page tier) decode
        program, and (speculation on) every (batch tier, spec width,
        page tier) verification program: ``|decode_tiers| ×
        (|chunk_tiers| + |page_tiers| + spec·|page_tiers|)``.  The menu
        is what makes this possible (and cheap to reason about): the
        compiled set is bounded by the tier product — k rides as ONE
        static chunk width (``spec_w``), never a per-draft-length axis
        — so a production engine pre-warms it and serves its lifetime
        without a single mid-traffic XLA compile (a straggler compile
        is a multi-second p99 spike — measured in tools/serve_bench.py).

        Side-effect-free by construction: the dummy steps run with
        all-zero block tables, so every write lands in the trash block
        and no real sequence's cache is touched.  Returns the number of
        programs compiled.

        A ``role="prefill"`` engine warms the mixed chunk menu ONLY —
        ``|decode_tiers| × |chunk_tiers|`` programs.  Its requests
        leave at the handoff boundary, so the decode and speculative
        families would be dead weight; not compiling them is both the
        smaller menu the disaggregated prefill tier is for and the
        structural proof it can never run a decode step."""
        before = len(self._progs)
        tables = jnp.zeros((1, self.max_blocks_per_seq), jnp.int32)
        for bt in self.decode_tiers:
            tb = jnp.broadcast_to(tables, (bt, self.max_blocks_per_seq))
            lens = jnp.ones((bt,), jnp.int32)
            for c in self.chunk_tiers:
                self._book_program("mixed", bt, c, None)
                self._mixed_fn(self.params, self.k_pool, self.v_pool,
                               tb, jnp.zeros((bt,), jnp.int32),
                               jnp.ones((bt,), jnp.int32),
                               jnp.zeros((bt, c), jnp.int32), pages=None)
            if self.role == "prefill":
                continue  # decode/spec programs never exist on this tier
            for pt in self.page_tiers:
                self._book_program("decode", bt, pt)
                self._decode_fn(self.params, self.k_pool, self.v_pool,
                                tb, lens, jnp.zeros((bt,), jnp.int32),
                                pages=pt)
            if self._drafter is not None:
                for pt in self.page_tiers:
                    self._book_program("mixed", bt, self.spec_w, pt)
                    self._mixed_fn(self.params, self.k_pool, self.v_pool,
                                   tb, jnp.zeros((bt,), jnp.int32),
                                   jnp.ones((bt,), jnp.int32),
                                   jnp.zeros((bt, self.spec_w), jnp.int32),
                                   pages=pt)
        return len(self._progs) - before

    # -- request intake ------------------------------------------------------

    def _validate_request(self, prompt_len: int, max_new_tokens: int,
                          rid: Optional[int] = None) -> None:
        """The intake contract, shared by ALL three entry points
        (submit, attach_source staging, run_static): no request may be
        able to outgrow its block table mid-serve, and the prefill step
        always emits one token so asking for zero is a caller error."""
        who = "" if rid is None else f"request {rid}: "
        if prompt_len < 1:
            raise ValueError(f"{who}empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"{who}max_new_tokens must be >= 1 (the prefill step "
                f"always emits one token), got {max_new_tokens}")
        if prompt_len + max_new_tokens > self.cfg.max_seq_len:
            raise ValueError(
                f"{who}prompt ({prompt_len}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq_len "
                f"{self.cfg.max_seq_len}")

    def submit(self, prompt, max_new_tokens: int, *, eos_id=None,
               arrival: Optional[float] = None,
               deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None,
               spec_k: Optional[int] = None) -> int:
        """Enqueue one request; returns its id (key into ``results``).
        ``deadline_s`` overrides the engine's default latency budget
        (``ServeConfig.deadline_s``); past it the request is shed or
        cancelled and ``results`` carries whatever was generated.
        ``trace_id`` is the caller's trace context (the fleet router
        propagates its id here so the request's spans correlate across
        router, engine and scheduler — docs/TRACING.md).  ``spec_k``
        overrides the engine's speculative lookahead for THIS request
        (clamped to the engine's ``spec_k`` — the menu axis; 0 turns
        speculation off for the request; None inherits)."""
        if not self.accepting:
            raise RuntimeError(
                "engine is draining (accepting=False); submit rejected")
        if spec_k is not None and spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self._validate_request(len(prompt), max_new_tokens)
        if deadline_s is None:
            deadline_s = self.serve_cfg.deadline_s
        req = Request(
            id=self._next_id, prompt=prompt,
            max_new_tokens=int(max_new_tokens), eos_id=eos_id,
            arrival=self._clock() if arrival is None else arrival,
            deadline_s=deadline_s if deadline_s and deadline_s > 0
            else None,
            trace_id=trace_id, spec_k=spec_k)
        self._next_id += 1
        self._ids_seen.add(req.id)
        if req.deadline_s:
            self._any_deadline = True
        self.scheduler.submit(Sequence(req=req, context=prompt))
        _REQ_SUBMITTED.inc()
        return req.id

    def _stage_rows(self, requests: Iterable[Request]):
        """Generator the staging DevicePrefetcher consumes: pads each
        prompt to its prefill tier and hands the row over for the
        background device_put.  Metadata rides a side deque in the same
        order (the staging queue is strictly FIFO)."""
        for req in requests:
            # the raise propagates to the consumer via the prefetcher
            self._validate_request(len(req.prompt), req.max_new_tokens,
                                   rid=req.id)
            row = np.zeros(
                (_tier_for(self.prefill_tiers, len(req.prompt)),), np.int32)
            row[:len(req.prompt)] = req.prompt
            self._staging_meta.append(req)
            yield (row,)

    def attach_source(self, requests: Iterable[Request],
                      depth: Optional[int] = None) -> None:
        """Open-loop intake: stage ``requests`` (an iterator that may
        block until each request's arrival time) through the device
        prefetcher while steps compute."""
        if not self.accepting:
            raise RuntimeError(
                "engine is draining (accepting=False); source rejected")
        if self._staging is not None and not self._source_done:
            raise RuntimeError("a request source is already attached")
        gen = self._stage_rows(requests)
        if self._staging is None:
            self._staging = DevicePrefetcher(gen, depth=depth,
                                             source_kind="serving")
        else:
            self._staging.restart(gen)
        self._source_done = False

    def _drain_staging(self, block: bool) -> None:
        if self._staging is None or self._source_done:
            return
        while True:
            item = self._staging.poll(block=block)
            block = False  # at most one blocking wait per drain
            if item is self._staging.EXHAUSTED:
                self._source_done = True
                self.scheduler._book()  # staged rows just became pending
                return
            if item is None:
                self.scheduler._book()  # refresh staged-depth gauge
                return
            req = self._staging_meta.popleft()
            if req.deadline_s is None and self.serve_cfg.deadline_s > 0:
                # sourced requests inherit the engine default exactly
                # like submit()'s do — the open-loop intake is the path
                # overload shedding exists for
                req.deadline_s = self.serve_cfg.deadline_s
            if req.deadline_s and not req.arrival:
                # a deadline is measured FROM arrival: a request whose
                # source left arrival at the 0.0 default would read as
                # hours past budget against a perf_counter clock and
                # shed instantly — its clock starts when it surfaces
                req.arrival = self._clock()
            if req.deadline_s:
                self._any_deadline = True
            # caller-chosen ids and submit()'s counter share `results`:
            # reject an id already used (it would silently clobber that
            # request's output) and keep the counter strictly above
            # every id seen so future submit()s can't collide either
            if req.id in self._ids_seen:
                raise ValueError(
                    f"sourced request id {req.id} already in use")
            self._ids_seen.add(req.id)
            self._next_id = max(self._next_id, req.id + 1)
            seq = Sequence(req=req, context=req.prompt)
            seq.staged = item[0]
            self.scheduler.submit(seq)
            _REQ_SUBMITTED.inc()

    # -- batch assembly ------------------------------------------------------

    def _batch_tier(self, n: int) -> int:
        return _tier_for(self.decode_tiers, n)

    def _tables_lens(self, seqs: List[Sequence], bt: int, lens: List[int]):
        tables = np.zeros((bt, self.max_blocks_per_seq), np.int32)
        for i, s in enumerate(seqs):
            tables[i, :len(s.blocks)] = s.blocks
        lens_arr = np.zeros((bt,), np.int32)
        lens_arr[:len(seqs)] = lens
        return jnp.asarray(tables), jnp.asarray(lens_arr)

    def _chunk_row(self, s: Sequence, c: int, width: int):
        """One prefill chunk's tokens — ``context[prefilled:prefilled+c]``
        — padded to the chunk tier ``width``.  The device-staged row is
        used ONLY when it IS the chunk (whole prompt at exactly the
        step's tier): any device-side slice/pad here would compile one
        tiny XLA program per distinct chunk length — an unbounded
        program set through the back door, measured as 60–150 ms
        first-use spikes.  Sliced chunks assemble from the host-side
        context instead (prompt tokens are KBs; the K/V is what's big).
        """
        row = s.staged
        if row is not None and s.prefilled == 0 and \
                c == len(s.context) and row.shape[0] == width:
            return row
        host = np.zeros((width,), np.int32)
        host[:c] = s.context[s.prefilled:s.prefilled + c]
        return host

    def _select_chunks(self, prefill_rows: List[Sequence], slots: int):
        """Chunk work for one mixed step: FIFO over sequences still
        prefilling.  Each chunk is capped by ``prefill_chunk`` (the
        Sarathi-style bound on per-step prefill work — what keeps
        decode latency flat under prompt bursts); the token budget
        caps how many chunks PACK into one step but never splits a
        chunk below the cap (with ``prefill_chunk=0`` this reproduces
        the pre-chunking whole-prompt prefill step exactly, budget
        gating the batch sum with a first-chunk bypass as admission
        always did).  Returns [(seq, chunk_len)]."""
        cap = self.serve_cfg.prefill_chunk or max(self.chunk_tiers)
        left = self.scheduler.token_budget
        sel: List[Tuple[Sequence, int]] = []
        for s in prefill_rows:
            if len(sel) >= slots:
                break
            rem = len(s.context) - s.prefilled
            c = min(rem, cap)
            if sel and c > left:
                break
            sel.append((s, c))
            left -= c
        return sel

    def _run_mixed(self, decode_rows: List[Sequence], chunk_sel):
        """Execute ONE mixed step over ``decode_rows`` (one token each)
        plus ``chunk_sel`` ([(seq, chunk_len)]) — the single program
        both the engine loop and the static baseline assemble through
        (the A/B must execute identical step programs).  Row order:
        decode rows first, chunk rows after.  Returns the (batch tier,
        width) per-position argmax grid: a decode row's token is column
        0, a chunk's first token column ``chunk_len - 1``."""
        n = len(decode_rows) + len(chunk_sel)
        bt = self._batch_tier(n)
        width = _tier_for(
            self.chunk_tiers, max([c for _, c in chunk_sel], default=1))
        rows = []
        lens_list = []
        chunk_lens = np.zeros((bt,), np.int32)
        for i, s in enumerate(decode_rows):
            host = np.zeros((width,), np.int32)
            host[0] = s.generated[-1]
            rows.append(host)
            lens_list.append(s.length - 1)
            chunk_lens[i] = 1
        for j, (s, c) in enumerate(chunk_sel):
            rows.append(self._chunk_row(s, c, width))
            lens_list.append(s.prefilled)
            chunk_lens[len(decode_rows) + j] = c
        rows.extend([np.zeros((width,), np.int32)] * (bt - n))
        if all(isinstance(r, np.ndarray) for r in rows):
            tokens = jnp.asarray(np.stack(rows))  # one host put
        else:  # device-staged fast-path rows in the mix
            tokens = jnp.stack([jnp.asarray(r) for r in rows])
        tables, lens = self._tables_lens(
            decode_rows + [s for s, _ in chunk_sel], bt, lens_list)
        self._book_program("mixed", bt, width, None)
        self._book_psum_bytes(bt, width)
        tracing = trace.enabled()  # arg/list packing off the hot path
        t0 = trace.now() if tracing else 0.0
        next_tok, self.k_pool, self.v_pool = self._mixed_fn(
            self.params, self.k_pool, self.v_pool, tables, lens,
            jnp.asarray(chunk_lens), tokens, pages=None)
        out = np.asarray(next_tok)  # device sync: the step's true extent
        if tracing:
            t1 = trace.now()
            self._last_step = ("mixed", t0, t1)
            trace.add_span("serve.step", t0, t1, kind="mixed", batch=n,
                           chunks=len(chunk_sel),
                           rids=[s.req.id for s in decode_rows])
            for s, c in chunk_sel:
                trace.add_span("serve.prefill_chunk", t0, t1,
                               rid=s.req.id, chunk=int(c),
                               offset=int(s.prefilled),
                               trace=s.req.trace_id)
        _STEP_MIXED.inc()
        _instr.SERVE_PREFILL_CHUNKS.inc(len(chunk_sel))
        self.prefill_tokens_computed += sum(c for _, c in chunk_sel)
        return out, self._clock()

    def _decode_once(self, seqs: List[Sequence]):
        """One decode step over ``seqs`` — tokens in cache = length - 1
        (the newest generated token's K/V is written by THIS step, at
        position length - 1).  The unwindowed gather copy is bounded by
        the batch's live max-context PAGE TIER (``pages``), not
        ``max_blocks`` — the static-shape-per-tier form of the paging
        savings on the copy."""
        bt = self._batch_tier(len(seqs))
        cache_lens = [s.length - 1 for s in seqs]
        pages = self.max_blocks_per_seq
        if self.cfg.window is None:
            need = max(blocks_for(s.length, self.serve_cfg.block_size)
                       for s in seqs)
            pages = _tier_for(self.page_tiers, need)
        tables, lens = self._tables_lens(seqs, bt, cache_lens)
        last = np.zeros((bt,), np.int32)
        last[:len(seqs)] = [s.generated[-1] for s in seqs]
        self._book_program("decode", bt, pages)
        self._book_psum_bytes(bt, 1)
        tracing = trace.enabled()
        t0 = trace.now() if tracing else 0.0
        next_tok, self.k_pool, self.v_pool = self._decode_fn(
            self.params, self.k_pool, self.v_pool, tables, lens,
            jnp.asarray(last), pages=pages)
        out = np.asarray(next_tok)  # device sync: the step's true extent
        if tracing:
            t1 = trace.now()
            self._last_step = ("decode", t0, t1)
            trace.add_span("serve.step", t0, t1, kind="decode",
                           batch=len(seqs),
                           rids=[s.req.id for s in seqs])
        _STEP_DECODE.inc()
        return out, self._clock()

    # -- speculative decode (docs/SERVING.md) --------------------------------

    def _propose_draft(self, s: Sequence) -> None:
        """Ask the drafter for this sequence's next-step lookahead.
        The per-request ``spec_k`` clamps BELOW the engine's (the menu
        width ``spec_w`` is sized for ``serve_cfg.spec_k``; a larger
        request knob would widen the program key), and the draft is
        capped so the verify step can never write past ``max_seq_len``
        or draft tokens the generation budget would discard anyway.
        An empty draft means the row decodes plain — drafting is
        always best-effort."""
        k = s.req.spec_k if s.req.spec_k is not None \
            else self.serve_cfg.spec_k
        remaining = s.req.max_new_tokens - (
            len(s.generated) + (len(s.context) - len(s.req.prompt)))
        k = min(int(k), self.serve_cfg.spec_k,
                self.cfg.max_seq_len - s.length - 1, remaining - 1)
        if k < 1:
            s.draft = []
            return
        stream = s.context if not s.generated else np.concatenate(
            [s.context, np.asarray(s.generated, np.int32)])
        s.draft = [int(t) for t in self._drafter.draft(stream, k)][:k]

    def _run_spec_step(self, rows: List[Sequence]):
        """One pure-speculative mixed step over the decode batch: row i
        feeds ``[last token] + draft`` as a chunk of length
        ``1 + len(draft)`` at its tail offset (``lens = length - 1``,
        exactly like plain decode), padded to the STATIC width
        ``spec_w`` — draft length varies per row and per step, the
        program key never does.  Draft-free rows ride as chunks of
        length 1.  The gather copy is page-tiered like the decode
        step's, over the batch's live context plus its speculative
        tail."""
        bt = self._batch_tier(len(rows))
        width = self.spec_w
        tokens_host = np.zeros((bt, width), np.int32)
        chunk_lens = np.zeros((bt,), np.int32)
        lens_list = []
        for i, s in enumerate(rows):
            fed = [s.generated[-1]] + s.draft
            tokens_host[i, :len(fed)] = fed
            chunk_lens[i] = len(fed)
            lens_list.append(s.length - 1)
        pages = self.max_blocks_per_seq
        if self.cfg.window is None:
            need = max(blocks_for(s.length + len(s.draft),
                                  self.serve_cfg.block_size) for s in rows)
            pages = _tier_for(self.page_tiers, need)
        tables, lens = self._tables_lens(rows, bt, lens_list)
        self._book_program("mixed", bt, width, pages)
        self._book_psum_bytes(bt, width)
        tracing = trace.enabled()
        t0 = trace.now() if tracing else 0.0
        next_tok, self.k_pool, self.v_pool = self._mixed_fn(
            self.params, self.k_pool, self.v_pool, tables, lens,
            jnp.asarray(chunk_lens), jnp.asarray(tokens_host), pages=pages)
        out = np.asarray(next_tok)  # device sync: the step's true extent
        if tracing:
            t1 = trace.now()
            self._last_step = ("spec", t0, t1)
            trace.add_span("serve.step", t0, t1, kind="spec",
                           batch=len(rows),
                           drafted=int(sum(len(s.draft) for s in rows)),
                           rids=[s.req.id for s in rows])
        _STEP_SPEC.inc()
        self.spec_steps += 1
        return out, self._clock()

    def _settle_spec(self, s: Sequence, row_argmax, now: float) -> List[int]:
        """Greedy accept/reject one verification row, then roll the
        speculative KV tail back: the sequence keeps the blocks its
        post-acceptance length occupies and :meth:`truncate_tail`
        releases the rest through the normal refcount path (a shared or
        prefix-registered tail block survives under its other refs —
        never a double free).  Positions past the accept point inside
        the SURVIVING tail block hold rejected-draft K/V; they are
        garbage the causal mask never attends (``lens`` = true length)
        and the next step overwrites.  Returns the emitted tokens —
        bit-identical to what plain greedy decode would emit, by the
        acceptance rule (speculative.accept_greedy)."""
        k = len(s.draft)
        emitted, m = accept_greedy(s.draft, row_argmax[:k + 1])
        rolled = k - m
        s.spec_drafted += k
        s.spec_accepted += m
        self.spec_drafted_tokens += k
        self.spec_accepted_tokens += m
        self.spec_rolled_back_tokens += rolled
        self.spec_verified_rows += 1
        _instr.SERVE_SPEC_DRAFTED.inc(k)
        _instr.SERVE_SPEC_ACCEPTED.inc(m)
        if rolled:
            _instr.SERVE_SPEC_ROLLED_BACK.inc(rolled)
        new_len = s.length + len(emitted)
        s.blocks = self.allocator.truncate_tail(s.blocks, new_len)
        s.draft = []
        if trace.enabled() and self._last_step is not None:
            t0, t1 = self._last_step[1], self._last_step[2]
            trace.add_span("serve.spec_verify", t0, t1, rid=s.req.id,
                           drafted=k, accepted=m, trace=s.req.trace_id)
            if rolled:
                trace.event("serve.spec_rollback", rid=s.req.id,
                            tokens=rolled, trace=s.req.trace_id)
        return emitted

    # -- token emission ------------------------------------------------------

    def _observe_token(self, seq: Sequence, token: int, now: float) -> None:
        """Shared emission bookkeeping for BOTH legs (continuous and the
        static baseline) — identical latency semantics is what keeps the
        bench A/B honest."""
        seq.generated.append(int(token))
        if self.token_log is not None:
            self.token_log.append((seq.req.id, now, seq.req.arrival))
        if seq.first_token_at is None:
            seq.first_token_at = now
            _LAT_FIRST.observe(now - seq.req.arrival)
            trace.event("serve.first_token", rid=seq.req.id,
                        ttft=now - seq.req.arrival,
                        trace=seq.req.trace_id)
            if self._last_step is not None and \
                    self._last_step[0] in ("decode", "spec"):
                # the decode step that produced the first token — the
                # last term of the TTFT decomposition (a first token
                # emitted by the final prefill chunk is already covered
                # by that chunk's span; a speculative step counts — it
                # IS the decode step, verifying k+1 positions)
                trace.add_span("serve.first_decode", self._last_step[1],
                               self._last_step[2], rid=seq.req.id,
                               trace=seq.req.trace_id)
        elif seq.last_token_at is not None:
            # honest inter-token gap: after an eviction it includes the
            # requeue wait + re-prefill — that IS the user-visible stall
            _LAT_INTER.observe(now - seq.last_token_at)
        seq.last_token_at = now

    def _emit(self, seq: Sequence, token: int, now: float) -> None:
        self._observe_token(seq, token, now)
        if seq.done:
            trace.event("serve.finish", rid=seq.req.id,
                        tokens=len(seq.generated),
                        trace=seq.req.trace_id)
            if seq.spec_drafted:
                _instr.SERVE_SPEC_ACCEPT_RATE.observe(
                    seq.spec_accepted / seq.spec_drafted)
            self.scheduler.finish(seq)
            # the emitted stream: tokens folded into context by evictions
            # plus those generated since (an EOS always completes the
            # sequence the step it is emitted, so none hides mid-stream)
            self.results[seq.req.id] = self._partial_result(seq)
            _REQ_COMPLETED.inc()

    def _partial_result(self, seq: Sequence) -> np.ndarray:
        """Whatever a sequence generated so far (tokens folded into the
        context by evictions plus those generated since) — the output
        an aborted request publishes."""
        return np.concatenate([
            seq.context[len(seq.req.prompt):].astype(np.int32),
            np.asarray(seq.generated, np.int32)])

    def _finalize_shed(self) -> None:
        """Publish partial outputs for deadline-shed/cancelled
        sequences — ``results`` carries whatever was generated (often
        nothing), so callers (and the fleet router's collection pass)
        never wait on a request the engine already gave up on."""
        for seq in self.scheduler.shed:
            self.results[seq.req.id] = self._partial_result(seq)
            _instr.SERVE_REQUESTS.labels("expired").inc()
        self.scheduler.shed.clear()

    def cancel_all(self) -> None:
        """Abort EVERY request this engine still holds — running,
        pending, deadline-shed, or device-staged — publishing each
        one's partial result (often empty) so no caller polling
        ``results`` waits on a request the engine gave up on.  Running
        sequences release their blocks through the normal refcount
        path.  The fleet router's ejection hook: a SUSPECT replica's
        re-routable work was already re-submitted elsewhere; this
        clears the bookkeeping so the replica reads as drained without
        ever stepping again."""
        sched = self.scheduler
        self._finalize_shed()
        for seq in list(sched.running):
            sched.finish(seq)
            self.results.setdefault(seq.req.id, self._partial_result(seq))
        for seq in list(sched.pending):
            self.results.setdefault(seq.req.id, self._partial_result(seq))
        sched.pending.clear()
        if self._staging is not None:
            # stop the staging producer FIRST (close joins its thread):
            # it appends to _staging_meta concurrently, and snapshotting
            # before it stops would publish results for a prefix while
            # the tail keeps arriving — pollers of the tail's ids would
            # wait forever, and the producer would park on a full queue
            self._staging.close()
        for req in list(self._staging_meta):
            self.results.setdefault(req.id, np.zeros((0,), np.int32))
        self._staging_meta.clear()
        self._source_done = True
        sched._book()

    # -- fault tolerance: KV snapshot / migration (docs/SERVING.md) ----------

    def export_requests(self, rids: Optional[Iterable[int]] = None
                        ) -> Dict[int, tuple]:
        """Snapshot every in-flight request's recoverable state (the
        fleet router's drain handshake and the replica's periodic
        snapshot both call this): ``{rid: (tokens_so_far, snap,
        arrival)}`` where ``tokens_so_far`` is the full VERIFIED
        stream — prompt,
        tokens folded into context by evictions, tokens generated
        since — and ``snap`` (or None) serializes the stream's full,
        written blocks with their K/V pages
        (:meth:`BlockAllocator.export_blocks`).  Only verified
        positions export: the partial tail and any unsettled
        speculative garbage stay out by the ``tokens_in_cache``
        invariant, so an importer's resumed decode is bit-identical.
        ``arrival`` is the request's original arrival stamp — a
        re-dispatch carries it so the survivor's queue keeps
        arrival-order fairness (and TTFT/deadline accounting stays
        measured from the true arrival).  Host-only work — one pool
        pull shared across requests, zero compiles."""
        want = set(rids) if rids is not None else None
        out: Dict[int, tuple] = {}
        bs = self.serve_cfg.block_size
        k_host = v_host = None
        for seq in list(self.scheduler.running) + \
                list(self.scheduler.pending):
            rid = seq.req.id
            if want is not None and rid not in want:
                continue
            stream = seq.context if not seq.generated else np.concatenate(
                [seq.context, np.asarray(seq.generated, np.int32)])
            stream = np.asarray(stream, np.int32)
            snap = None
            n_full = min(seq.tokens_in_cache // bs, len(seq.blocks))
            if n_full > 0 and self.allocator.prefix_cache:
                if k_host is None:  # one transfer for the whole scan
                    k_host = np.asarray(self.k_pool)
                    v_host = np.asarray(self.v_pool)
                blocks = seq.blocks[:n_full]
                pages = [(np.array(k_host[:, b]), np.array(v_host[:, b]))
                         for b in blocks]
                snap = self.allocator.export_blocks(
                    blocks, stream[:n_full * bs], pages,
                    source=self.snap_source)
            out[rid] = (stream, snap, seq.req.arrival)
        for req in list(self._staging_meta):  # staged: prompt-only (cold)
            if want is None or req.id in want:
                out[req.id] = (np.asarray(req.prompt, np.int32), None,
                               req.arrival)
        return out

    def import_kv(self, snap: dict) -> int:
        """Re-register a migrated block chain in THIS engine's
        allocator and pools — the warm recovery path.  The chain
        hashes verify first (:meth:`BlockAllocator.import_blocks`
        raises ``ValueError`` on a corrupt snapshot before any state
        changes: the ``serve.migrate`` corrupt-detection contract);
        index hits cost nothing; fresh blocks get their pages written
        host-side and put back under the pool's sharding.  The whole
        chain then parks on the prefix-cache LRU, so the re-submitted
        request's admission matches it like any other cached prefix —
        zero new step programs, the compile-free contract holds on
        the recovery path.  Returns the number of matchable blocks."""
        blocks, fresh = self.allocator.import_blocks(snap)
        try:
            if fresh:
                pages = snap.get("pages")
                if not pages:
                    raise ValueError(
                        "snapshot carries no pages but its chain is not "
                        "fully cached here — cannot warm-import"
                        + snap_origin(snap))
                k_host = np.array(self.k_pool)
                v_host = np.array(self.v_pool)
                for i, b in fresh:
                    kp, vp = pages[i]
                    k_host[:, b] = kp
                    v_host[:, b] = vp
                if self.mesh is not None:
                    sharding = NamedSharding(
                        self.mesh,
                        P(None, None, None, self.shard_axis, None))
                    self.k_pool = jax.device_put(k_host, sharding)
                    self.v_pool = jax.device_put(v_host, sharding)
                else:
                    self.k_pool = jnp.asarray(k_host)
                    self.v_pool = jnp.asarray(v_host)
        except Exception:
            # never leave a registered-but-pages-unwritten block
            # matchable (it would serve garbage K/V)
            for _i, b in fresh:
                if b in self.allocator._meta:
                    self.allocator._drop_cache_entry(b)
            self.allocator.free(blocks)
            raise
        self.allocator.free(blocks)  # park the chain, matchable
        return len(blocks)

    def cancel(self, rid: int) -> bool:
        """Abort ONE request without publishing a result (the hedged-
        dispatch loser: its partial output must never race the
        winner's into the router's collection).  A running sequence
        releases its blocks through the normal refcount path; a
        queued one just leaves.  Device-staged rows cannot be plucked
        mid-stage — they drain, serve, and their result is ignored.
        Returns whether the request was found and cancelled."""
        sched = self.scheduler
        for seq in list(sched.running):
            if seq.req.id == rid:
                sched.finish(seq)
                return True
        for seq in list(sched.pending):
            if seq.req.id == rid:
                sched.pending.remove(seq)
                sched._book()
                return True
        return False

    def _handoff(self, seq: Sequence) -> None:
        """Park a prefill-complete request for the fleet's tier
        boundary (``role="prefill"`` only).  The request exports like
        a migration — full VERIFIED stream plus the ``kvsnap/1``
        chain — BEFORE it leaves the scheduler, so the snapshot sees
        its blocks while they are still owned.  ``finish`` then frees
        them through the normal refcount path, which PARKS the full
        chain on the prefix-cache LRU: a repeated template's next
        prefill still matches it here, even though the request itself
        decodes on another replica.  The router drains
        ``self.handoffs`` every fleet step and re-registers the chain
        in a decode-tier replica."""
        rid = seq.req.id
        rec = self.export_requests(rids=[rid]).get(rid)
        self.scheduler.finish(seq)
        if rec is not None:
            self.handoffs[rid] = rec

    # -- the scheduler loop --------------------------------------------------

    def step(self) -> bool:
        """One iteration: drain staging, admit (prefix-matching), draft
        (speculation on, decode-only batches), grow, then run ONE
        program — a MIXED step whenever prefill work is pending (chunks
        packed alongside the running decode batch, so a streaming
        prompt never stalls decodes), a SPECULATIVE verify step when
        any draft is pending, a decode step otherwise.  Returns False
        when there is nothing left to do."""
        idle = not self.scheduler.running and not self.scheduler.pending
        self._drain_staging(block=idle and not self._source_done)
        if self._any_deadline:
            now = self._clock()
            # cancel expired in-flight sequences (blocks free through
            # the normal refcount path) and shed expired admits; their
            # partial results publish so callers never wait forever
            self.scheduler.cancel_expired(now)
            self.scheduler.admit(now)
            self._finalize_shed()
        else:
            self.scheduler.admit()
        if self._drafter is not None and all(
                s.in_decode for s in self.scheduler.running):
            # drafts propose BEFORE growth (grow_running books the
            # speculative tail's blocks, shedding the draft first under
            # pool pressure) and only for pure-decode batches: a mixed
            # step's chunk width is the prefill tier axis, and riding
            # drafts through it would cross the k axis into the chunk
            # menu — a program-set product the bounded menu exists to
            # avoid.  Prefill phases are short; decode is where the
            # steps (and the HBM bytes) are.
            for s in self.scheduler.running:
                self._propose_draft(s)
        self.scheduler.grow_running()
        running = list(self.scheduler.running)
        decode_rows = [s for s in running if s.in_decode]
        prefill_rows = [s for s in running if not s.in_decode]
        if prefill_rows:
            # decode rows ride the mixed step ONLY under chunked
            # prefill: with the chunk tier bounded, a decode row's
            # padded q-width stays small and the ride is what keeps its
            # latency flat through a prompt burst.  Unchunked, the
            # chunk width is the whole prompt tier — riding would charge
            # every decode token the full prompt's q-work for no
            # latency win over just waiting the step out, so the
            # pre-chunking prefill-only step is kept verbatim.
            if self.serve_cfg.prefill_chunk <= 0:
                decode_rows = []
            # >= 1 chunk slot is guaranteed: admission caps running at
            # max_decode_batch, so with prefill_rows non-empty the
            # decode rows can fill at most bt_max - 1 of the batch
            bt_max = max(self.decode_tiers)
            sel = self._select_chunks(
                prefill_rows, bt_max - len(decode_rows))
            toks, now = self._run_mixed(decode_rows, sel)
            for s, c in sel:
                s.prefilled += c
            # publish BEFORE emission: _emit may finish a sequence and
            # release its blocks — registering after release could
            # index a block the free list is about to hand out
            for s in running:
                if s.blocks:
                    self.scheduler.publish_full_blocks(s)
            for i, s in enumerate(decode_rows):
                self._emit(s, toks[i, 0], now)
            base = len(decode_rows)
            for j, (s, c) in enumerate(sel):
                if s.in_decode:  # prompt complete -> its first token
                    self._emit(s, toks[base + j, c - 1], now)
            if self.role == "prefill":
                # the handoff boundary: a request that just crossed
                # into decode leaves NOW — this engine has no decode
                # programs to run it with (done rows already finished
                # inside _emit and publish their result normally)
                for s, _c in sel:
                    if s.in_decode and not s.done:
                        self._handoff(s)
            return True
        if decode_rows:
            if any(s.draft for s in decode_rows):
                out, now = self._run_spec_step(decode_rows)
                # settle (accept + rollback) BEFORE publication: the
                # published-block count is computed from tokens already
                # in cache (which lags the step — see tokens_in_cache),
                # so it can never reach into the truncated tail, and
                # publication must never index rejected-draft blocks
                emitted = [self._settle_spec(s, out[i], now) if s.draft
                           else [int(out[i, 0])]
                           for i, s in enumerate(decode_rows)]
                for s in decode_rows:
                    self.scheduler.publish_full_blocks(s)
                for s, toks in zip(decode_rows, emitted):
                    for t in toks:
                        if s.done:  # eos/budget inside an accepted run:
                            break   # the tail tokens were never real
                        self._emit(s, t, now)
                return True
            toks, now = self._decode_once(decode_rows)
            for s in decode_rows:
                self.scheduler.publish_full_blocks(s)
            for i, s in enumerate(decode_rows):
                self._emit(s, toks[i], now)
            return True
        return not self._source_done or bool(self.scheduler.pending)

    def run(self) -> Dict[int, np.ndarray]:
        """Drive :meth:`step` until every submitted/staged request has
        completed; returns ``results`` (id -> generated token ids)."""
        while self.step():
            pass
        return self.results

    # -- the pre-Orca baseline ----------------------------------------------

    def run_static(self, requests: List[Request],
                   batch_size: int) -> Dict[int, np.ndarray]:
        """Static (request-level) batching baseline: fixed batches held
        until every member finishes, each member holding a contiguous
        reservation for the batch's worst-case length — the two wastes
        continuous batching + paging remove.  Shares the engine's jitted
        tier programs, pools and greedy sampling, so the A/B isolates
        the SCHEDULING policy."""
        results: Dict[int, np.ndarray] = {}
        for r in requests:
            self._validate_request(len(r.prompt), r.max_new_tokens,
                                   rid=r.id)
        for at in range(0, len(requests), batch_size):
            chunk = requests[at:at + batch_size]
            seqs = [Sequence(req=r, context=np.asarray(r.prompt, np.int32))
                    for r in chunk]
            worst = max(len(r.prompt) + r.max_new_tokens for r in chunk)
            for s in seqs:
                got = self.allocator.alloc(
                    blocks_for(worst, self.serve_cfg.block_size))
                if got is None:
                    raise RuntimeError(
                        "static baseline could not reserve "
                        f"{worst}-token contiguous KV for a batch of "
                        f"{len(chunk)} — the reservation waste paging "
                        "removes")
                s.blocks = got
            # whole prompts in as few steps as the chunk-tier cap
            # allows, NO token-budget pacing and NO prefix publication/
            # matching — the pre-Orca baseline neither paces nor caches
            while True:
                todo = [s for s in seqs if not s.in_decode]
                if not todo:
                    break
                cap = self.serve_cfg.prefill_chunk or max(self.chunk_tiers)
                sel = [(s, min(len(s.context) - s.prefilled, cap))
                       for s in todo]
                toks, now = self._run_mixed([], sel)
                for j, (s, c) in enumerate(sel):
                    s.prefilled += c
                    if s.in_decode:
                        self._static_emit(s, toks[j, c - 1], now, results)
            while not all(s.done for s in seqs):
                toks, now = self._decode_once(seqs)
                for i, s in enumerate(seqs):
                    if not s.done:
                        self._static_emit(s, toks[i], now, results)
            for s in seqs:
                self.allocator.free(s.blocks)
                s.blocks = []
        return results

    def _static_emit(self, seq: Sequence, token: int, now: float,
                     results: Dict[int, np.ndarray]) -> None:
        self._observe_token(seq, token, now)
        if seq.done:
            results[seq.req.id] = np.asarray(seq.generated, np.int32)
