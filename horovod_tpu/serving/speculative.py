"""Speculative decoding on the chunk machinery (drafters + acceptance).

Decode is HBM-bound: every step re-streams the weights and the paged
KV, so bytes/step IS tokens/s (docs/PERF.md rounds 11-14).  Speculative
decoding (Leviathan et al. 2023) gets more tokens out of the same bytes
by VERIFYING k drafted tokens in one step instead of generating one —
and the repo already owns the exact compute shape verification needs:
PR 10's chunk rows score ``q_len >= 1`` positions at an arbitrary
per-row kv offset, so a verification row is literally a chunk row of
length k+1 at the sequence tail.  No new kernel, no approximation: the
chunk kernel is bit-exact against decode (the standing exactness
contract), and greedy accept/reject below reproduces the
non-speculative token stream EXACTLY regardless of draft quality — a
bad drafter costs throughput, never correctness.

This module is the host-side half: the :class:`Drafter` protocol and
its zero-parameter prompt-lookup implementation (Saxena 2023 — match
the trailing n-gram against the sequence's own prompt + generated
history; strong on exactly the templated traffic the prefix cache
already measured at 0.94 hit rate), plus :func:`accept_greedy`, the
pure accept/reject rule.  The engine owns the device half (packing
verification rows into the mixed step, the k axis of the warmup menu)
and the rollback (``BlockAllocator.truncate_tail``).
"""

from __future__ import annotations

from typing import Callable, List, Protocol, Sequence, Tuple, runtime_checkable


@runtime_checkable
class Drafter(Protocol):
    """Anything that proposes up to ``k`` next tokens for a sequence.

    ``tokens`` is the sequence's full visible history (prompt +
    generated so far); the return is a list of AT MOST ``k`` proposed
    continuations (possibly empty — no draft means the engine falls
    back to a plain one-token decode step for that sequence).
    Drafts are proposals only: greedy verification makes acceptance
    exact, so a drafter may be arbitrarily wrong."""

    def draft(self, tokens: Sequence[int], k: int) -> List[int]:
        ...


class PromptLookupDrafter:
    """Zero-parameter n-gram drafter (prompt lookup, Saxena 2023).

    Finds an earlier occurrence of the sequence's trailing n-gram
    (longest first, ``max_ngram`` down to ``min_ngram``) in its own
    history and proposes the tokens that followed it.  Among matches of
    the winning n-gram the MOST RECENT one with a full ``k``-token
    continuation wins (recency tracks the current phrasing; but a match
    sitting right at the cursor can only contribute the couple of
    tokens between itself and the end — on short-period repetition that
    starves every draft, so a slightly older full-length match beats a
    newer truncated one).  Falls back to the most recent match when no
    occurrence has ``k`` tokens of headroom.  Templated and repetitive
    traffic repeats its own phrases, so the continuation after a
    repeated n-gram is a strong guess — and it costs zero parameters
    and zero device compute."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"({min_ngram}, {max_ngram})")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def draft(self, tokens: Sequence[int], k: int) -> List[int]:
        toks = list(tokens)
        n_tok = len(toks)
        if k <= 0 or n_tok < self.min_ngram + 1:
            return []
        for n in range(min(self.max_ngram, n_tok - 1),
                       self.min_ngram - 1, -1):
            tail = toks[n_tok - n:]
            best: List[int] = []
            for i in range(n_tok - n - 1, -1, -1):
                if toks[i:i + n] == tail:
                    cont = toks[i + n:i + n + k]
                    if len(cont) >= k:
                        return cont  # most recent FULL-length match
                    if not best:
                        best = cont  # most recent match, kept as fallback
            if best:
                return best
        return []


class ModelDrafter:
    """Tiny-draft-model hook behind the same protocol: wraps any
    ``fn(tokens, k) -> proposed tokens`` callable (a distilled model's
    host-side greedy loop, a trie over corpus statistics, ...).  The
    engine neither knows nor cares — greedy verification keeps the
    output stream exact either way."""

    def __init__(self, fn: Callable[[Sequence[int], int], Sequence[int]]):
        self._fn = fn

    def draft(self, tokens: Sequence[int], k: int) -> List[int]:
        return [int(t) for t in self._fn(tokens, k)][:k]


#: registry for ``HVD_TPU_SERVE_SPEC_DRAFTER`` (docs/running.md)
_DRAFTERS = {
    "prompt_lookup": PromptLookupDrafter,
}


def make_drafter(name: str) -> Drafter:
    """Construct a registered drafter by name (the env-var spelling)."""
    try:
        return _DRAFTERS[name]()
    except KeyError:
        raise ValueError(
            f"unknown drafter {name!r}; registered: "
            f"{sorted(_DRAFTERS)}") from None


def accept_greedy(draft: Sequence[int],
                  verifier_argmax: Sequence[int]) -> Tuple[List[int], int]:
    """Greedy accept/reject: the exactness-preserving rule.

    ``verifier_argmax[i]`` is the verifier's greedy token at the
    position draft[i] was fed (so ``verifier_argmax`` has
    ``len(draft) + 1`` entries: one per draft position plus the bonus
    position after the last draft token).  The leading run where
    ``draft[i] == verifier_argmax[i]`` is accepted; the first
    disagreement is replaced by the verifier's own token — which is
    BY CONSTRUCTION what non-speculative greedy decode would have
    emitted there, because every accepted prefix position fed the
    verifier the same token greedy decode would have.  When the whole
    draft is accepted, the bonus position's argmax rides along free
    (the verify step already computed it).  Returns
    ``(emitted_tokens, n_accepted)``: ``len(emitted) == n_accepted + 1``
    always — a fully rejected draft still emits one token, so a
    speculative step never emits less than plain decode."""
    m = 0
    for d, v in zip(draft, verifier_argmax):
        if int(d) != int(v):
            break
        m += 1
    emitted = [int(t) for t in draft[:m]] + [int(verifier_argmax[m])]
    return emitted, m
