"""Fleet router: prefix-affinity placement + SLO-driven replica scale.

The serving half of the closed loop (ROADMAP item 3, docs/FLEET.md).
PR 12's DCN-exclusion rule deliberately keeps one engine inside one
ICI slice; serving more traffic than one slice can carry means
*replicating* engines — and once there are replicas, placement IS
latency: PR 10 measured the prefix cache as a 6.7× TTFT lever, and a
request routed to a replica that has never seen its template pays the
full prefill that another replica would have served from cache.

**Placement rule** (SGLang's RadixAttention routing, on this repo's
block-hash index instead of a radix tree):

1. score every accepting replica by
   :meth:`~horovod_tpu.fleet.replica.ServingReplica.cached_prefix_blocks`
   — the longest leading run of the prompt's chain hashes present in
   that replica's published block index (a pure peek; no refcounts
   move);
2. route to the best scorer (``affinity``);
3. on an all-zero tie — an unseen template — fall back to the
   replica with the least queue depth (``least_queue``), which both
   balances load AND spreads templates across replicas, so the cache
   working set partitions instead of replicating;
4. ``mode="round_robin"`` bypasses 1-3 — the A/B baseline
   ``tools/serve_bench.py --fleet`` measures against.

Placement moves *time*, never values: greedy decode is deterministic,
so outputs are token-identical under any routing (the bench asserts
it before reporting a number).

**Scaling**: the same :mod:`.policy` engine that resizes training
worlds evaluates the router's in-process signals — sliding-window p99
TTFT and mean queue depth per accepting replica — against the
``HVD_TPU_FLEET_*`` SLOs.  Scale-out spawns + warms a replica before
it takes traffic (zero mid-traffic compiles, the standing menu
contract); scale-in picks the accepting replica with the least queued
work, **drains** it (no new placements; in-flight and queued
sequences step to completion) and retires it only once empty.

**Disaggregation** (``prefill_replicas > 0`` /
``HVD_TPU_FLEET_PREFILL_REPLICAS``; ROADMAP item 2, the Splitwise /
DistServe shape): the fleet splits into a **prefill tier** (engines
built with ``role="prefill"`` — mixed chunk programs only, requests
leave at the handoff boundary) and a **decode tier** (full-menu
engines).  A request routes into the prefill tier, chunks its prompt
there, and at prefill completion its paged-KV block chain crosses the
tier boundary as a ``kvsnap/1`` snapshot (chaos site
``serve.handoff``): chain-hash verified re-registration on a decode
replica (**warm** — decode re-prefixes from cache, zero prefill
recompute) or, when the wire drops/corrupts, a deterministic cold
re-prefill.  Decode steps never share a batch with prefill chunks
again — the interference chunking only *bounded* is structurally
gone.  Each tier scales on its own signal: TTFT drives the prefill
tier (``policy``), per-replica decode tokens/s drives the decode tier
(``decode_policy`` / ``HVD_TPU_FLEET_DECODE_TPS_FLOOR``).  Placement
still moves time, never values — the handoff is the PR-18 migration
machinery on the happy path, so outputs stay token-identical.

The router is single-threaded and in-process: callers drive it with
:meth:`submit` + :meth:`step` (or :meth:`run_until_drained`), the
same way the engine itself is driven.  That is the bench/CI shape;
the surface (submit/step/scale) is what a multi-process front-end
would put behind RPC.
"""

from __future__ import annotations

import collections
import dataclasses
import inspect
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import chaos as _chaos
from .. import trace as _trace
from ..common.retry import env_float, env_int
from ..metrics import instruments as _instr
from ..ops.comm_model import measured_kvsnap_bytes
from ..trace import flight as _flight
from ..utils.logging import get_logger
from .policy import TargetTrackingPolicy, decode_policy_from_env
from .replica import DRAINING, PARKED, READY, RETIRED, ServingReplica

__all__ = ["FleetRouter"]


@dataclasses.dataclass
class _Placement:
    """Where one router-global request currently lives — enough to
    re-submit it verbatim if its replica turns suspect (greedy decode
    is deterministic, so a re-routed request regenerates identical
    tokens on the survivor)."""

    replica: ServingReplica
    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_id: Optional[int]
    arrival: Optional[float]
    deadline_s: Optional[float]
    #: trace context born at submit — a re-route must carry it so the
    #: survivor's spans still correlate with the fleet.route event
    trace_id: Optional[str] = None
    #: per-request speculative lookahead knob — re-routes carry it so a
    #: survivor decodes the request under the same k (greedy outputs
    #: are k-independent; the knob moves throughput/latency only)
    spec_k: Optional[int] = None
    rerouted: bool = False
    #: the emitted-token WATERMARK: tokens already generated before a
    #: migration, carried in the re-submitted prompt.  ``prompt`` stays
    #: the ORIGINAL client prompt for its whole life, so the collection
    #: pass prepends this prefix to the survivor's output exactly once
    #: — generated tokens are never emitted twice (docs/SERVING.md)
    prefix: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros((0,), np.int32))
    #: live hedged second dispatch, (replica, rid); first completion
    #: wins, the loser is cancelled
    hedge: Optional[Tuple[ServingReplica, int]] = None
    #: a hedge decision was already taken for this placement (issued OR
    #: suppressed) — each request is considered at most once
    hedged: bool = False
    #: router-clock stamp of the current dispatch (the hedge age base)
    placed_at: Optional[float] = None
    #: which tier the request currently lives on: ``"mixed"`` (the
    #: single-tier fleet), ``"prefill"`` (disagg, pre-handoff) or
    #: ``"decode"`` (disagg, post-handoff) — hedging and ejection
    #: survivor walks stay within the placement's tier
    tier: str = "mixed"

_ROUTE_AFFINITY = _instr.FLEET_ROUTED.labels("affinity")
_ROUTE_LEAST_QUEUE = _instr.FLEET_ROUTED.labels("least_queue")
_ROUTE_RR = _instr.FLEET_ROUTED.labels("round_robin")
_MIGRATE_WARM = _instr.SERVE_MIGRATIONS.labels("warm")
_MIGRATE_COLD = _instr.SERVE_MIGRATIONS.labels("cold")
_HEDGE_WON = _instr.SERVE_HEDGES.labels("won")
_HEDGE_LOST = _instr.SERVE_HEDGES.labels("lost")
_HEDGE_SUPPRESSED = _instr.SERVE_HEDGES.labels("suppressed")
_HANDOFF_WARM = _instr.SERVE_HANDOFFS.labels("warm")
_HANDOFF_COLD = _instr.SERVE_HANDOFFS.labels("cold")

#: prefill-tier replica count: > 0 turns disaggregation on (the
#: ``replicas`` argument then sizes the decode tier); 0 (default)
#: keeps the classic single-tier fleet (docs/FLEET.md).
ENV_PREFILL_REPLICAS = "HVD_TPU_FLEET_PREFILL_REPLICAS"


class FleetRouter:
    """Spread open-loop load across N serving replicas (module
    docstring).  ``build_engine`` constructs one fresh
    :class:`~horovod_tpu.serving.engine.ServingEngine` per replica
    (replicas must be homogeneous — same params, same menus — for
    placement-independent outputs)."""

    def __init__(self, build_engine: Callable[[], object], *,
                 replicas: int = 2, mode: str = "affinity",
                 policy: Optional[TargetTrackingPolicy] = None,
                 spares: int = 0, max_skew: int = 32,
                 ttft_window: int = 64,
                 prefill_replicas: Optional[int] = None,
                 decode_policy: Optional[TargetTrackingPolicy] = None,
                 clock=time.perf_counter):
        if mode not in ("affinity", "round_robin"):
            raise ValueError(f"unknown routing mode {mode!r}")
        if replicas < 1:
            raise ValueError(f"need >= 1 replica, got {replicas}")
        if prefill_replicas is None:
            prefill_replicas = env_int(ENV_PREFILL_REPLICAS, 0)
        if prefill_replicas < 0:
            raise ValueError(
                f"need >= 0 prefill replicas, got {prefill_replicas}")
        self._build = build_engine
        self.mode = mode
        self.policy = policy
        #: disaggregated two-tier fleet (module docstring): ``replicas``
        #: sizes the decode tier, ``prefill_replicas`` the prefill tier
        self.disagg = int(prefill_replicas) > 0
        #: decode-tier scale policy (tokens/s-per-replica floor); the
        #: generic ``policy`` drives the prefill tier in disagg mode
        self.decode_policy = decode_policy
        if self.disagg and self.decode_policy is None:
            self.decode_policy = decode_policy_from_env()
        #: cache affinity yields to load balance past this queue skew:
        #: when the cache-best replica's queue exceeds the fleet
        #: minimum by more than ``max_skew``, the request routes
        #: least-queue instead (and the new replica caches the
        #: template — load-driven cache replication, the RadixAttention
        #: balance rule)
        self.max_skew = int(max_skew)
        self._clock = clock
        self._next_name = 0
        self._rr = 0  # round-robin cursor
        self.replicas: List[ServingReplica] = []
        self.retired: List[ServingReplica] = []
        #: global id -> live placement record
        self._placed: Dict[int, _Placement] = {}
        self._next_gid = 0
        self.results: Dict[int, np.ndarray] = {}
        #: (arrival-ordered) sliding window of recent TTFTs — the
        #: policy's p99_ttft signal
        self._ttfts: collections.deque = collections.deque(
            maxlen=max(8, int(ttft_window)))
        self._ttft_seen: Dict[ServingReplica, int] = {}
        #: per-router placement counts (the metric counters aggregate
        #: across routers/legs; the bench wants per-leg numbers)
        self.route_counts = {"affinity": 0, "least_queue": 0,
                             "round_robin": 0}
        #: applied scale actions, in order: (direction, new_size) —
        #: disagg entries carry a third element, the resized tier
        self.scale_events: List[tuple] = []
        #: hedged dispatch (docs/SERVING.md fault tolerance): a request
        #: still waiting on its first token past the sliding p99 TTFT
        #: gets a second, identical dispatch; first completion wins
        self.hedge_enabled = bool(env_int("HVD_TPU_SERVE_HEDGE", 0))
        #: lifetime hedge allowance as a fraction of submitted requests
        #: — the retry budget that keeps hedging from amplifying an
        #: overload past the deadline-shedding bar
        self.hedge_budget = max(0.0, env_float(
            "HVD_TPU_SERVE_HEDGE_BUDGET", 0.1))
        self._submitted = 0
        self._hedges_issued = 0
        #: per-router hedge outcomes (the metric counters aggregate
        #: across routers; the bench wants per-leg numbers)
        self.hedges = {"won": 0, "lost": 0, "suppressed": 0}
        #: per-recovery records ({gid, path, ms}) — bench columns
        self.recovery: List[dict] = []
        #: tier-handoff outcome counts (disagg; bench columns)
        self.handoffs = {"warm": 0, "cold": 0}
        #: per-handoff records ({gid, path, ms, bytes, blocks}) — the
        #: bench's modeled==measured migrated-bytes evidence
        self.handoff_records: List[dict] = []
        #: kvsnap bytes that crossed a replica boundary warm (handoffs
        #: + loss migrations) — mirrors the registry counter per router
        self.migrated_bytes = 0
        #: EMA of handoff wall time — the two-hop deadline filter's
        #: middle term (prefill delay + THIS + decode delay)
        self._handoff_ema: Optional[float] = None
        self._decode_tokens = 0
        self._tok_rate_prev: Optional[Tuple[float, int]] = None
        if self.disagg:
            for _ in range(replicas):
                self._spawn_replica(tier="decode")
            for _ in range(int(prefill_replicas)):
                self._spawn_replica(tier="prefill")
        else:
            for _ in range(replicas):
                self._spawn_replica()
        # warm spares: spawned + fully compiled now (before traffic),
        # activated instantly at scale-out — building an engine
        # mid-traffic is seconds of XLA compile the SLO can't absorb
        # (disagg: spares join the decode tier — prefill scale-out is
        # the cheaper compile, its menu is the mixed chunk family only)
        for _ in range(max(0, int(spares))):
            self._spawn_replica(park=True,
                                tier="decode" if self.disagg else "mixed")
        if self.policy is not None:
            self.policy.min_size = max(1, self.policy.min_size)
        if self.decode_policy is not None:
            self.decode_policy.min_size = max(
                1, self.decode_policy.min_size)

    # -- replica lifecycle ---------------------------------------------------

    def _build_for(self, tier: str) -> Callable[[], object]:
        """The engine factory for one tier.  A prefill-tier engine must
        be built with ``role="prefill"`` BEFORE warmup (the role decides
        the program menu): a ``build_engine`` that takes a ``role``
        kwarg gets it passed; otherwise the built engine's role is
        flipped post-construction (warmup runs later, in
        :meth:`ServingReplica.spawn`, so the menu still comes out
        right) and its drafter dropped — speculation is a decode
        accelerator the prefill tier can never use."""
        if tier != "prefill":
            return self._build
        build = self._build
        try:
            params = inspect.signature(build).parameters.values()
            takes_role = any(
                p.name == "role"
                or p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params)
        except (TypeError, ValueError):
            takes_role = False
        if takes_role:
            return lambda: build(role="prefill")

        def build_prefill():
            eng = build()
            eng.role = "prefill"
            eng._drafter = None
            return eng
        return build_prefill

    def _spawn_replica(self, park: bool = False,
                       tier: str = "mixed") -> ServingReplica:
        # tier-prefixed names in disagg mode ("prefill0"/"decode1") so
        # logs, health sources and kvsnap source tags read at a glance
        name = f"{tier}{self._next_name}" if tier != "mixed" \
            else str(self._next_name)
        r = ServingReplica(name, self._build_for(tier), tier=tier,
                           clock=self._clock)
        self._next_name += 1
        r.spawn(park=park)
        self.replicas.append(r)
        self._ttft_seen[r] = 0
        self._book_replica_gauges()
        return r

    def _book_replica_gauges(self) -> None:
        for state in (READY, DRAINING, PARKED):
            _instr.FLEET_REPLICAS.labels(state).set(
                sum(1 for r in self.replicas if r.state == state))

    def _accepting(self, tier: Optional[str] = None
                   ) -> List[ServingReplica]:
        return [r for r in self.replicas if r.accepting
                and (tier is None or r.tier == tier)]

    @property
    def size(self) -> int:
        """Accepting replicas — what the policy scales."""
        return len(self._accepting())

    def tier_size(self, tier: str) -> int:
        """Accepting replicas of one tier (the per-tier policies'
        ``current`` in disagg mode)."""
        return len(self._accepting(tier))

    def scale_to(self, n: int, tier: Optional[str] = None) -> bool:
        """Converge the accepting-replica count to ``n``: unpark warm
        spares (instant) or spawn+warm new replicas to grow, drain the
        least-loaded (retired once empty, by :meth:`step`) to shrink.
        ``tier`` scopes the resize to one tier of a disaggregated
        fleet (spares only unpark into their own tier — a parked
        decode engine has the wrong menu for prefill duty).  Returns
        True when the resize was applied."""
        n = max(1, int(n))
        acc = self._accepting(tier)
        if n > len(acc):
            for _ in range(n - len(acc)):
                spare = next((r for r in self.replicas
                              if r.state == PARKED
                              and (tier is None or r.tier == tier)),
                             None)
                if spare is not None:
                    spare.unpark()
                else:
                    self._spawn_replica(tier=tier or "mixed")
            self._book_replica_gauges()
            return True
        while len(acc) > n and len(acc) > 1:
            victim = min(acc, key=lambda r: (r.queue_depth(),
                                             len(r.engine.scheduler.running)))
            get_logger().info(
                "fleet: draining replica %s (queue %d)", victim.name,
                victim.queue_depth())
            victim.drain()
            acc = self._accepting(tier)
        self._book_replica_gauges()
        return True

    # -- placement -----------------------------------------------------------

    def _two_hop_overhead(self) -> float:
        """Estimated seconds a disaggregated request spends AFTER its
        prefill replica's queue: handoff (EMA) + the best decode-tier
        queue delay.  The deadline filter must charge the full two-hop
        path — judging a prefill replica by its own queue alone admits
        requests whose budget the handoff + decode hop then eats
        (the satellite-2 fix; 0.0 for a single-tier fleet)."""
        if not self.disagg:
            return 0.0
        dq = min((x.est_queue_delay()
                  for x in self._accepting("decode")), default=0.0)
        return (self._handoff_ema or 0.0) + dq

    def _route(self, prompt: np.ndarray,
               remaining_budget: Optional[float] = None,
               exclude: Tuple[ServingReplica, ...] = (),
               tier: Optional[str] = None,
               extra_delay: float = 0.0) -> ServingReplica:
        acc = [r for r in self._accepting(tier) if r not in exclude]
        if not acc:
            raise RuntimeError("no accepting replicas")
        if self.mode == "round_robin":
            r = acc[self._rr % len(acc)]
            self._rr += 1
            _ROUTE_RR.inc()
            self.route_counts["round_robin"] += 1
            return r
        if remaining_budget is not None:
            # deadline-aware placement: a replica whose estimated queue
            # delay already exceeds the request's remaining budget
            # would only produce a shed — skip it while ANY viable
            # replica exists (all over budget: route normally and let
            # the engine's own deadline machinery shed honestly).
            # ``extra_delay`` charges the hops PAST this replica (the
            # two-hop handoff + decode delay in a disaggregated fleet)
            viable = [r for r in acc
                      if r.est_queue_delay() + extra_delay
                      <= remaining_budget]
            if viable:
                acc = viable
        scores = [(r.cached_prefix_blocks(prompt), r) for r in acc]
        best_score = max(s for s, _ in scores)
        if best_score > 0:
            # ties (same cached span on several replicas) break toward
            # the shorter queue — affinity must not defeat balance
            r = min((r for s, r in scores if s == best_score),
                    key=lambda r: r.queue_depth())
            # the balance escape: a cache hit is worth a bounded queue
            # penalty, not an unbounded one — past max_skew the
            # request routes least-queue and the template replicates
            # onto the cooler replica (load-driven cache replication)
            if r.queue_depth() - min(x.queue_depth() for x in acc) \
                    <= self.max_skew:
                _ROUTE_AFFINITY.inc()
                self.route_counts["affinity"] += 1
                return r
        r = min(acc, key=lambda r: r.queue_depth())
        _ROUTE_LEAST_QUEUE.inc()
        self.route_counts["least_queue"] += 1
        return r

    def submit(self, prompt, max_new_tokens: int, *, eos_id=None,
               arrival: Optional[float] = None,
               deadline_s: Optional[float] = None,
               spec_k: Optional[int] = None) -> int:
        """Place one request; returns a router-global id (key into
        :attr:`results`).  A replica whose ``submit`` raises books an
        error (SUSPECT + ejection at ``HVD_TPU_FLEET_REPLICA_ERRORS``
        consecutive) and THIS request retries on the next-best
        survivor — a raising replica can no longer keep winning
        affinity for its cached templates.  ``spec_k`` is the
        per-request speculative-lookahead knob, forwarded to whichever
        replica wins placement (and to any later re-route)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        remaining = None
        if deadline_s and deadline_s > 0:
            now = self._clock()
            arr = now if arrival is None else arrival
            remaining = max(0.0, deadline_s - (now - arr))
        # trace context is born HERE and propagates router -> replica
        # -> engine -> scheduler: every span the request touches
        # downstream carries this id (docs/TRACING.md)
        tid = _trace.new_trace_id() if _trace.enabled() else None
        # disagg: a fresh request always enters through the prefill
        # tier, and its viability filter charges the whole two-hop path
        tier = "prefill" if self.disagg else None
        extra = self._two_hop_overhead()
        tried: List[ServingReplica] = []
        for _ in range(len(self.replicas) + 1):
            r = self._route(prompt, remaining, exclude=tuple(tried),
                            tier=tier, extra_delay=extra)
            try:
                rid = r.submit(prompt, max_new_tokens, eos_id=eos_id,
                               arrival=arrival, deadline_s=deadline_s,
                               trace_id=tid, spec_k=spec_k)
                r.note_ok()
            except ValueError:
                # client-input validation (over-long prompt, zero
                # max_new_tokens): the CALLER's error, identical on
                # every replica — booking it as replica health would
                # let a few bad requests eject the whole fleet
                raise
            except Exception as e:
                get_logger().warning(
                    "fleet: replica %s submit raised (%s: %s)",
                    r.name, type(e).__name__, e)
                if r.note_error():
                    self._eject(r)
                tried.append(r)
                continue
            gid = self._next_gid
            self._next_gid += 1
            self._submitted += 1
            self._placed[gid] = _Placement(
                replica=r, rid=rid, prompt=prompt,
                max_new_tokens=int(max_new_tokens), eos_id=eos_id,
                arrival=arrival, deadline_s=deadline_s, trace_id=tid,
                spec_k=spec_k, placed_at=self._clock(),
                tier=tier or "mixed")
            _trace.event("fleet.route", gid=gid, rid=rid,
                         replica=r.name, mode=self.mode, trace=tid)
            return gid
        raise RuntimeError("no replica accepted the request")

    # -- driving -------------------------------------------------------------

    def step(self) -> bool:
        """One pass: step every replica that has work, collect
        completions and TTFT samples, eject suspects (consecutive step
        errors or a healthz stall trip), retire drained replicas, tick
        the scale policy.  Returns True while anything is in flight."""
        busy = False
        for r in list(self.replicas):
            if r.state == RETIRED or r.engine is None:
                continue
            r.queue_depth()  # sample: keeps peak_queue_depth honest
            # in every routing mode, not just where routing reads it
            if r.has_work:
                busy = True
                try:
                    r.step()
                    r.note_ok()
                except Exception as e:
                    get_logger().warning(
                        "fleet: replica %s step raised (%s: %s)",
                        r.name, type(e).__name__, e)
                    if r.note_error():
                        self._eject(r)
                        continue
            # the healthz stall source (has-work-but-no-progress) feeds
            # the same consecutive-error counter as submit/step raises
            if not r.suspect and r.state in (READY, DRAINING) \
                    and not r.healthy():
                if r.note_error():
                    self._eject(r)
                    continue
            self._collect(r)
            if r.state == DRAINING and r.drained:
                r.retire()
                self.replicas.remove(r)
                self.retired.append(r)
                self._book_replica_gauges()
        if self.disagg:
            # AFTER the per-replica pass: every prefill replica that
            # crossed the handoff boundary this step has parked its
            # snapshots by now; a handoff is only parked by a replica
            # that stepped (busy=True), so run_until_drained cannot
            # exit with one pending.  DRAINING prefill replicas hold
            # their engines until this pass empties them (the
            # handoff-aware ``drained`` gate).
            self._collect_handoffs()
        if self.hedge_enabled:
            self._maybe_hedge()
        if self.policy is not None or self.decode_policy is not None:
            self._maybe_scale()
        return busy

    def _first_token_at(self, p: _Placement) -> Optional[float]:
        """The placement's first-token timestamp on its primary, or
        None while it is still in prefill (the hedgeable phase)."""
        eng = p.replica.engine
        if eng is None:
            return None
        for seq in eng.scheduler.running:
            if seq.req.id == p.rid:
                return seq.first_token_at
        return None

    def _maybe_hedge(self) -> None:
        """Hedged dispatch (``HVD_TPU_SERVE_HEDGE``): a request still
        waiting on its FIRST token past the sliding-window p99 TTFT
        gets one identical second dispatch on the least-queue other
        replica; whichever completes first wins and the loser is
        cancelled (:meth:`_collect`).  Only prefill-phase requests
        hedge — a decoding request's progress would be thrown away,
        and decode stragglers are the ejection path's job.  The
        ``HVD_TPU_SERVE_HEDGE_BUDGET`` fraction bounds total hedges so
        tail-chasing cannot amplify an overload (The Tail at Scale)."""
        if len(self._ttfts) < 16:
            return  # no stable delay estimate yet
        xs = sorted(self._ttfts)
        delay = xs[min(len(xs) - 1, int(0.99 * len(xs)))]
        now = self._clock()
        for gid, p in list(self._placed.items()):
            if p.hedged or p.rerouted or p.placed_at is None:
                continue
            if now - p.placed_at <= delay:
                continue
            if self._first_token_at(p) is not None:
                p.hedged = True  # decoding: past the hedgeable phase
                continue
            if self._hedges_issued + 1 > self.hedge_budget * max(
                    1, self._submitted):
                p.hedged = True
                self.hedges["suppressed"] += 1
                _HEDGE_SUPPRESSED.inc()
                continue
            # tier-matched: a hedge is an identical dispatch, and only
            # the placement's own tier has the menu to serve it (in a
            # single-tier fleet every replica is "mixed", so this is
            # the old all-replicas set)
            others = [x for x in self._accepting(p.tier)
                      if x is not p.replica]
            tgt = min(others, key=lambda x: x.queue_depth(),
                      default=None)
            if tgt is None or tgt.est_queue_delay() > delay:
                # no survivor could plausibly beat the primary —
                # a hedge would only add load
                p.hedged = True
                self.hedges["suppressed"] += 1
                _HEDGE_SUPPRESSED.inc()
                continue
            try:
                hrid = tgt.submit(
                    np.concatenate([p.prompt, p.prefix])
                    if p.prefix.size else p.prompt,
                    p.max_new_tokens - int(p.prefix.size),
                    eos_id=p.eos_id, arrival=p.arrival,
                    deadline_s=p.deadline_s, trace_id=p.trace_id,
                    spec_k=p.spec_k)
                tgt.note_ok()
            except Exception as e:
                get_logger().warning(
                    "fleet: hedge to replica %s raised (%s: %s)",
                    tgt.name, type(e).__name__, e)
                tgt.note_error()
                p.hedged = True
                continue
            p.hedged = True
            p.hedge = (tgt, hrid)
            self._hedges_issued += 1
            _trace.event("serve.hedge", gid=gid,
                         primary=p.replica.name, hedge=tgt.name,
                         delay=delay, trace=p.trace_id)

    # -- the tier boundary (disagg): prefill -> decode handoff ---------------

    def _collect_handoffs(self) -> None:
        """Drain every prefill replica's parked handoffs (requests
        whose prefill just completed) into the decode tier."""
        for r in list(self.replicas):
            if r.tier != "prefill" or r.engine is None:
                continue
            pending = getattr(r.engine, "handoffs", None)
            if not pending:
                continue
            for rid in list(pending):
                stream, snap, arr = pending.pop(rid)
                self._dispatch_handoff(r, rid, stream, snap, arr)

    def _dispatch_handoff(self, src: ServingReplica, rid: int,
                          stream, snap: Optional[dict],
                          arr: Optional[float]) -> None:
        """Move ONE prefill-complete request across the tier boundary:
        its ``kvsnap/1`` block chain crosses the ``serve.handoff``
        chaos point and re-registers on a decode replica
        (:meth:`ServingEngine.import_kv` — **warm**: the re-submitted
        request re-prefixes the whole prompt + first token from cache,
        zero prefill recompute on the decode tier); a dropped or
        corrupted wire degrades to **cold** (the decode replica
        re-prefills — deterministic, never wrong, exactly the PR-18
        migration contract).  The first token the prefill tier emitted
        becomes the placement's watermark, so collection prepends it
        exactly once and TTFT stays a prefill-tier measurement."""
        gid = p = None
        via_hedge = False
        for g, cand in self._placed.items():
            if cand.replica is src and cand.rid == rid:
                gid, p = g, cand
                break
            if cand.hedge is not None and cand.hedge[0] is src \
                    and cand.hedge[1] == rid:
                gid, p, via_hedge = g, cand, True
                break
        if p is None:
            return  # cancelled / already resolved elsewhere
        t0 = self._clock()
        # hedged prefill resolves FIRST-HANDOFF-WINS: both dispatches
        # of a hedged pair prefill independently and each would park a
        # handoff — the first one collected carries the request across,
        # the loser cancels AND its (possibly already-parked) handoff
        # is discarded so the request cannot cross the boundary twice
        if via_hedge:
            loser, lrid = p.replica, p.rid
            p.replica, p.rid = src, rid
            p.hedge = None
            if loser.engine is not None:
                loser.engine.cancel(lrid)
                getattr(loser.engine, "handoffs", {}).pop(lrid, None)
            self.hedges["won"] += 1
            _HEDGE_WON.inc()
        elif p.hedge is not None:
            loser, lrid = p.hedge
            p.hedge = None
            if loser.engine is not None:
                loser.engine.cancel(lrid)
                getattr(loser.engine, "handoffs", {}).pop(lrid, None)
            self.hedges["lost"] += 1
            _HEDGE_LOST.inc()
        # the engine request's prompt is p.prompt (+ any earlier
        # migration watermark), so slicing past the ORIGINAL prompt
        # recovers the full generated run — the _eject idiom
        gen = np.asarray(stream[len(p.prompt):], np.int32)
        if p.eos_id is not None and gen.size:
            hits = np.flatnonzero(gen == p.eos_id)
            if hits.size:
                gen = gen[:int(hits[0]) + 1]
        remaining = p.max_new_tokens - int(gen.size)
        if remaining < 1 or (p.eos_id is not None and gen.size
                             and gen[-1] == p.eos_id):
            # done AT the boundary (eos or budget on the first token):
            # no decode tier needed
            self.results[gid] = gen
            del self._placed[gid]
            return
        wire_snap = None
        if snap is not None:
            wire = np.asarray(snap["tokens"], np.int32).tobytes()
            out = _chaos.point("serve.handoff", wire)
            if out is not _chaos.DROP:
                wire_snap = dict(snap)
                wire_snap["tokens"] = np.frombuffer(out, np.int32)
        remaining_budget = None
        if p.deadline_s and p.deadline_s > 0:
            base = arr if arr is not None else (
                p.arrival if p.arrival is not None else t0)
            remaining_budget = max(0.0, p.deadline_s - (t0 - base))
        full = np.concatenate([p.prompt, gen]) if gen.size else p.prompt
        placed = None
        path = "cold"
        nbytes = 0
        tried: List[ServingReplica] = []
        for _ in range(len(self._accepting("decode")) + 1):
            try:
                tgt = self._route(full, remaining_budget,
                                  exclude=tuple(tried), tier="decode")
            except RuntimeError:
                break  # decode tier empty / exhausted
            try:
                path = "cold"
                if wire_snap is not None:
                    try:
                        tgt.engine.import_kv(wire_snap)
                        path = "warm"
                        nbytes = measured_kvsnap_bytes(wire_snap)
                    except ValueError as e:
                        get_logger().warning(
                            "fleet: handoff snapshot rejected for gid "
                            "%d (%s) — cold re-prefill", gid, e)
                        wire_snap = None  # bad wire: don't retry it
                nrid = tgt.submit(
                    full, int(remaining), eos_id=p.eos_id,
                    arrival=arr if arr is not None else p.arrival,
                    deadline_s=p.deadline_s, trace_id=p.trace_id,
                    spec_k=p.spec_k)
                tgt.note_ok()
                placed = (tgt, nrid)
                break
            except Exception as e:
                get_logger().warning(
                    "fleet: handoff to replica %s raised (%s: %s)",
                    tgt.name, type(e).__name__, e)
                if tgt.note_error():
                    self._eject(tgt)
                tried.append(tgt)
        if placed is None:
            # no decode replica accepted: complete with the watermark
            # (the boundary token) rather than wedge the request
            self.results[gid] = gen
            del self._placed[gid]
            return
        p.replica, p.rid = placed
        p.tier = "decode"
        p.prefix = gen
        p.placed_at = self._clock()
        p.hedged = True  # past the hedgeable (prefill) phase
        if placed[0].engine is not None:
            placed[0].engine.scheduler.resort_pending_by_arrival()
        dt = self._clock() - t0
        self._handoff_ema = dt if self._handoff_ema is None else (
            0.8 * self._handoff_ema + 0.2 * dt)
        self.handoffs[path] += 1
        (_HANDOFF_WARM if path == "warm" else _HANDOFF_COLD).inc()
        _instr.SERVE_HANDOFF_SECONDS.observe(dt)
        if path == "warm" and nbytes:
            _instr.SERVE_MIGRATED_BYTES.inc(nbytes)
            self.migrated_bytes += nbytes
        self.handoff_records.append({
            "gid": gid, "path": path, "ms": dt * 1e3, "bytes": nbytes,
            "blocks": len(snap["hashes"]) if snap else 0})
        _trace.add_span("serve.handoff", t0, self._clock(), gid=gid,
                        src=src.name, dst=placed[0].name, path=path,
                        bytes=nbytes, carried=int(gen.size),
                        trace=p.trace_id)

    def _eject(self, r: ServingReplica) -> None:
        """A replica turned SUSPECT: collect what it already finished,
        migrate its remaining work ONCE to survivors (a request whose
        survivor also fails completes with what it has rather than
        ping-ponging), release its scheduler bookkeeping (blocks free
        through the normal refcount path) and drain-retire it.

        Recovery is loss-free and token-identical (docs/SERVING.md):

        * the dying engine is asked to **export** its in-flight
          requests (tokens generated so far + a KV block snapshot);
          if it can't answer, the replica's last periodic
          ``kv_snapshots`` (``HVD_TPU_SERVE_SNAPSHOT_STEPS``) stand in;
        * **warm path** — the snapshot re-registers on the survivor
          (``import_kv``) so the re-submitted request re-prefixes from
          cache and pays no prefill recompute.  The snapshot crosses a
          ``serve.migrate`` chaos point; a corrupted wire FAILS the
          chain-hash verification and degrades to the cold path —
          never into wrong tokens;
        * **cold path** — re-submit ``prompt + generated-so-far``
          (greedy decode is deterministic, so the survivor regenerates
          the identical continuation);
        * generated tokens are never emitted twice: the already-
          generated prefix moves to ``p.prefix`` and the collection
          pass prepends it exactly once.

        A survivor crossing its own error threshold DURING the
        re-route is ejected afterwards (bounded: each ejection removes
        a replica).  A replica already DRAINING voluntarily
        (scale-down) that then stalls still gets the full ejection —
        the guard is the ``ejected`` flag, not the lifecycle state."""
        if r.ejected or r.state == RETIRED:
            return
        r.ejected = True
        t0 = self._clock()
        self._collect(r)
        # black box FIRST: the bundle must show the dying replica's
        # final spans, not the recovery's
        _flight.maybe_dump("replica_loss", extra={"replica": r.name})
        # a dying prefill replica's parked handoffs dispatch to the
        # decode tier NOW (their prefill work is done and exported —
        # losing it to the cancel_all below would waste it); the moved
        # placements then read ``p.replica is not r`` and skip the
        # migration loop.  Only the VICTIM's handoffs: a full
        # _collect_handoffs here could recurse through a decode
        # ejection back into this frame.
        if self.disagg and r.engine is not None:
            for hrid in list(getattr(r.engine, "handoffs", None) or ()):
                h_stream, h_snap, h_arr = r.engine.handoffs.pop(hrid)
                self._dispatch_handoff(r, hrid, h_stream, h_snap, h_arr)
        # freshest stream state wins: a live (merely suspect) engine
        # exports right now; a truly dead one falls back to its last
        # periodic snapshot
        handoff: Dict[int, tuple] = {}
        if r.engine is not None:
            try:
                handoff = r.engine.export_requests()
            except Exception as e:
                get_logger().warning(
                    "fleet: replica %s export failed (%s: %s) — "
                    "using last periodic snapshot", r.name,
                    type(e).__name__, e)
        if not handoff:
            handoff = dict(r.kv_snapshots)
        # disagg: survivors stay within the victim's tier — a decode
        # request re-routed onto a prefill engine would find no decode
        # programs.  The one safe crossing is prefill -> decode (a
        # "both"-role menu is a superset), taken only when the prefill
        # tier has no survivor left.
        if self.disagg:
            survivors = [x for x in self._accepting(r.tier) if x is not r]
            if not survivors and r.tier == "prefill":
                survivors = [x for x in self._accepting("decode")
                             if x is not r]
        else:
            survivors = [x for x in self._accepting() if x is not r]
        touched: List[ServingReplica] = []
        moved = dropped = 0
        for gid, p in list(self._placed.items()):
            if p.replica is not r:
                # a hedge living on the dying replica is simply lost
                if p.hedge is not None and p.hedge[0] is r:
                    p.hedge = None
                continue
            # first-wins promotion: if the primary dies while a live
            # hedge already carries this request elsewhere, the hedge
            # BECOMES the placement — no re-dispatch needed
            if p.hedge is not None and p.hedge[0] is not r \
                    and p.hedge[0].engine is not None:
                p.replica, p.rid = p.hedge
                p.hedge = None
                p.rerouted = True
                moved += 1
                continue
            p.hedge = None
            tokens, snap, arr = handoff.get(p.rid, (None, None, None))
            if tokens is not None:
                # the exported stream is context+generated of the
                # CURRENT engine request, whose prompt already includes
                # any earlier migration prefix — slicing past the
                # ORIGINAL prompt therefore recovers the FULL generated
                # run; never concat p.prefix on top of it
                gen = np.asarray(tokens[len(p.prompt):], np.int32)
            else:
                gen = p.prefix
            if p.rerouted:
                # one-reroute bound: a twice-unlucky request completes
                # with its watermark instead of ping-ponging
                self.results[gid] = gen
                del self._placed[gid]
                dropped += 1
                continue
            if p.eos_id is not None and gen.size:
                hits = np.flatnonzero(gen == p.eos_id)
                if hits.size:
                    gen = gen[:int(hits[0]) + 1]
            remaining = p.max_new_tokens - int(gen.size)
            if remaining < 1 or (p.eos_id is not None and gen.size
                                 and gen[-1] == p.eos_id):
                # already done — the kill landed between the last
                # token and collection
                self.results[gid] = gen
                del self._placed[gid]
                continue
            # warm-path wire: the snapshot's token stream crosses the
            # serve.migrate chaos point as bytes (drop => cold path;
            # corruption => chain-hash mismatch on import => cold path)
            wire_snap = None
            if snap is not None and survivors:
                wire = np.asarray(snap["tokens"], np.int32).tobytes()
                out = _chaos.point("serve.migrate", wire)
                if out is not _chaos.DROP:
                    wire_snap = dict(snap)
                    wire_snap["tokens"] = np.frombuffer(out, np.int32)
            placed = None
            path = "cold"
            # walk EVERY accepting survivor least-queue-first: one
            # survivor flaking must not drop a request another could
            # serve — and its flake books toward its own suspect
            # counter like any other submit error
            for tgt in sorted(survivors, key=lambda x: x.queue_depth()):
                if not tgt.accepting:
                    continue
                try:
                    path = "cold"
                    if wire_snap is not None:
                        try:
                            tgt.engine.import_kv(wire_snap)
                            path = "warm"
                            nb = measured_kvsnap_bytes(wire_snap)
                            _instr.SERVE_MIGRATED_BYTES.inc(nb)
                            self.migrated_bytes += nb
                        except ValueError as e:
                            get_logger().warning(
                                "fleet: KV snapshot rejected for gid "
                                "%d (%s) — cold re-prefill", gid, e)
                            wire_snap = None  # bad wire: don't retry it
                    nrid = tgt.submit(
                        np.concatenate([p.prompt, gen])
                        if gen.size else p.prompt,
                        int(remaining), eos_id=p.eos_id,
                        arrival=arr if arr is not None else p.arrival,
                        deadline_s=p.deadline_s,
                        trace_id=p.trace_id, spec_k=p.spec_k)
                    tgt.note_ok()
                    placed = (tgt, nrid)
                    break
                except Exception as e:
                    get_logger().warning(
                        "fleet: re-route to replica %s raised "
                        "(%s: %s)", tgt.name, type(e).__name__, e)
                    tgt.note_error()
            if placed is None:
                self.results[gid] = gen
                del self._placed[gid]
                dropped += 1
                continue
            p.replica, p.rid = placed
            p.tier = placed[0].tier  # prefill->decode fallback crossing
            p.rerouted = True
            p.prefix = gen
            p.placed_at = self._clock()
            moved += 1
            if placed[0] not in touched:
                touched.append(placed[0])
            (_MIGRATE_WARM if path == "warm" else _MIGRATE_COLD).inc()
            dt = self._clock() - t0
            _instr.SERVE_RECOVERY_SECONDS.observe(dt)
            self.recovery.append({"gid": gid, "path": path,
                                  "ms": dt * 1e3})
            _trace.event("serve.migrate", gid=gid, src=r.name,
                         dst=placed[0].name, path=path,
                         carried=int(gen.size), trace=p.trace_id)
        if r.engine is not None:
            # abort everything the engine still holds (blocks release
            # through the normal refcount path; partial results publish
            # so engine-sourced requests — which the router never
            # placed and cannot re-route — complete empty instead of
            # leaving their pollers waiting forever)
            r.engine.cancel_all()
        # arrival-order fairness: migrated requests joined the
        # survivors' pending queues at the tail — re-sort by original
        # arrival so ejection doesn't reorder admission
        for tgt in touched:
            if tgt.engine is not None:
                tgt.engine.scheduler.resort_pending_by_arrival()
        get_logger().error(
            "fleet: ejected suspect replica %s (%d request(s) "
            "re-routed, %d dropped)", r.name, moved, dropped)
        r.drain()
        self._book_replica_gauges()
        for tgt in survivors:
            if tgt.suspect:
                self._eject(tgt)

    def run_until_drained(self) -> Dict[int, np.ndarray]:
        while self.step():
            pass
        return self.results

    def _collect(self, r: ServingReplica) -> None:
        # disagg: only prefill-tier first tokens feed the TTFT window —
        # a decode replica's "first token" is the handed-off request's
        # first DECODE emission, stamped from the original arrival; it
        # measures the whole two-hop path and would poison the hedging
        # delay estimate and the prefill tier's p99_ttft signal
        if not self.disagg or r.tier == "prefill":
            for _rid, ttft in r.ttft_samples()[
                    self._ttft_seen.get(r, 0):]:
                self._ttfts.append(ttft)
                self._ttft_seen[r] = self._ttft_seen.get(r, 0) + 1
        if r.engine is None:
            return
        # map replica-local completions back to router-global ids;
        # hedged placements resolve FIRST-WINS (the loser cancels, its
        # blocks free through the normal refcount path)
        for gid, p in list(self._placed.items()):
            primary_done = p.replica is r and p.rid in r.engine.results
            hedge_done = (p.hedge is not None and p.hedge[0] is r
                          and p.hedge[0].engine is not None
                          and p.hedge[1] in p.hedge[0].engine.results)
            if not primary_done and not hedge_done:
                continue
            if primary_done:
                res = r.engine.results[p.rid]
                if p.hedge is not None:
                    loser, lrid = p.hedge
                    if loser.engine is not None:
                        loser.engine.cancel(lrid)
                    self.hedges["lost"] += 1
                    _HEDGE_LOST.inc()
            else:
                res = p.hedge[0].engine.results[p.hedge[1]]
                if p.replica.engine is not None:
                    p.replica.engine.cancel(p.rid)
                self.hedges["won"] += 1
                _HEDGE_WON.inc()
            # prepend the pre-migration watermark exactly once
            res = np.asarray(res, np.int32)
            if self.disagg and r.tier == "decode":
                # tokens this decode replica generated (the watermark
                # came from the prefill tier) — the decode tier's
                # tokens/s throughput-floor numerator
                self._decode_tokens += int(res.size)
            self.results[gid] = (np.concatenate([p.prefix, res])
                                 if p.prefix.size else res)
            del self._placed[gid]

    # -- SLO signals + scaling ----------------------------------------------

    def signals(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        acc = self._accepting()
        if acc:
            out["queue_depth"] = sum(
                r.queue_depth() for r in acc) / len(acc)
        if self._ttfts:
            xs = sorted(self._ttfts)
            # exact small-window p99 (the registry histograms stay the
            # durable record; the policy wants the recent window)
            idx = min(len(xs) - 1, int(0.99 * len(xs)))
            out["p99_ttft"] = xs[idx]
            _instr.FLEET_ROUTER_P99_TTFT.set(out["p99_ttft"])
        if self.disagg:
            # decode tokens/s per accepting decode replica, rated
            # between signal reads — the decode tier's throughput
            # floor (the first read only pins the baseline)
            now = self._clock()
            if self._tok_rate_prev is not None:
                t_prev, n_prev = self._tok_rate_prev
                dt = now - t_prev
                if dt > 0:
                    out["decode_tokens_per_s"] = (
                        (self._decode_tokens - n_prev) / dt
                        / max(1, len(self._accepting("decode"))))
            self._tok_rate_prev = (now, self._decode_tokens)
        return out

    def _maybe_scale(self) -> None:
        sig = self.signals()
        now = self._clock()
        if not self.disagg:
            if self.policy is None:
                return
            d = self.policy.evaluate(sig, self.size, now)
            _instr.FLEET_DESIRED_SIZE.labels("serve").set(d.desired)
            if d.direction != "hold" and d.desired != self.size:
                get_logger().info(
                    "fleet: serve scale %s %d -> %d (%s)",
                    d.direction, self.size, d.desired, d.reason)
                if self.scale_to(d.desired):
                    _instr.FLEET_SCALE_EVENTS.labels(
                        "serve", d.direction).inc()
                    self.scale_events.append((d.direction, d.desired))
                    self.policy.note_applied(now)
            return
        # disagg: each tier scales on its own signal — TTFT is decided
        # entirely before the handoff (prefill capacity), decode
        # tokens/s entirely after it (decode capacity); scale_events
        # entries grow a tier field so the bench can tell them apart
        for pol, tier, kind in ((self.policy, "prefill",
                                 "serve_prefill"),
                                (self.decode_policy, "decode",
                                 "serve_decode")):
            if pol is None:
                continue
            cur = self.tier_size(tier)
            d = pol.evaluate(sig, cur, now)
            _instr.FLEET_DESIRED_SIZE.labels(kind).set(d.desired)
            if d.direction != "hold" and d.desired != cur:
                get_logger().info(
                    "fleet: %s tier scale %s %d -> %d (%s)", tier,
                    d.direction, cur, d.desired, d.reason)
                if self.scale_to(d.desired, tier=tier):
                    _instr.FLEET_SCALE_EVENTS.labels(
                        kind, d.direction).inc()
                    self.scale_events.append(
                        (d.direction, d.desired, tier))
                    pol.note_applied(now)

    # -- bench/introspection columns -----------------------------------------

    def prefix_stats(self) -> Tuple[int, int]:
        """(hit blocks, lookup blocks) aggregated over every replica,
        live and retired — the fleet-wide hit rate numerator and
        denominator."""
        hits = lookups = 0
        for r in self.replicas + self.retired:
            sched = getattr(r.engine, "scheduler", None) \
                if r.engine is not None else None
            if sched is not None:
                hits += sched.prefix_hit_blocks
                lookups += sched.prefix_lookup_blocks
            else:  # retired replicas keep their final counts
                hits += getattr(r, "_final_hits", 0)
                lookups += getattr(r, "_final_lookups", 0)
        return hits, lookups

    def all_ttfts(self) -> List[float]:
        """Every TTFT sample across live AND retired replicas — the
        bench's full-leg distribution (the policy's sliding window is
        deliberately smaller)."""
        out: List[float] = []
        for r in self.replicas + self.retired:
            out.extend(t for _rid, t in r.ttft_samples())
        return out

    def all_compile_free(self) -> bool:
        return all(r.compile_free for r in self.replicas) and all(
            getattr(r, "_final_compile_free", True) for r in self.retired)

    def hedge_rate(self) -> float:
        """Hedges issued per submitted request (bench column; the
        budget bounds it at ``hedge_budget``)."""
        return self._hedges_issued / max(1, self._submitted)

    def migration_ms(self) -> float:
        """Mean detection-to-re-dispatch latency over this router's
        recoveries, in milliseconds (0.0 when none happened)."""
        if not self.recovery:
            return 0.0
        return sum(x["ms"] for x in self.recovery) / len(self.recovery)
