"""Preemption notices as a first-class, chaos-drillable event.

Cloud TPU/VM preemption arrives as SIGTERM with a short grace window;
upstream Horovod (and PRs 1-3 here) only ever saw the aftermath — the
process dies, the driver blacklists the slot, recovery re-prefills
from the last commit.  A *notice*, handled, is strictly better: the
worker gets to take a PLANNED snapshot of its live progress and leave
cleanly, so nothing is lost and the driver books a scale-down instead
of a failure.

:class:`PreemptionGuard` implements the notice path (docs/FLEET.md):

1. **SIGTERM** (or the chaos drill below) starts the leave;
2. **report**: the driver is told ``leaving`` over the PR-3
   notification connection FIRST, so the vacating worker's clean exit
   is booked as a planned departure (``_Worker.leaving``), its slot is
   held against an immediate refill, and the survivors get a planned
   (failure=False) reset epoch;
3. **planned snapshot**: a bounded live snapshot
   (``HVD_TPU_ELASTIC_PLANNED_SNAPSHOT_SECONDS`` budget, the same
   machinery the PR-3 watchdog uses) falls back to the last commit if
   the main thread is wedged; when checkpoint auto-resume is armed the
   snapshot is ALSO published as a ``ckpt-<step>`` state checkpoint —
   from any rank — so a replacement worker elsewhere resumes the
   preempted worker's progress, not just rank 0's;
4. **leave**: ``hvd_tpu_recovery_seconds{phase="planned"}`` records
   the notice-to-exit wall time, then the process exits 0.

The chaos site ``fleet.preempt`` makes the whole path drillable: the
guard's poll thread evaluates it every ``HVD_TPU_FLEET_PREEMPT_POLL``
seconds (the metadata-server poll shape real clouds have), and a
``kill`` rule with a NEGATIVE ``code`` delivers that signal to the
process instead of exiting — ``fleet.preempt:kill,code=-15,at=4`` is
a SIGTERM preemption notice on the 4th poll, grace path and all
(docs/FAULT_TOLERANCE.md).  ``kill`` with the default positive code
stays a hard preemption: the grace window expiring before the
snapshot finishes is also a case worth drilling.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
from typing import Callable, Optional

from .. import chaos
from ..common.retry import env_float
from ..metrics import instruments as _instr
from ..utils.logging import get_logger

__all__ = ["PreemptionGuard"]

ENV_POLL = "HVD_TPU_FLEET_PREEMPT_POLL"


class PreemptionGuard:
    """Install with the job's elastic state to honor preemption
    notices with a planned snapshot + clean leave (module docstring).

    ``on_leave`` (optional) receives ``{"step", "planned_s",
    "snapshot"}`` just before the process exits — soak harnesses log
    it; production leaves it None."""

    def __init__(self, state, *,
                 on_leave: Optional[Callable[[dict], None]] = None,
                 poll_s: Optional[float] = None,
                 clock=time.time):
        self.state = state
        self.on_leave = on_leave
        self.poll_s = (env_float(ENV_POLL, 0.5)
                       if poll_s is None else float(poll_s))
        self._clock = clock
        self._leaving = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._prev_handler = None

    def install(self) -> "PreemptionGuard":
        """Arm the SIGTERM handler (main thread only — signal module
        contract) and start the notice-poll thread."""
        self._prev_handler = signal.signal(signal.SIGTERM, self._handler)
        self._thread = threading.Thread(
            target=self._poll_loop, name="hvd_tpu_fleet_preempt",
            daemon=True)
        self._thread.start()
        return self

    def uninstall(self) -> None:
        self._stop.set()
        if self._prev_handler is not None:
            signal.signal(signal.SIGTERM, self._prev_handler)
            self._prev_handler = None

    # -- notice sources ------------------------------------------------------

    def _poll_loop(self) -> None:
        """The metadata-poll stand-in: real clouds surface preemption
        through a poll or a signal; chaos drills both through the
        ``fleet.preempt`` site (a negative-code kill rule = deliver
        the signal, a plain kill = hard preemption)."""
        while not self._stop.wait(self.poll_s):
            chaos.point("fleet.preempt")

    def _handler(self, signum, frame) -> None:
        # handlers must return fast; the leave runs on its own thread
        # (the main thread is mid-training and the snapshot machinery
        # is deadline-bounded against exactly that)
        if self._leaving.is_set():
            return
        self._leaving.set()
        get_logger().warning(
            "fleet: preemption notice (signal %d) — planned snapshot, "
            "then leaving", signum)
        threading.Thread(target=self._leave, name="hvd_tpu_fleet_leave",
                         daemon=True).start()

    # -- the leave -----------------------------------------------------------

    def _leave(self) -> None:
        from ..elastic import worker as _worker

        t0 = self._clock()
        _instr.FLEET_PREEMPTIONS.inc()
        budget = env_float("HVD_TPU_ELASTIC_PLANNED_SNAPSHOT_SECONDS",
                           30.0)
        # 1) tell the driver FIRST: the 'leaving' mark must be in place
        #    before our exit code 0 can be observed, or the driver
        #    books job completion / failure instead of a scale-down.
        #    report_leaving blocks for the driver's ack (deterministic);
        #    an un-acked report (old driver, lost conn) gets a small
        #    grace as a best effort
        if _worker.elastic_enabled():
            acked = _worker.notification_manager.report_leaving(
                "preemption notice; planned snapshot then leave")
            if not acked:
                time.sleep(0.25)
        # 2) planned snapshot: bounded live attempt, commit fallback —
        #    the same keep-state contract as the PR-3 planned watchdog
        snap, ok = _worker._bounded_live_snapshot(self.state, budget)
        kind = "live"
        if not ok:
            snap = getattr(self.state, "_saved", None)
            kind = "commit" if snap is not None else "none"
            if snap is None:
                get_logger().error(
                    "fleet: no live snapshot and no commit — leaving "
                    "bare; progress on this worker since boot is lost")
        # 3) publish for the fleet: with auto-resume armed, the
        #    snapshot becomes a state checkpoint ANY replacement can
        #    pick up (save_state_checkpoint's rank-0 gate is bypassed —
        #    the preempted worker IS the authority on its progress)
        ckpt_dir = getattr(self.state, "_resume_dir", None)
        step = 0
        if snap is not None:
            step_attr = getattr(self.state, "_resume_step_attr", "step")
            try:
                step = int(getattr(self.state, step_attr, 0))
            except (TypeError, ValueError):
                step = 0
            if ckpt_dir:
                from .. import checkpoint as _ckpt

                try:
                    _ckpt.save_state_checkpoint(
                        ckpt_dir, self.state, step, snapshot=snap,
                        all_ranks=True)
                except Exception as e:
                    get_logger().warning(
                        "fleet: leave checkpoint failed (%s); the "
                        "commit/auto-resume path still applies", e)
        planned_s = self._clock() - t0
        _instr.RECOVERY_SECONDS.labels("planned").set(planned_s)
        try:
            from .. import trace as _trace
            from ..trace import flight as _flight

            _trace.event("fleet.preempt", step=step, planned_s=planned_s,
                         snapshot=kind)
            _flight.maybe_dump("preempt", extra={"step": step,
                                                 "snapshot": kind})
        except Exception:
            pass
        get_logger().warning(
            "fleet: planned leave complete in %.2fs (snapshot=%s, "
            "step=%d); exiting 0", planned_s, kind, step)
        if self.on_leave is not None:
            try:
                self.on_leave({"step": step, "planned_s": planned_s,
                               "snapshot": kind})
            except Exception:
                pass
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)
