"""SLO-driven elastic autoscaling + prefix-affinity serving fleet.

The closed loop ROADMAP item 3 names (docs/FLEET.md): PRs 1+3 built
the sensors (gauges, heartbeats, chaos, crash-atomic recovery), PRs
8-12 built a serving engine that scales inside one ICI slice — this
package DECIDES capacity and placement on top of both:

* :mod:`.policy` — target-tracking SLO controller + timed drill plans
  (:class:`TargetTrackingPolicy`, :class:`SchedulePolicy`);
* :mod:`.autoscaler` — the evaluate-and-apply loop; training worlds
  resize through ``ElasticDriver.request_world_size`` at epoch
  boundaries, signals come from worker metrics endpoints or
  ``cluster_snapshot()`` dicts;
* :mod:`.router` / :mod:`.replica` — N in-process ``ServingEngine``
  replicas behind prefix-affinity placement (route to the replica
  whose published block-hash index already holds the prompt's prefix;
  least-queue fallback), scaled against p99-TTFT/queue-depth SLOs
  with drain-before-teardown;
* :mod:`.preemption` — SIGTERM grace → planned snapshot → clean
  leave, drillable through the ``fleet.preempt`` chaos site.

Import shape: ``policy``/``autoscaler`` are import-light (stdlib +
metrics — the elastic driver loads them before jax exists);
``router``/``replica`` pull in the serving stack and are re-exported
lazily here.
"""

from __future__ import annotations

from .autoscaler import (  # noqa: F401
    Autoscaler, EndpointSignalSource, maybe_training_autoscaler,
    register_targets_endpoint,
)
from .policy import (  # noqa: F401
    Decision, SchedulePolicy, Target, TargetTrackingPolicy,
    histogram_quantile, plan_from_env, snapshot_signals,
)

__all__ = [
    "Autoscaler", "Decision", "EndpointSignalSource", "FleetRouter",
    "PreemptionGuard", "SchedulePolicy", "ServingReplica", "Target",
    "TargetTrackingPolicy", "histogram_quantile",
    "maybe_training_autoscaler", "plan_from_env",
    "register_targets_endpoint", "snapshot_signals",
]

_LAZY = {
    "FleetRouter": ".router",
    "ServingReplica": ".replica",
    "PreemptionGuard": ".preemption",
}


def __getattr__(name: str):
    # router/replica import the serving stack (jax, flax); the driver
    # imports this package pre-jax, so they load on first touch only
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(mod, __name__), name)
