"""Serving-replica lifecycle: spawn → warmup → ready → drain → retire.

One :class:`ServingReplica` wraps one
:class:`~horovod_tpu.serving.engine.ServingEngine` behind the small
surface the :class:`~horovod_tpu.fleet.router.FleetRouter` needs, and
reuses the PR-1/PR-3 machinery instead of growing its own:

* **spawn** builds + warms the engine through
  :func:`~horovod_tpu.common.retry.retry_call`
  (site ``fleet.replica_spawn`` — transient construction failures ride
  the shared backoff+jitter policy and land in
  ``hvd_tpu_retry_attempts``), and pins the warmup program count so
  ``compile_free`` is checkable per replica for its whole life;
* **heartbeat**: a replica that HAS work but hasn't completed a step
  within ``HVD_TPU_FLEET_REPLICA_STALL_SECONDS`` reports unhealthy —
  the same has-progress-vs-has-work distinction the PR-3 transport
  heartbeats draw (busy-compiling peers keep beating; a wedged one
  doesn't).  Each replica registers a ``/healthz`` source
  (``fleet_replica_<name>``) for the life of its engine;
* **drain** stops intake (the engine's ``accepting`` gate) while
  in-flight and already-queued sequences keep stepping to completion;
  ``drained`` is the router's teardown gate — a retiring replica's
  work is never dropped;
* **retire** releases the engine (params + KV pools) and the health
  source.

The replica never decides anything: placement and scaling live in the
router/policy.  It is deliberately process-local — the in-process
fleet is the bench/CI shape, and the lifecycle surface is what a
multi-process deployment would speak over RPC.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

import numpy as np

from .. import chaos as _chaos
from ..common.retry import env_float, env_int, retry_call
from ..metrics import instruments as _instr
from ..metrics.exposition import (
    register_health_source, unregister_health_source,
)
from ..utils.logging import get_logger

__all__ = ["ServingReplica", "DRAINING", "NEW", "PARKED", "READY",
           "RETIRED"]

NEW = "new"
#: spawned + warmed but not taking traffic — the warm-spare pool the
#: router unparks on scale-out (activation is instant; building and
#: warming an engine mid-traffic is seconds of compile)
PARKED = "parked"
READY = "ready"
DRAINING = "draining"
RETIRED = "retired"

ENV_STALL = "HVD_TPU_FLEET_REPLICA_STALL_SECONDS"
ENV_SPAWN_RETRIES = "HVD_TPU_FLEET_REPLICA_SPAWN_RETRIES"
#: consecutive submit/step errors (or healthz stall trips) before the
#: router marks a replica SUSPECT — ejected from placement, in-flight
#: work re-routed once (docs/FLEET.md)
ENV_ERRORS = "HVD_TPU_FLEET_REPLICA_ERRORS"
#: engine steps between periodic KV snapshots (0 = off): every N
#: completed steps the replica exports its in-flight requests' verified
#: streams + full-block pages (``engine.export_requests``) so the
#: router has a warm migration source even when a replica dies without
#: a drain handshake (docs/SERVING.md fault tolerance)
ENV_SNAPSHOT_STEPS = "HVD_TPU_SERVE_SNAPSHOT_STEPS"


class ServingReplica:
    """One engine's lifecycle wrapper (module docstring)."""

    def __init__(self, name: str, build_fn: Callable[[], object], *,
                 tier: str = "mixed", clock=time.perf_counter):
        self.name = str(name)
        self._build = build_fn
        self._clock = clock
        #: placement tier in a disaggregated fleet: ``"prefill"``
        #: (engine role ``prefill`` — requests leave at the handoff
        #: boundary), ``"decode"`` (full-menu engine that receives the
        #: migrated KV), or ``"mixed"`` (the single-tier default; both
        #: phases on every replica).  Pure routing metadata — the
        #: lifecycle below is tier-blind (docs/FLEET.md).
        self.tier = str(tier)
        self.state = NEW
        self.engine = None
        self.warmed_programs = 0
        self.spawned_at: Optional[float] = None
        self.retired_at: Optional[float] = None
        self._last_progress: Optional[float] = None
        self._stall_s = env_float(ENV_STALL, 60.0)
        #: peak of :meth:`queue_depth` over this replica's life (bench)
        self.peak_queue_depth = 0
        #: SUSPECT: ejected from placement after consecutive errors or
        #: a stall trip (router re-routes its work; docs/FLEET.md)
        self.suspect = False
        #: the router's ejection already ran (re-entrancy guard: a
        #: voluntarily-DRAINING replica that then stalls must still be
        #: ejectable, so the guard is this flag, not the state)
        self.ejected = False
        self._errors = 0
        self._error_threshold = max(1, env_int(ENV_ERRORS, 3))
        #: EMA of step wall time — the router's queue-delay estimate
        #: (deadline-aware placement) multiplies it by queue depth
        self.avg_step_s: Optional[float] = None
        #: periodic KV snapshot cadence (steps; 0 = off) and the last
        #: snapshot taken — the router's warm-migration fallback when
        #: this replica dies without a drain handshake
        self._snapshot_steps = max(0, env_int(ENV_SNAPSHOT_STEPS, 0))
        self._steps_since_snapshot = 0
        self.kv_snapshots: dict = {}

    # -- lifecycle -----------------------------------------------------------

    def spawn(self, park: bool = False) -> "ServingReplica":
        """Build + warm the engine (retry-wrapped); READY on return —
        or PARKED with ``park=True`` (a warm spare: fully compiled,
        taking no traffic until :meth:`unpark`).  Warmup compiles the
        engine's WHOLE tier menu, so a replica activated mid-traffic
        serves its first request compile-free — the menu discipline
        every serving PR has held."""
        if self.state != NEW:
            raise RuntimeError(f"replica {self.name} already spawned "
                               f"({self.state})")
        self.engine = retry_call(
            self._build,
            site="fleet.replica_spawn",
            retry_on=(RuntimeError, OSError),
            attempts=max(1, env_int(ENV_SPAWN_RETRIES, 3)),
            describe=f"serving replica {self.name} build",
        )
        # every kvsnap this engine exports names its sender, so a
        # chain-hash reject on the far side of a handoff or migration
        # points at the originating replica (satellite: kvsnap source)
        self.engine.snap_source = self.name
        self.warmed_programs = self.engine.warmup()
        self.engine.token_log = []
        self.state = PARKED if park else READY
        self.spawned_at = self._last_progress = self._clock()
        register_health_source(f"fleet_replica_{self.name}", self._health)
        get_logger().info("fleet: replica %s %s (%d tier programs)",
                          self.name, self.state, self.warmed_programs)
        return self

    def unpark(self) -> None:
        """Activate a warm spare (instant — the engine is compiled)."""
        if self.state != PARKED:
            raise RuntimeError(
                f"replica {self.name} is {self.state}, not parked")
        self.state = READY
        self._last_progress = self._clock()

    def drain(self) -> None:
        """Stop intake; in-flight + queued sequences keep stepping."""
        if self.state in (READY, PARKED):
            self.state = DRAINING
            self.engine.accepting = False

    @property
    def drained(self) -> bool:
        """True once nothing is left in flight (the teardown gate).
        A parked handoff counts as in flight: the snapshot only lives
        in this engine until the router's next collection pass, so a
        prefill-tier replica retiring mid-drain must hold its engine
        until every handoff has been picked up."""
        if self.engine is None:
            return True
        return not self.has_work and not getattr(
            self.engine, "handoffs", None)

    def retire(self) -> None:
        """Release the engine (params + KV pools) and health source.
        Call only when :attr:`drained` — the router enforces it."""
        if self.state == RETIRED:
            return
        if not self.drained:
            raise RuntimeError(
                f"replica {self.name} still has work; drain before retire")
        unregister_health_source(f"fleet_replica_{self.name}")
        # final accounting outlives the engine (fleet-wide bench stats)
        sched = self.engine.scheduler
        self._final_hits = sched.prefix_hit_blocks
        self._final_lookups = sched.prefix_lookup_blocks
        self._final_compile_free = self.compile_free
        self._final_ttfts = self.ttft_samples()
        self.state = RETIRED
        self.retired_at = self._clock()
        self.engine = None
        get_logger().info("fleet: replica %s retired", self.name)

    # -- the router's working surface ----------------------------------------

    @property
    def accepting(self) -> bool:
        return self.state == READY and not self.suspect

    @property
    def has_work(self) -> bool:
        sched = self.engine.scheduler
        return bool(sched.running or sched.pending
                    or sched.staged_depth())

    def note_error(self) -> bool:
        """Book one submit/step error or stall trip.  Returns True on
        the transition to SUSPECT (``HVD_TPU_FLEET_REPLICA_ERRORS``
        consecutive errors) — the router then ejects the replica and
        re-routes its work."""
        self._errors += 1
        if self._errors >= self._error_threshold and not self.suspect:
            self.suspect = True
            _instr.FLEET_REPLICA_SUSPECTS.inc()
            get_logger().error(
                "fleet: replica %s SUSPECT after %d consecutive "
                "error(s); ejecting from placement", self.name,
                self._errors)
            return True
        return False

    def note_ok(self) -> None:
        """A successful operation resets the consecutive-error run."""
        self._errors = 0

    def submit(self, prompt, max_new_tokens: int, *, eos_id=None,
               arrival: Optional[float] = None,
               deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None,
               spec_k: Optional[int] = None) -> int:
        if not self.accepting:
            raise RuntimeError(
                f"replica {self.name} is {self.state}, not accepting")
        # a dropped/killed dispatch raises here — the router books it
        # toward this replica's consecutive-error count and retries the
        # request on the next-best survivor (docs/FAULT_TOLERANCE.md)
        _chaos.raise_point("serve.dispatch")
        return self.engine.submit(prompt, max_new_tokens, eos_id=eos_id,
                                  arrival=arrival, deadline_s=deadline_s,
                                  trace_id=trace_id, spec_k=spec_k)

    def step(self) -> bool:
        """One engine step; progress timestamps feed the heartbeat and
        the step-time EMA feeds the queue-delay estimate.  Chaos site
        ``serve.replica_step`` fires BEFORE the engine steps — a raise
        here books toward the consecutive-error threshold exactly like
        a real step failure (the soak's replica-loss lever); a kill is
        the process-death case the periodic snapshots exist for."""
        _chaos.raise_point("serve.replica_step")
        t0 = self._clock()
        more = self.engine.step()
        now = self._clock()
        dt = max(0.0, now - t0)
        self.avg_step_s = dt if self.avg_step_s is None else (
            0.8 * self.avg_step_s + 0.2 * dt)
        self._last_progress = now
        if self._snapshot_steps > 0:
            self._steps_since_snapshot += 1
            if self._steps_since_snapshot >= self._snapshot_steps:
                self._steps_since_snapshot = 0
                self.snapshot_kv()
        return more

    def snapshot_kv(self) -> None:
        """Export every in-flight request's verified stream + full-block
        pages (the router's warm-migration fallback source).  Chaos
        site ``serve.snapshot``: a drop here skips THIS cadence — the
        previous snapshot stays valid (recovery falls further behind
        the stream, never wrong: the migrated prefix is still a
        verified prefix and the survivor regenerates the rest)."""
        try:
            _chaos.raise_point("serve.snapshot")
        except _chaos.ChaosInjected:
            return
        self.kv_snapshots = self.engine.export_requests()

    def est_queue_delay(self) -> float:
        """Rough seconds of queue ahead of a new request on this
        replica (queue depth x step-time EMA) — the router skips
        replicas whose estimate already exceeds a request's remaining
        deadline budget."""
        return (self.avg_step_s or 0.0) * self.queue_depth()

    def queue_depth(self) -> int:
        """Requests waiting for admission on this replica (scheduler
        pending + device-staged) — the least-queue routing signal,
        the same sum the ``hvd_tpu_serve_queue_depth`` gauge carries."""
        depth = self.engine.scheduler.queue_depth()
        self.peak_queue_depth = max(self.peak_queue_depth, depth)
        return depth

    def cached_prefix_blocks(self, tokens: Sequence[int]) -> int:
        """Blocks of ``tokens``' longest prefix this replica's
        published block-hash index already holds — the affinity
        placement score.  A pure peek: no refcounts move (the real
        match happens at admission on whichever replica wins)."""
        prompt = np.asarray(tokens).reshape(-1)
        bs = self.engine.allocator.block_size
        return self.engine.allocator.peek_prefix(
            prompt, max_blocks=(len(prompt) - 1) // bs)

    @property
    def compile_free(self) -> bool:
        """No program compiled after warmup — the standing zero
        post-warmup-compiles contract, per replica."""
        return (self.engine is not None
                and self.engine.program_count == self.warmed_programs)

    def ttft_samples(self):
        """(request_id, ttft_seconds) for every first token this
        replica emitted — the router's SLO signal feed; survives
        retirement (the final list is captured before the engine is
        released)."""
        if self.engine is None:
            return list(getattr(self, "_final_ttfts", ()))
        seen = set()
        out = []
        for rid, emit, arr in (self.engine.token_log or ()):
            if rid not in seen:
                seen.add(rid)
                out.append((rid, emit - arr))
        return out

    # -- heartbeat -----------------------------------------------------------

    def _health(self):
        stalled = False
        if self.state in (READY, DRAINING) and self.engine is not None \
                and self.has_work and self._last_progress is not None:
            stalled = (self._clock() - self._last_progress) > self._stall_s
        return not stalled, {
            "state": self.state,
            "queue_depth": self.queue_depth() if self.engine else 0,
            "stalled": stalled,
        }

    def healthy(self) -> bool:
        return self._health()[0]
