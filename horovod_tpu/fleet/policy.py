"""Autoscale policy engine: decide capacity from live signals.

The closed loop's brain (ROADMAP item 3, docs/FLEET.md): the sensors
already exist — PR-1's registry gauges/histograms, PR-3's heartbeats
and recovery accounting — but nothing *decided* capacity; upstream
Horovod's elastic mode only ever reacts to failures (SURVEY §5.3).
This module turns "what the gauges say" into "how many workers /
serving replicas there should be", and nothing else: it never spawns,
drains or kills anything itself.  The appliers live in
:mod:`.autoscaler` (training worlds via
``ElasticDriver.request_world_size``) and :mod:`.router` (serving
replicas via spawn/drain/retire).

Two policies share the :meth:`evaluate` interface
``(signals, current, now) -> Decision``:

* :class:`TargetTrackingPolicy` — the SLO controller.  Each
  :class:`Target` names a signal (``p99_ttft``, ``queue_depth``,
  ``step_time``, ``throughput``...) and the value it should sit at;
  the load ratio ``observed / target`` (inverted for floor-style
  targets such as throughput) is the classic target-tracking control
  signal: ratio 2.0 means the fleet is carrying twice the load its
  capacity should, so capacity doubles.  Three dampers keep
  chaos-injected noise (and real-world flapping) from thrashing it:

  - a **deadband** around 1.0 inside which nothing happens,
  - **hysteresis** on scale-in: every watched ratio must sit under
    ``scale_in_at`` for N consecutive evaluations (capacity removal is
    the dangerous direction — a single quiet sample must not shed the
    replica that was absorbing the burst),
  - a **cooldown** after any applied action, both directions (the
    signal needs time to reflect the new capacity before it is judged
    again).

* :class:`SchedulePolicy` — a timed resize plan (``"4:3,10:2"`` =
  size 3 from t=4 s, size 2 from t=10 s).  The drill/soak form of the
  same loop: chaos-soak scenarios and capacity rehearsals drive the
  exact code path the SLO controller drives, with deterministic
  timing.  ``HVD_TPU_FLEET_PLAN`` wires it into the elastic driver.

Targets are settable three ways: at construction, from the
environment (:meth:`TargetTrackingPolicy.from_env`, the
``HVD_TPU_FLEET_*`` rows in docs/running.md), and over HTTP while the
job runs (:func:`horovod_tpu.fleet.autoscaler.register_targets_endpoint`
mounts ``/control/fleet/targets`` on the PR-1 metrics endpoint).
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..common.retry import env_float, env_int

__all__ = [
    "Decision", "SchedulePolicy", "Target", "TargetTrackingPolicy",
    "decode_policy_from_env", "histogram_quantile", "snapshot_signals",
]

# the SLO knobs (docs/running.md): a target is armed iff its variable
# is set to a positive value
ENV_TTFT_SLO = "HVD_TPU_FLEET_TTFT_SLO"
ENV_QUEUE_SLO = "HVD_TPU_FLEET_QUEUE_SLO"
ENV_STEP_TIME_SLO = "HVD_TPU_FLEET_STEP_TIME_SLO"
ENV_THROUGHPUT_FLOOR = "HVD_TPU_FLEET_THROUGHPUT_FLOOR"
#: decode-tier throughput floor (tokens/s per accepting decode
#: replica) for the disaggregated serving fleet — kept OUT of
#: :meth:`TargetTrackingPolicy.from_env` so setting it never arms a
#: decode target on a training fleet's policy (docs/FLEET.md)
ENV_DECODE_TPS_FLOOR = "HVD_TPU_FLEET_DECODE_TPS_FLOOR"


@dataclasses.dataclass(frozen=True)
class Target:
    """One SLO: ``signal`` should sit at ``value``.

    ``invert=False`` (ceilings: p99 TTFT, queue depth, step time):
    load ratio = observed / value — above 1.0 means overloaded.
    ``invert=True`` (floors: throughput): ratio = value / observed —
    a throughput UNDER the floor reads as overload the same way."""

    signal: str
    value: float
    invert: bool = False

    def ratio(self, observed: float) -> Optional[float]:
        if self.value <= 0:
            return None
        if not self.invert:
            return observed / self.value
        # a floor with a zero observation is infinitely underserved
        return math.inf if observed <= 0 else self.value / observed


@dataclasses.dataclass(frozen=True)
class Decision:
    """One policy evaluation's outcome.  ``direction`` is ``"out"``,
    ``"in"`` or ``"hold"``; ``desired`` is the capacity the fleet
    should converge to (== ``current`` on hold)."""

    direction: str
    desired: int
    reason: str
    signal: Optional[str] = None
    value: Optional[float] = None
    ratio: Optional[float] = None


class TargetTrackingPolicy:
    """Target-tracking scale controller with deadband, scale-in
    hysteresis and cooldown (module docstring).  Thread-safe:
    :meth:`set_target` may be called from the HTTP control handler
    while :meth:`evaluate` runs on the autoscaler thread."""

    def __init__(self, targets: Sequence[Target], *,
                 min_size: int = 1, max_size: int = 8,
                 deadband: float = 0.1, scale_in_at: float = 0.5,
                 hysteresis: int = 3, cooldown_s: float = 30.0):
        if min_size < 1 or max_size < min_size:
            raise ValueError(
                f"need 1 <= min_size <= max_size, got {min_size}/{max_size}")
        if not 0.0 < scale_in_at < 1.0:
            raise ValueError(
                f"scale_in_at must be in (0, 1), got {scale_in_at}")
        if deadband < 0:
            raise ValueError(f"deadband must be >= 0, got {deadband}")
        self._lock = threading.Lock()
        self._targets: Dict[str, Target] = {t.signal: t for t in targets}
        self.min_size = int(min_size)
        self.max_size = int(max_size)
        self.deadband = float(deadband)
        self.scale_in_at = float(scale_in_at)
        self.hysteresis = max(1, int(hysteresis))
        self.cooldown_s = float(cooldown_s)
        self._low_streak = 0
        self._last_action_at: Optional[float] = None

    # -- targets (env-, call- and HTTP-settable) ----------------------------

    def targets(self) -> Dict[str, Target]:
        with self._lock:
            return dict(self._targets)

    def set_target(self, signal: str, value: float,
                   invert: Optional[bool] = None) -> Target:
        """Replace (or create) one target's value at runtime; the next
        evaluation uses it.  ``invert`` defaults to the existing
        target's orientation (False for a new signal)."""
        value = float(value)
        if value <= 0:
            raise ValueError(f"target for {signal!r} must be > 0")
        with self._lock:
            old = self._targets.get(signal)
            inv = old.invert if (invert is None and old is not None) \
                else bool(invert)
            t = Target(signal, value, inv)
            self._targets[signal] = t
            return t

    @classmethod
    def from_env(cls, *, min_size: Optional[int] = None,
                 max_size: Optional[int] = None) -> "TargetTrackingPolicy":
        """Build from the ``HVD_TPU_FLEET_*`` knobs (docs/running.md):
        a target is armed iff its SLO variable is set to a positive
        value; the damper knobs always apply."""
        targets = []
        for env, signal, invert in (
                (ENV_TTFT_SLO, "p99_ttft", False),
                (ENV_QUEUE_SLO, "queue_depth", False),
                (ENV_STEP_TIME_SLO, "step_time", False),
                (ENV_THROUGHPUT_FLOOR, "throughput", True)):
            v = env_float(env, 0.0)
            if v > 0:
                targets.append(Target(signal, v, invert))
        return cls(
            targets,
            min_size=min_size if min_size is not None
            else env_int("HVD_TPU_FLEET_MIN", 1),
            max_size=max_size if max_size is not None
            else env_int("HVD_TPU_FLEET_MAX", 8),
            deadband=env_float("HVD_TPU_FLEET_DEADBAND", 0.1),
            scale_in_at=env_float("HVD_TPU_FLEET_SCALE_IN_AT", 0.5),
            hysteresis=env_int("HVD_TPU_FLEET_HYSTERESIS", 3),
            cooldown_s=env_float("HVD_TPU_FLEET_COOLDOWN", 30.0),
        )

    # -- the decision --------------------------------------------------------

    def note_applied(self, now: Optional[float] = None) -> None:
        """The caller applied a decision: start the cooldown window.
        Kept separate from :meth:`evaluate` so a decision the applier
        could NOT honor (no free slots, replica spawn failed) does not
        burn the cooldown."""
        with self._lock:
            self._last_action_at = time.monotonic() if now is None else now

    def evaluate(self, signals: Dict[str, float], current: int,
                 now: Optional[float] = None) -> Decision:
        now = time.monotonic() if now is None else now
        current = max(1, int(current))
        with self._lock:
            targets = list(self._targets.values())
            cooling = (self._last_action_at is not None
                       and now - self._last_action_at < self.cooldown_s)
            ratios: List[Tuple[float, Target, float]] = []
            for t in targets:
                if t.signal not in signals:
                    continue
                v = float(signals[t.signal])
                r = t.ratio(v)
                if r is not None:
                    ratios.append((r, t, v))
            if not ratios:
                self._low_streak = 0
                return Decision("hold", current, "no watched signals")
            worst_r, worst_t, worst_v = max(ratios, key=lambda x: x[0])

            # -- scale out: any ratio past the deadband -----------------
            if worst_r > 1.0 + self.deadband:
                self._low_streak = 0
                if cooling:
                    return Decision("hold", current,
                                    "overloaded but cooling down",
                                    worst_t.signal, worst_v, worst_r)
                desired = min(self.max_size,
                              max(current + 1,
                                  math.ceil(current * min(worst_r, 8.0))))
                if desired <= current:
                    return Decision("hold", current, "already at max_size",
                                    worst_t.signal, worst_v, worst_r)
                return Decision(
                    "out", desired,
                    f"{worst_t.signal}={worst_v:.4g} is "
                    f"{worst_r:.2f}x its target {worst_t.value:.4g}",
                    worst_t.signal, worst_v, worst_r)

            # -- scale in: EVERY ratio low, streak + cooldown permitting
            if worst_r < self.scale_in_at:
                self._low_streak += 1
                if self._low_streak < self.hysteresis:
                    return Decision("hold", current,
                                    f"underloaded {self._low_streak}/"
                                    f"{self.hysteresis} evaluations",
                                    worst_t.signal, worst_v, worst_r)
                if cooling:
                    return Decision("hold", current,
                                    "underloaded but cooling down",
                                    worst_t.signal, worst_v, worst_r)
                if current <= self.min_size:
                    return Decision("hold", current, "already at min_size",
                                    worst_t.signal, worst_v, worst_r)
                # one step at a time: removing capacity is the risky
                # direction, and the cooldown re-judges before the next
                return Decision(
                    "in", current - 1,
                    f"all signals under {self.scale_in_at:.2f}x of "
                    f"target for {self._low_streak} evaluations",
                    worst_t.signal, worst_v, worst_r)

            self._low_streak = 0
            return Decision("hold", current, "within deadband",
                            worst_t.signal, worst_v, worst_r)


class SchedulePolicy:
    """A timed resize plan: ``[(t_offset_s, size), ...]``; the desired
    size is the last entry whose offset has elapsed (before the first
    entry: hold at current).  The drill form of the closed loop —
    chaos-soak scale scenarios and capacity rehearsals drive the same
    ``request_world_size``/replica paths the SLO controller drives,
    with deterministic timing.  Spec grammar (``HVD_TPU_FLEET_PLAN``):
    ``"T:N[,T:N...]"``, offsets in seconds, strictly ascending."""

    def __init__(self, plan: Sequence[Tuple[float, int]],
                 t0: Optional[float] = None):
        plan = [(float(t), int(n)) for t, n in plan]
        if not plan:
            raise ValueError("empty resize plan")
        if any(n < 1 for _, n in plan):
            raise ValueError(f"plan sizes must be >= 1: {plan}")
        if any(b <= a for (a, _), (b, _) in zip(plan, plan[1:])):
            raise ValueError(f"plan offsets must be strictly ascending: "
                             f"{plan}")
        self.plan = plan
        self._t0 = t0  # lazily pinned at the first evaluate

    @classmethod
    def parse(cls, spec: str, t0: Optional[float] = None) -> "SchedulePolicy":
        entries = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            try:
                t, n = part.split(":", 1)
                entries.append((float(t), int(n)))
            except ValueError:
                raise ValueError(
                    f"bad plan entry {part!r} (want T_SECONDS:SIZE)"
                ) from None
        return cls(entries, t0=t0)

    def evaluate(self, signals: Dict[str, float], current: int,
                 now: Optional[float] = None) -> Decision:
        now = time.monotonic() if now is None else now
        if self._t0 is None:
            self._t0 = now
        elapsed = now - self._t0
        desired = None
        for t, n in self.plan:
            if elapsed >= t:
                desired = n
        if desired is None or desired == current:
            return Decision("hold", current, f"plan holds at t={elapsed:.1f}s")
        direction = "out" if desired > current else "in"
        return Decision(direction, desired,
                        f"plan entry t<={elapsed:.1f}s wants {desired}")

    def note_applied(self, now: Optional[float] = None) -> None:
        pass  # the plan is time-driven; no cooldown state


# -- signal extraction -------------------------------------------------------


def histogram_quantile(bounds: Sequence[float], counts: Sequence[float],
                       q: float) -> float:
    """Prometheus-style quantile from fixed-bucket counts.

    ``counts`` are PER-BUCKET observation counts aligned with
    ``bounds`` plus one trailing overflow bucket (+Inf) — the registry
    snapshot/cluster_snapshot layout.  Linear interpolation within the
    winning bucket; the overflow bucket clamps to the last bound (the
    honest answer a bounded histogram can give)."""
    if len(counts) not in (len(bounds), len(bounds) + 1):
        raise ValueError(
            f"counts ({len(counts)}) must align with bounds "
            f"({len(bounds)}) plus an optional overflow bucket")
    total = float(sum(counts))
    if total <= 0:
        return 0.0
    rank = q * total
    cum = 0.0
    prev_bound = 0.0
    for i, n in enumerate(counts):
        lo = cum
        cum += float(n)
        if cum >= rank and n > 0:
            if i >= len(bounds):
                return float(bounds[-1])
            hi_bound = float(bounds[i])
            frac = (rank - lo) / float(n)
            return prev_bound + (hi_bound - prev_bound) * frac
        if i < len(bounds):
            prev_bound = float(bounds[i])
    return float(bounds[-1])


def _series_sum(entry: dict) -> float:
    return sum(float(state) for _labels, state in entry.get("series", []))


def snapshot_signals(snap: dict, prev: Optional[dict] = None,
                     dt: Optional[float] = None) -> Dict[str, float]:
    """Extract the policy's standard signals from a
    :func:`horovod_tpu.metrics.aggregate.cluster_snapshot` /
    ``snapshot()`` dict — the driver-side loop consumes the gauges the
    workers already publish instead of growing a second telemetry path.

      queue_depth  sum of ``hvd_tpu_serve_queue_depth`` across ranks
      p99_ttft     q0.99 of the ``first``-kind token-latency histogram
      step_time    q0.50 of ``hvd_tpu_step_duration_seconds``
      throughput   rate of ``hvd_tpu_serve_steps_total`` (or training
                   step count) between ``prev`` and ``snap`` over
                   ``dt`` seconds — needs both; omitted otherwise

    Missing metrics simply produce no signal (the policy skips absent
    signals), so one extractor serves training and serving snapshots.
    """
    metrics = snap.get("metrics", {})
    out: Dict[str, float] = {}
    q = metrics.get("hvd_tpu_serve_queue_depth")
    if q is not None:
        # gauges carry a synthetic leading rank label in merged
        # snapshots; summing the series is the fleet-wide queue either way
        out["queue_depth"] = _series_sum(q)
    lat = metrics.get("hvd_tpu_serve_token_latency_seconds")
    if lat is not None and lat.get("buckets"):
        for labels, state in lat.get("series", []):
            if list(labels) and list(labels)[-1] == "first" \
                    and state.get("count", 0) > 0:
                out["p99_ttft"] = histogram_quantile(
                    lat["buckets"], state["buckets"], 0.99)
                break
    step = metrics.get("hvd_tpu_step_duration_seconds")
    if step is not None and step.get("buckets"):
        buckets = [0.0] * (len(step["buckets"]) + 1)
        count = 0
        for _labels, state in step.get("series", []):
            count += state.get("count", 0)
            for i, n in enumerate(state.get("buckets", [])):
                if i < len(buckets):
                    buckets[i] += n
        if count > 0:
            out["step_time"] = histogram_quantile(
                step["buckets"], buckets, 0.5)
    if prev is not None and dt and dt > 0:
        cur_e = metrics.get("hvd_tpu_serve_steps_total")
        if cur_e is not None:
            prev_e = prev.get("metrics", {}).get(
                "hvd_tpu_serve_steps_total")
            delta = _series_sum(cur_e) - (
                _series_sum(prev_e) if prev_e else 0.0)
            out["throughput"] = max(0.0, delta) / dt
    return out


def decode_policy_from_env() -> Optional["TargetTrackingPolicy"]:
    """The disaggregated router's decode-tier policy
    (``HVD_TPU_FLEET_DECODE_TPS_FLOOR``, docs/FLEET.md): a floor-style
    target on ``decode_tokens_per_s`` — per-replica decode throughput
    UNDER the floor reads as overload (too few decode replicas for the
    handoff inflow), so the decode tier scales out; comfortably above
    it, the hysteresis/cooldown dampers let it shed.  Returns None
    unless the floor is set positive.  The prefill tier keeps the
    generic :meth:`TargetTrackingPolicy.from_env` (TTFT-shaped — time
    to first token is decided entirely before the handoff)."""
    floor = env_float(ENV_DECODE_TPS_FLOOR, 0.0)
    if floor <= 0:
        return None
    return TargetTrackingPolicy(
        [Target("decode_tokens_per_s", floor, invert=True)],
        min_size=env_int("HVD_TPU_FLEET_MIN", 1),
        max_size=env_int("HVD_TPU_FLEET_MAX", 8),
        deadband=env_float("HVD_TPU_FLEET_DEADBAND", 0.1),
        scale_in_at=env_float("HVD_TPU_FLEET_SCALE_IN_AT", 0.5),
        hysteresis=env_int("HVD_TPU_FLEET_HYSTERESIS", 3),
        cooldown_s=env_float("HVD_TPU_FLEET_COOLDOWN", 30.0),
    )


ENV_PLAN = "HVD_TPU_FLEET_PLAN"


def plan_from_env() -> Optional[SchedulePolicy]:
    """The driver's drill hook: a :class:`SchedulePolicy` when
    ``HVD_TPU_FLEET_PLAN`` is set, else None."""
    spec = os.environ.get(ENV_PLAN, "").strip()
    return SchedulePolicy.parse(spec) if spec else None
