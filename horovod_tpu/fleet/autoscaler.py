"""The autoscale loop: policy decisions applied to a live fleet.

One :class:`Autoscaler` closes the loop for either fleet kind:

* **training** — the elastic driver passes
  ``apply_fn=driver.request_world_size`` (the PR-13 resize entry
  point): the decision lands as a planned membership change at the
  next epoch boundary, through the exact rendezvous machinery
  failure recovery already exercises.  The driver starts one
  automatically when ``HVD_TPU_FLEET_PLAN`` is set
  (:func:`maybe_training_autoscaler`); SLO mode takes signals from
  worker metrics endpoints (:class:`EndpointSignalSource`, the PR-1
  scrape surface) or from ``cluster_snapshot()`` dicts the training
  loop already produces (:func:`.policy.snapshot_signals`).
* **serving** — the :class:`~horovod_tpu.fleet.router.FleetRouter`
  embeds the same policy engine directly (its signals are in-process;
  no scrape hop) and applies decisions as replica spawn/drain/retire.

The loop itself is deliberately dumb: read signals, evaluate, apply,
book the metrics, sleep.  Every interesting property (hysteresis,
cooldown, clamping) lives in :mod:`.policy` where it is unit-testable
without threads.
"""

from __future__ import annotations

import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..common.retry import env_float
from ..metrics import instruments as _instr
from ..utils.logging import get_logger
from .policy import Decision, histogram_quantile, plan_from_env

__all__ = [
    "Autoscaler", "EndpointSignalSource", "maybe_training_autoscaler",
    "parse_prom_text", "register_targets_endpoint",
]

ENV_INTERVAL = "HVD_TPU_FLEET_INTERVAL"
ENV_SCRAPE = "HVD_TPU_FLEET_SCRAPE"


class Autoscaler:
    """Periodic evaluate-and-apply driver around one policy.

    ``current_fn`` reports the fleet's live size, ``signals_fn`` (may
    be None for time-plan policies) its load signals, ``apply_fn``
    receives the desired size and returns truthy when the resize was
    accepted (a rejected apply — no free slots yet, replica spawn
    failed — leaves the policy's cooldown un-burnt so the next tick
    retries)."""

    def __init__(self, policy, apply_fn: Callable[[int], object], *,
                 current_fn: Callable[[], int],
                 signals_fn: Optional[Callable[[], Dict[str, float]]] = None,
                 interval_s: Optional[float] = None,
                 kind: str = "train",
                 clock=time.monotonic):
        self.policy = policy
        self._apply = apply_fn
        self._current = current_fn
        self._signals = signals_fn
        self.interval_s = (env_float(ENV_INTERVAL, 5.0)
                           if interval_s is None else float(interval_s))
        self.kind = kind
        self._clock = clock
        self._desired_g = _instr.FLEET_DESIRED_SIZE.labels(kind)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_decision: Optional[Decision] = None
        self._applied_desired: Optional[int] = None

    def tick(self, now: Optional[float] = None) -> Decision:
        """One evaluation: the unit the thread loops over (tests call
        it directly with injected clocks/signals)."""
        now = self._clock() if now is None else now
        signals = self._signals() if self._signals is not None else {}
        current = int(self._current())
        d = self.policy.evaluate(signals, current, now)
        self.last_decision = d
        self._desired_g.set(d.desired)
        if d.direction != "hold" and d.desired != current \
                and d.desired != self._applied_desired:
            # the != _applied_desired guard: a target already handed to
            # the applier stays in force there (request_world_size is
            # sticky) — re-applying it every tick while the fleet
            # converges (or while capacity is short) would inflate the
            # scale-event counter without bound for one decision
            get_logger().info(
                "fleet[%s]: scale %s %d -> %d (%s)", self.kind,
                d.direction, current, d.desired, d.reason)
            if self._apply(d.desired):
                _instr.FLEET_SCALE_EVENTS.labels(
                    self.kind, d.direction).inc()
                self._applied_desired = d.desired
                self.policy.note_applied(now)
                from .. import trace as _trace

                _trace.event("fleet.scale", kind=self.kind,
                             direction=d.direction, current=current,
                             desired=d.desired, reason=d.reason)
                if d.direction == "out":
                    # a scale-out IS an SLO breach being answered: the
                    # signals and spans of the 30 s leading up to it
                    # are exactly what the post-mortem wants
                    from ..trace import flight as _flight

                    _flight.maybe_dump("slo_breach", extra={
                        "kind": self.kind, "desired": d.desired,
                        "reason": d.reason})
        return d

    # -- thread form (the driver/router run it; tests use tick()) -----------

    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name=f"hvd_tpu_fleet_{self.kind}",
                daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:
                # the autoscaler must never take the driver down — a
                # scrape hiccup or a transient apply failure is a
                # skipped tick, not a dead fleet
                get_logger().warning("fleet[%s]: tick failed: %s",
                                     self.kind, e)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# -- metrics-endpoint signals ------------------------------------------------


def parse_prom_text(text: str) -> Dict[Tuple[str, Tuple[str, ...]], float]:
    """Parse Prometheus text-format 0.0.4 samples into
    ``{(metric_name, (label_value, ...)): value}`` — just enough of the
    format to read back what :func:`..metrics.exposition.render` wrote
    (label VALUES in declaration order; names dropped — the reader
    knows the catalogue's label order from docs/METRICS.md)."""
    out: Dict[Tuple[str, Tuple[str, ...]], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            head, value = line.rsplit(" ", 1)
            if "{" in head:
                name, rest = head.split("{", 1)
                labels = tuple(
                    p.split("=", 1)[1].strip('"')
                    for p in rest.rstrip("}").split('",')
                    if "=" in p)
            else:
                name, labels = head, ()
            out[(name, labels)] = float(value)
        except ValueError:
            continue
    return out


class EndpointSignalSource:
    """Policy signals scraped from worker ``/metrics`` endpoints (the
    PR-1 exposition surface) — the driver-side loop's eyes when it has
    no in-process registry to read.

      queue_depth   sum of ``hvd_tpu_serve_queue_depth``
      p99_ttft      q0.99 of the ``first``-kind token-latency histogram
                    (windowed: computed on the bucket DELTAS since the
                    previous scrape, so old traffic can't mask a fresh
                    SLO breach)
      step_time     q0.50 of ``hvd_tpu_step_duration_seconds`` deltas
      throughput    rate of ``hvd_tpu_serve_steps_total`` between
                    scrapes
      decode_tokens_per_s
                    emitted-token rate (token-latency ``_count``
                    deltas) per scraped endpoint — the disaggregated
                    decode tier's throughput-floor signal

    Unreachable endpoints contribute nothing (the policy holds on "no
    watched signals" rather than act on a partial picture when every
    scrape fails)."""

    LATENCY = "hvd_tpu_serve_token_latency_seconds"
    STEP = "hvd_tpu_step_duration_seconds"
    QUEUE = "hvd_tpu_serve_queue_depth"
    STEPS_TOTAL = "hvd_tpu_serve_steps_total"

    def __init__(self, urls: Sequence[str], timeout_s: float = 2.0,
                 clock=time.monotonic):
        self.urls = [u if "://" in u else f"http://{u}" for u in urls]
        self.timeout_s = timeout_s
        self._clock = clock
        self._prev: Optional[Dict] = None
        self._prev_at: Optional[float] = None

    def _fetch(self) -> Dict[Tuple[str, Tuple[str, ...]], float]:
        merged: Dict[Tuple[str, Tuple[str, ...]], float] = {}
        for url in self.urls:
            target = url.rstrip("/") + "/metrics"
            try:
                with urllib.request.urlopen(
                        target, timeout=self.timeout_s) as resp:
                    samples = parse_prom_text(
                        resp.read().decode("utf-8", "replace"))
            except OSError as e:
                get_logger().debug("fleet: scrape %s failed: %s",
                                   target, e)
                continue
            for k, v in samples.items():
                merged[k] = merged.get(k, 0.0) + v
        return merged

    def _buckets(self, samples, name: str, kind: Optional[str]
                 ) -> Tuple[List[float], List[float]]:
        """(ascending bounds, per-bucket cumulative counts) of one
        histogram series (``kind`` filters the leading label value)."""
        rows = []
        for (n, labels), v in samples.items():
            if n != name + "_bucket":
                continue
            if kind is not None and (not labels or labels[0] != kind):
                continue
            le = labels[-1]
            bound = float("inf") if le == "+Inf" else float(le)
            rows.append((bound, v))
        rows.sort(key=lambda r: r[0])
        return [b for b, _ in rows], [c for _, c in rows]

    def _quantile(self, cur, prev, name, kind, q) -> Optional[float]:
        bounds, cum = self._buckets(cur, name, kind)
        if not bounds:
            return None
        prev_cum = [0.0] * len(cum)
        if prev is not None:
            _pb, pc = self._buckets(prev, name, kind)
            if len(pc) == len(cum):
                prev_cum = pc
        # cumulative -> per-bucket, windowed on the scrape delta
        per = []
        last = 0.0
        for c, p in zip(cum, prev_cum):
            d = max(0.0, (c - p) - last)
            per.append(d)
            last = c - p
        if sum(per) <= 0:
            return None
        finite = [b for b in bounds if b != float("inf")]
        return histogram_quantile(finite, per[:len(finite) + 1], q)

    def __call__(self) -> Dict[str, float]:
        now = self._clock()
        cur = self._fetch()
        if not cur:
            self._prev, self._prev_at = None, None
            return {}
        out: Dict[str, float] = {}
        q = [v for (n, _l), v in cur.items() if n == self.QUEUE]
        if q:
            out["queue_depth"] = sum(q)
        p99 = self._quantile(cur, self._prev, self.LATENCY, "first", 0.99)
        if p99 is not None:
            out["p99_ttft"] = p99
        p50 = self._quantile(cur, self._prev, self.STEP, None, 0.5)
        if p50 is not None:
            out["step_time"] = p50
        if self._prev is not None and self._prev_at is not None:
            dt = now - self._prev_at
            if dt > 0:
                steps = sum(v for (n, _l), v in cur.items()
                            if n == self.STEPS_TOTAL)
                prev_steps = sum(v for (n, _l), v in self._prev.items()
                                 if n == self.STEPS_TOTAL)
                out["throughput"] = max(0.0, steps - prev_steps) / dt
                # decode-tier throughput per scraped endpoint: the
                # token-latency histogram's _count is one observation
                # per emitted token, so its scrape-to-scrape rate is
                # tokens/s — divided per endpoint it is the
                # decode_tokens_per_s floor signal the disaggregated
                # router's decode policy watches (docs/FLEET.md)
                toks = sum(v for (n, _l), v in cur.items()
                           if n == self.LATENCY + "_count")
                prev_toks = sum(v for (n, _l), v in self._prev.items()
                                if n == self.LATENCY + "_count")
                out["decode_tokens_per_s"] = (
                    max(0.0, toks - prev_toks) / dt
                    / max(1, len(self.urls)))
        self._prev, self._prev_at = cur, now
        return out


# -- wiring ------------------------------------------------------------------


def register_targets_endpoint(policy, name: str = "fleet/targets") -> None:
    """Mount the policy's targets on the metrics endpoint:
    ``GET /control/fleet/targets`` lists them,
    ``GET /control/fleet/targets?set=p99_ttft:0.5`` retunes one at
    runtime (docs/FLEET.md) — the ISSUE's HTTP-settable targets."""
    from ..metrics import exposition as _expo

    def handler(params: Dict[str, str]) -> Tuple[int, dict]:
        if "set" in params:
            try:
                signal, raw = params["set"].split(":", 1)
                t = policy.set_target(signal.strip(), float(raw))
            except ValueError as e:
                return 400, {"error": str(e)}
            get_logger().warning(
                "fleet: target %s set to %s over HTTP", t.signal, t.value)
        return 200, {"targets": {
            s: {"value": t.value, "invert": t.invert}
            for s, t in policy.targets().items()}}

    _expo.register_control_handler(name, handler)


def maybe_training_autoscaler(request_world_size, current_fn,
                              *, min_size: int, max_size: Optional[int],
                              ) -> Optional[Autoscaler]:
    """The elastic driver's init hook: build a training autoscaler
    from the environment, or None when nothing opts in.

    ``HVD_TPU_FLEET_PLAN`` (a timed drill plan) wins; otherwise any
    armed ``HVD_TPU_FLEET_*_SLO``/``_FLOOR`` target plus
    ``HVD_TPU_FLEET_SCRAPE`` (comma-separated worker metrics
    endpoints) arms the SLO controller.  Driver min/max-np bound the
    policy either way."""
    import os

    from .policy import TargetTrackingPolicy

    hi = max_size if max_size is not None else 64
    plan = plan_from_env()
    if plan is not None:
        return Autoscaler(plan, request_world_size,
                          current_fn=current_fn, kind="train")
    policy = TargetTrackingPolicy.from_env(min_size=min_size, max_size=hi)
    if not policy.targets():
        return None
    urls = [u for u in os.environ.get(ENV_SCRAPE, "").split(",")
            if u.strip()]
    if not urls:
        get_logger().warning(
            "fleet: SLO targets armed but HVD_TPU_FLEET_SCRAPE is empty "
            "— the training autoscaler has no signal source; not started")
        return None
    register_targets_endpoint(policy)
    return Autoscaler(policy, request_world_size, current_fn=current_fn,
                      signals_fn=EndpointSignalSource(urls), kind="train")
