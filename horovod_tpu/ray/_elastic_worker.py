"""Local-backend worker entrypoint for ElasticRayExecutor (reference
analog: the elastic remote function ElasticRayExecutor.run dispatches in
horovod/ray/elastic.py).  Unlike _worker.py, the rank is only known after
the elastic rendezvous, so the result file is keyed by the final rank."""

import os
import pickle
import sys


def main():
    payload_path, result_dir = sys.argv[1], sys.argv[2]
    with open(payload_path, "rb") as f:
        fn, args, kwargs = pickle.load(f)

    import horovod_tpu as hvd

    hvd.init()
    result = fn(*args, **kwargs)
    rank = hvd.cross_rank()
    tmp = os.path.join(result_dir, f".result_{rank}.tmp")
    with open(tmp, "wb") as f:
        pickle.dump(result, f)
    os.replace(tmp, os.path.join(result_dir, f"result_{rank}.pkl"))
    from horovod_tpu.elastic.worker import clean_shutdown

    clean_shutdown()


if __name__ == "__main__":
    main()
