"""RayExecutor-style programmatic job execution.

Reference parity: horovod/ray/runner.py (``RayExecutor``) — an executor
object that starts a fleet of workers, runs a user function on every
worker with the framework initialized, and collects per-rank results
(SURVEY.md §2.4).

Backends:
  * **ray** (when importable): one Ray actor per worker, placement-group
    scheduling — the reference's deployment model.
  * **local** (always available, used in this image — ray is not
    installed): one subprocess per worker wired into the same
    coordination env ``tpurun`` uses.  This keeps the API contract fully
    testable and doubles as a programmatic `horovod.run()` analog.

Functions must be picklable (module-level); closures need cloudpickle,
which this environment does not ship.
"""

from __future__ import annotations

import os
import pickle
import socket
import subprocess
import sys
import tempfile
from typing import Any, Callable, List, Optional

__all__ = ["RayExecutor", "ElasticRayExecutor"]


def _ray_available() -> bool:
    try:
        import ray  # noqa: F401

        return True
    except ImportError:
        return False


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class RayExecutor:
    """Reference: horovod/ray/runner.py RayExecutor.

    Usage::

        executor = RayExecutor(num_workers=4)
        executor.start()
        results = executor.run(train_fn, args=[config])  # len == 4
        executor.shutdown()
    """

    def __init__(self, settings: Optional[dict] = None,
                 num_workers: int = 1, use_current_process: bool = False,
                 env_vars: Optional[dict] = None):
        self.num_workers = num_workers
        self.settings = settings or {}
        self.env_vars = dict(env_vars or {})
        self._started = False
        self._backend = "ray" if _ray_available() else "local"

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Allocate workers (reference: RayExecutor.start creating the
        actor fleet).  The local backend allocates lazily at run()."""
        if self._backend == "ray":
            import ray

            if not ray.is_initialized():
                ray.init(ignore_reinit_error=True)
        self._started = True

    def shutdown(self) -> None:
        self._started = False

    # -- execution ----------------------------------------------------------

    def run(self, fn: Callable, args: Optional[List[Any]] = None,
            kwargs: Optional[dict] = None) -> List[Any]:
        """Run ``fn(*args, **kwargs)`` on every worker with the framework
        initialized; returns the per-rank results in rank order
        (reference: RayExecutor.run → run_remote + get)."""
        if not self._started:
            raise RuntimeError("call start() before run()")
        args, kwargs = list(args or []), dict(kwargs or {})
        if self._backend == "ray":
            return self._run_ray(fn, args, kwargs)
        return self._run_local(fn, args, kwargs)

    def execute(self, fn: Callable) -> List[Any]:
        """Reference: RayExecutor.execute — fn receives no arguments."""
        return self.run(fn)

    # -- backends -----------------------------------------------------------

    def _run_ray(self, fn, args, kwargs):
        import ray

        coordinator = f"{socket.gethostname()}:{_free_port()}"
        native_port = _free_port()

        @ray.remote
        def worker(rank):
            for k, v in self._worker_env(coordinator, native_port,
                                         rank).items():
                os.environ[k] = v
            import horovod_tpu as hvd

            hvd.init()
            return fn(*args, **kwargs)

        return ray.get([worker.remote(r) for r in range(self.num_workers)])

    def _worker_env(self, coordinator, native_port, rank):
        env = dict(self.env_vars)
        env.update({
            "HVD_TPU_COORDINATOR": coordinator,
            "HVD_TPU_NATIVE_PORT": str(native_port),
            "HVD_TPU_NUM_PROCESSES": str(self.num_workers),
            "HVD_TPU_PROCESS_ID": str(rank),
        })
        return env

    def _run_local(self, fn, args, kwargs):
        coordinator = f"127.0.0.1:{_free_port()}"
        native_port = _free_port()
        with tempfile.TemporaryDirectory(prefix="hvd_tpu_ray_") as tmp:
            payload = os.path.join(tmp, "payload.pkl")
            with open(payload, "wb") as f:
                pickle.dump((fn, args, kwargs), f)
            procs = []
            for rank in range(self.num_workers):
                env = dict(os.environ)
                env.update(self._worker_env(coordinator, native_port,
                                            rank))
                repo_root = os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))))
                env["PYTHONPATH"] = (
                    repo_root + os.pathsep + env.get("PYTHONPATH", "")
                )
                procs.append(subprocess.Popen(
                    [sys.executable, "-m", "horovod_tpu.ray._worker",
                     payload, os.path.join(tmp, f"result_{rank}.pkl")],
                    env=env,
                ))
            codes = [p.wait() for p in procs]
            if any(codes):
                raise RuntimeError(
                    f"RayExecutor(local) worker failure, exit codes {codes}"
                )
            results = []
            for rank in range(self.num_workers):
                with open(os.path.join(tmp, f"result_{rank}.pkl"),
                          "rb") as f:
                    results.append(pickle.load(f))
            return results


class ElasticRayExecutor:
    """Elastic executor with the RayExecutor API (reference:
    horovod/ray/elastic.py ElasticRayExecutor).

    Design mapping: the reference drives worker discovery from the Ray
    autoscaler and respawns actors on membership change.  Here discovery
    is a callable returning ``[(host, slots), ...]`` fed to the same
    :class:`~horovod_tpu.runner.elastic_driver.ElasticDriver` that powers
    ``tpurun --host-discovery-script`` — workers that die are blacklisted
    and replaced, survivors recover via the elastic State contract
    (commit/restore/sync), and ``run()`` returns the per-rank results of
    the final world.  With ray installed the actor-fleet backend would
    plug in at ``_spawn`` (placement-group per worker); this image ships
    no ray, so the subprocess backend is the tested path and the ray
    backend is EXPERIMENTAL (see README).

    Usage::

        executor = ElasticRayExecutor(min_workers=1, max_workers=4)
        executor.start()
        results = executor.run(train_fn)   # train_fn uses hvd.elastic.run
        executor.shutdown()
    """

    def __init__(self, settings: Optional[dict] = None,
                 min_workers: int = 1, max_workers: Optional[int] = None,
                 env_vars: Optional[dict] = None,
                 discovery: Optional[Callable] = None):
        self.settings = settings or {}
        self.min_workers = min_workers
        self.max_workers = max_workers or min_workers
        self.env_vars = dict(env_vars or {})
        self._discovery_fn = discovery
        self._started = False

    def start(self) -> None:
        self._started = True

    def shutdown(self) -> None:
        self._started = False

    def run(self, fn: Callable, args: Optional[List[Any]] = None,
            kwargs: Optional[dict] = None) -> List[Any]:
        if not self._started:
            raise RuntimeError("call start() before run()")
        from ..runner.elastic_driver import ElasticDriver, HostDiscovery

        args, kwargs = list(args or []), dict(kwargs or {})
        discovery_fn = self._discovery_fn or (
            lambda: [("localhost", self.max_workers)]
        )

        class _CallableDiscovery(HostDiscovery):
            def __init__(self):  # no script: discovery is the callable
                super().__init__(script="", default_slots=1)

            def find_available_hosts(self):
                return discovery_fn()

        with tempfile.TemporaryDirectory(prefix="hvd_tpu_rayel_") as tmp:
            payload = os.path.join(tmp, "payload.pkl")
            with open(payload, "wb") as f:
                pickle.dump((fn, args, kwargs), f)
            repo_root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            knob_env = dict(self.env_vars)
            knob_env["PYTHONPATH"] = (
                repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")
            )
            driver = ElasticDriver(
                command=[sys.executable, "-m",
                         "horovod_tpu.ray._elastic_worker", payload, tmp],
                discovery=_CallableDiscovery(),
                min_np=self.min_workers,
                max_np=self.max_workers,
                knob_env=knob_env,
            )
            rc = driver.run()
            if rc != 0:
                raise RuntimeError(
                    f"ElasticRayExecutor job failed with exit code {rc}"
                )
            results = []
            rank = 0
            while True:
                path = os.path.join(tmp, f"result_{rank}.pkl")
                if not os.path.exists(path):
                    break
                with open(path, "rb") as f:
                    results.append(pickle.load(f))
                rank += 1
            return results
