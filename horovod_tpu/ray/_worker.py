"""Local-backend worker entrypoint for RayExecutor (reference analog:
the remote function body Ray actors execute in horovod/ray/runner.py)."""

import pickle
import sys


def main():
    payload_path, result_path = sys.argv[1], sys.argv[2]
    with open(payload_path, "rb") as f:
        fn, args, kwargs = pickle.load(f)

    import horovod_tpu as hvd

    hvd.init()
    result = fn(*args, **kwargs)
    with open(result_path, "wb") as f:
        pickle.dump(result, f)
    # coordinated teardown before interpreter exit (see
    # basics._register_early_distributed_shutdown): harmless if single
    from horovod_tpu.elastic.worker import clean_shutdown

    clean_shutdown()


if __name__ == "__main__":
    main()
