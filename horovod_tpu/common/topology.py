"""Pod topology discovery and mesh construction.

TPU-native replacement for the reference's rank/communicator bootstrap
(horovod/common/mpi/mpi_context.cc MPI_Comm_rank + host-hash allgather, and
horovod/common/gloo/gloo_context.cc HTTP rendezvous — SURVEY.md §3.1): on TPU
the runtime already knows the pod topology, so ``jax.devices()`` +
``jax.process_index()`` replace the entire rendezvous dance.  Multi-host
membership is established once via ``jax.distributed.initialize`` (the JAX
coordination service plays the role of the Gloo HTTP store).

The world is modelled as a 1-D ``jax.sharding.Mesh`` over every chip, axis
name ``"hvd"`` — data parallelism is sharding over that axis and gradient
reduction is ``psum`` riding ICI.  Hierarchical (intra-slice ICI +
inter-slice DCN) layouts reshape the same devices into a 2-D
``("dcn", "ici")`` mesh, the analog of the reference's local/cross
communicators used by NCCLHierarchicalAllreduce
(horovod/common/ops/nccl_operations.cc).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: Name of the world data-parallel mesh axis ("the ring" in reference terms).
WORLD_AXIS = "hvd"
#: Axis names of the hierarchical 2-D mesh (inter-slice DCN x intra-slice ICI).
DCN_AXIS = "dcn"
ICI_AXIS = "ici"


def _detect_slice_ids(devices: Sequence) -> Optional[List[int]]:
    """Per-device physical slice ids, when the runtime exposes them.

    Real multislice TPU runtimes tag each PJRT device with its slice
    (``slice_index`` on current jaxlib; ``coords``-less multislice pods
    expose only that attribute).  Returns None only when the tags carry
    no usable information: a device missing the attribute (CPU, an older
    runtime — unknown, let the caller fall back) or ids that do not
    partition the world into equal groups (an unequal split cannot form
    the rectangular (dcn, ici) mesh).  A UNIFORM tag is authoritative,
    not unknown: the runtime is explicitly reporting one slice, and the
    per-process fallback must not fabricate a DCN tier on a multi-host
    single-slice pod (chips there are ICI-linked across hosts).
    """
    ids = [getattr(d, "slice_index", None) for d in devices]
    if any(i is None for i in ids):
        return None
    uniq = sorted(set(ids))
    counts = {u: ids.count(u) for u in uniq}
    if len(set(counts.values())) != 1:
        return None
    return list(ids)


@dataclasses.dataclass(frozen=True)
class Topology:
    """Immutable snapshot of the device world at ``init()`` time.

    Plays the role of the reference's Controller rank bookkeeping
    (horovod/common/controller.cc: rank/local_rank/cross_rank,
    local_sizes/local_comm_ranks) but is computed directly from PJRT
    topology instead of a host-hash allgather.
    """

    devices: tuple  # all devices, in global (iota) order
    local_devices: tuple  # devices addressable by this process
    process_index: int
    num_processes: int

    @property
    def size(self) -> int:
        """Number of chips == number of data-parallel workers."""
        return len(self.devices)

    @property
    def local_size(self) -> int:
        return len(self.local_devices)

    @property
    def rank(self) -> int:
        """Global rank of this process's lead device.

        In the reference one process drives one GPU, so rank == process
        index.  On TPU one process drives ``local_size`` chips; we define
        the process rank as the global index of its first device so that
        (a) ranks are unique per process, (b) rank 0 is the coordinator,
        and (c) it degenerates to the classic value when local_size == 1.
        """
        if not self.local_devices:
            return 0
        first = self.local_devices[0]
        return self.devices.index(first)

    def owns_rank(self, world_rank: int) -> bool:
        """True when the chip at ``world_rank`` belongs to this process —
        the ownership test root-rank semantics need (a root_rank names a
        chip; its owning process supplies the data)."""
        if not 0 <= world_rank < self.size:
            raise ValueError(
                f"rank {world_rank} out of range [0, {self.size})"
            )
        return self.devices[world_rank] in self.local_devices

    def mesh(self) -> Mesh:
        """The 1-D world mesh: every chip on axis ``"hvd"``."""
        return Mesh(np.asarray(self.devices, dtype=object), (WORLD_AXIS,))

    def slice_ids(self) -> List[int]:
        """Physical fabric-tier id of every device, in world order.

        Resolution order (docs/COLLECTIVES.md):
          1. ``HVD_TPU_SLICE_SIZE`` — explicit chips-per-slice override;
             world order is grouped into consecutive runs of that size.
             This is how virtual CPU meshes (and tests) model a
             multislice fabric, and how an operator corrects a runtime
             that doesn't tag devices.
          2. the runtime's own ``slice_index`` device attribute (real
             multislice TPU jobs).
          3. one slice per process when processes partition the world
             evenly (each host's chips share ICI; DCN links hosts — the
             reference's intra-node/inter-node split).
          4. a single slice (flat world; no DCN tier).
        """
        from .retry import env_int  # deferred: retry pulls in metrics

        override = env_int("HVD_TPU_SLICE_SIZE", 0)
        if override > 0:
            if self.size % override != 0:
                raise ValueError(
                    f"HVD_TPU_SLICE_SIZE={override} does not divide the "
                    f"{self.size}-device world into equal slices"
                )
            return [i // override for i in range(self.size)]
        detected = _detect_slice_ids(self.devices)
        if detected is not None:
            # renumber to dense 0..n-1 in first-appearance order so the
            # ids index hierarchical_mesh rows
            order = {}
            return [order.setdefault(s, len(order)) for s in detected]
        procs = max(self.num_processes, 1)
        if procs > 1 and self.size % procs == 0:
            by_proc = {}
            ids = []
            for d in self.devices:
                p = getattr(d, "process_index", 0)
                ids.append(by_proc.setdefault(p, len(by_proc)))
            if all(ids.count(s) == self.size // procs for s in set(ids)):
                return ids
        return [0] * self.size

    @property
    def num_slices(self) -> int:
        """Number of fabric slices (DCN groups); 1 = no DCN tier."""
        return len(set(self.slice_ids()))

    @property
    def slice_size(self) -> int:
        """Chips per slice (the ICI group size)."""
        return self.size // self.num_slices

    def process_slice_groups(self) -> Optional[List[List[int]]]:
        """Member processes per slice, for process-granular two-level
        exchanges (the eager ZeRO hierarchical path): ``groups[s]`` is
        the ascending process-index list of slice ``s``.

        Returns None when the grouping cannot support a rectangular
        local/cross communicator split — a single slice, a process whose
        chips straddle slices, or unequal processes-per-slice — so the
        caller falls back to the flat exchange with no negotiation (the
        decision is a pure function of the frozen topology, identical on
        every rank)."""
        ids = self.slice_ids()
        if len(set(ids)) <= 1:
            return None
        proc_slice = {}
        for d, s in zip(self.devices, ids):
            p = getattr(d, "process_index", 0)
            if proc_slice.setdefault(p, s) != s:
                return None  # chips of one process straddle slices
        groups: dict = {}
        for p in sorted(proc_slice):
            groups.setdefault(proc_slice[p], []).append(p)
        if len(groups) <= 1 or len({len(v) for v in groups.values()}) != 1:
            return None
        return [groups[s] for s in sorted(groups)]

    def hierarchical_mesh(self, num_groups: Optional[int] = None) -> Mesh:
        """2-D ``(dcn, ici)`` mesh for two-level reductions.

        ``num_groups`` defaults to the detected slice count
        (:meth:`slice_ids` — runtime ``slice_index`` tags, the
        ``HVD_TPU_SLICE_SIZE`` override, or one group per process), so
        the mesh rows reflect the physical fabric tiers.  Reference
        analog: the local/cross communicator split in
        horovod/common/mpi/mpi_context.cc used by hierarchical allreduce.
        """
        if num_groups is None:
            slice_ids = self.slice_ids()
            groups = len(set(slice_ids))
            # a single detected slice yields a (1, world) mesh: no DCN
            # tier is invented here — slice_ids() already consulted the
            # per-process fallback where host boundaries ARE the best
            # available information, so all-zeros means the runtime
            # authoritatively reported one slice (or nothing partitions)
            # and a fabricated tier would quantize fast-fabric traffic
            # for zero benefit
            # row-major device layout by detected slice, preserving world
            # order within each slice — rows ARE the physical ICI groups
            rows = [
                [d for d, s in zip(self.devices, slice_ids) if s == g]
                for g in range(groups)
            ]
            arr = np.asarray(rows, dtype=object)
            return Mesh(arr, (DCN_AXIS, ICI_AXIS))
        groups = num_groups
        if groups <= 0 or self.size % groups != 0:
            raise ValueError(
                f"cannot split {self.size} devices into {groups} equal groups"
            )
        arr = np.asarray(self.devices, dtype=object).reshape(groups, self.size // groups)
        return Mesh(arr, (DCN_AXIS, ICI_AXIS))

    def replicated_sharding(self, mesh: Optional[Mesh] = None) -> NamedSharding:
        return NamedSharding(mesh or self.mesh(), P())

    def world_sharding(self, mesh: Optional[Mesh] = None) -> NamedSharding:
        """Leading-axis sharding over all chips."""
        return NamedSharding(mesh or self.mesh(), P(WORLD_AXIS))


def discover(devices: Optional[Sequence] = None) -> Topology:
    """Build a :class:`Topology` from the live JAX backend.

    Replaces the reference init-time bootstrap in SURVEY.md §3.1
    (horovod/common/operations.cc InitializeHorovodOnce): no rendezvous —
    PJRT already knows everything.
    """
    devs = tuple(devices) if devices is not None else tuple(jax.devices())
    local = tuple(d for d in devs if getattr(d, "process_index", 0) == jax.process_index())
    if not local:  # explicit device subset may exclude this process
        local = tuple(jax.local_devices())
    return Topology(
        devices=devs,
        local_devices=local,
        process_index=jax.process_index(),
        num_processes=jax.process_count(),
    )
