"""Pod topology discovery and mesh construction.

TPU-native replacement for the reference's rank/communicator bootstrap
(horovod/common/mpi/mpi_context.cc MPI_Comm_rank + host-hash allgather, and
horovod/common/gloo/gloo_context.cc HTTP rendezvous — SURVEY.md §3.1): on TPU
the runtime already knows the pod topology, so ``jax.devices()`` +
``jax.process_index()`` replace the entire rendezvous dance.  Multi-host
membership is established once via ``jax.distributed.initialize`` (the JAX
coordination service plays the role of the Gloo HTTP store).

The world is modelled as a 1-D ``jax.sharding.Mesh`` over every chip, axis
name ``"hvd"`` — data parallelism is sharding over that axis and gradient
reduction is ``psum`` riding ICI.  Hierarchical (intra-slice ICI +
inter-slice DCN) layouts reshape the same devices into a 2-D
``("dcn", "ici")`` mesh, the analog of the reference's local/cross
communicators used by NCCLHierarchicalAllreduce
(horovod/common/ops/nccl_operations.cc).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: Name of the world data-parallel mesh axis ("the ring" in reference terms).
WORLD_AXIS = "hvd"
#: Axis names of the hierarchical 2-D mesh (inter-slice DCN x intra-slice ICI).
DCN_AXIS = "dcn"
ICI_AXIS = "ici"


@dataclasses.dataclass(frozen=True)
class Topology:
    """Immutable snapshot of the device world at ``init()`` time.

    Plays the role of the reference's Controller rank bookkeeping
    (horovod/common/controller.cc: rank/local_rank/cross_rank,
    local_sizes/local_comm_ranks) but is computed directly from PJRT
    topology instead of a host-hash allgather.
    """

    devices: tuple  # all devices, in global (iota) order
    local_devices: tuple  # devices addressable by this process
    process_index: int
    num_processes: int

    @property
    def size(self) -> int:
        """Number of chips == number of data-parallel workers."""
        return len(self.devices)

    @property
    def local_size(self) -> int:
        return len(self.local_devices)

    @property
    def rank(self) -> int:
        """Global rank of this process's lead device.

        In the reference one process drives one GPU, so rank == process
        index.  On TPU one process drives ``local_size`` chips; we define
        the process rank as the global index of its first device so that
        (a) ranks are unique per process, (b) rank 0 is the coordinator,
        and (c) it degenerates to the classic value when local_size == 1.
        """
        if not self.local_devices:
            return 0
        first = self.local_devices[0]
        return self.devices.index(first)

    def owns_rank(self, world_rank: int) -> bool:
        """True when the chip at ``world_rank`` belongs to this process —
        the ownership test root-rank semantics need (a root_rank names a
        chip; its owning process supplies the data)."""
        if not 0 <= world_rank < self.size:
            raise ValueError(
                f"rank {world_rank} out of range [0, {self.size})"
            )
        return self.devices[world_rank] in self.local_devices

    def mesh(self) -> Mesh:
        """The 1-D world mesh: every chip on axis ``"hvd"``."""
        return Mesh(np.asarray(self.devices, dtype=object), (WORLD_AXIS,))

    def hierarchical_mesh(self, num_groups: Optional[int] = None) -> Mesh:
        """2-D ``(dcn, ici)`` mesh for two-level reductions.

        ``num_groups`` defaults to the number of processes (one group per
        host/slice).  Reference analog: the local/cross communicator split
        in horovod/common/mpi/mpi_context.cc used by hierarchical allreduce.
        """
        groups = num_groups if num_groups is not None else max(self.num_processes, 1)
        if groups <= 0 or self.size % groups != 0:
            raise ValueError(
                f"cannot split {self.size} devices into {groups} equal groups"
            )
        arr = np.asarray(self.devices, dtype=object).reshape(groups, self.size // groups)
        return Mesh(arr, (DCN_AXIS, ICI_AXIS))

    def replicated_sharding(self, mesh: Optional[Mesh] = None) -> NamedSharding:
        return NamedSharding(mesh or self.mesh(), P())

    def world_sharding(self, mesh: Optional[Mesh] = None) -> NamedSharding:
        """Leading-axis sharding over all chips."""
        return NamedSharding(mesh or self.mesh(), P(WORLD_AXIS))


def discover(devices: Optional[Sequence] = None) -> Topology:
    """Build a :class:`Topology` from the live JAX backend.

    Replaces the reference init-time bootstrap in SURVEY.md §3.1
    (horovod/common/operations.cc InitializeHorovodOnce): no rendezvous —
    PJRT already knows everything.
    """
    devs = tuple(devices) if devices is not None else tuple(jax.devices())
    local = tuple(d for d in devs if getattr(d, "process_index", 0) == jax.process_index())
    if not local:  # explicit device subset may exclude this process
        local = tuple(jax.local_devices())
    return Topology(
        devices=devs,
        local_devices=local,
        process_index=jax.process_index(),
        num_processes=jax.process_count(),
    )
