"""Core lifecycle and rank/size API.

Reference parity: horovod/common/basics.py (HorovodBasics) + the C API it
fronts in horovod/common/operations.cc (horovod_init / horovod_rank /
horovod_size / horovod_local_rank / ... — SURVEY.md §3.1).  The reference's
``init()`` spawns the C++ background thread and runs a network rendezvous;
on TPU the PJRT runtime already holds the pod topology, so ``init()`` is a
local bootstrap: discover devices, build the world mesh, attach process
sets, load the native controller, and read env config.

Multi-process (one process per TPU host, the reference's one-process-per-GPU
analog) is established *before* ``init()`` via ``jax.distributed.initialize``
— the ``tpurun`` launcher exports the coordinator address the same way
``horovodrun`` exports HOROVOD_GLOO_RENDEZVOUS_ADDR (SURVEY.md §3.3).
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Optional, Sequence

import jax

from ..utils.env_parser import Config
from ..utils.logging import get_logger
from . import topology as _topology
from .retry import env_float, env_int
from .exceptions import NotInitializedError
from .process_sets import ProcessSetRegistry, global_process_set
from .topology import Topology


class _GlobalState:
    """Singleton mirroring horovod/common/global_state.h (HorovodGlobalState):
    holds topology, config, process-set table, the collective engine and the
    native controller handle."""

    def __init__(self):
        self.lock = threading.RLock()
        self.initialized = False
        self.topology: Optional[Topology] = None
        self.config: Optional[Config] = None
        self.process_set_registry = ProcessSetRegistry()
        self.engine = None  # ops.engine.CollectiveEngine, set by init()
        self.controller = None  # native controller (ctypes), set by init()
        self.timeline = None


_state = _GlobalState()


def _maybe_init_distributed() -> None:
    """Join the multi-process world if the launcher configured one.

    ``tpurun`` exports HVD_TPU_COORDINATOR / HVD_TPU_NUM_PROCESSES /
    HVD_TPU_PROCESS_ID (SURVEY.md §3.3's env-plumbing step); on managed TPU
    pods ``jax.distributed.initialize()`` auto-detects and these are unset.
    """
    if os.environ.get("HVD_TPU_ELASTIC") in ("1", "true"):
        # elastic workers are spawned with only the driver's address; the
        # world shape (rank/size/coordinator) always comes from a driver
        # rendezvous (reference: §3.4 elastic rendezvous hands out ranks)
        from ..elastic import worker as _elastic_worker

        _elastic_worker.ensure_assignment()
    coord = os.environ.get("HVD_TPU_COORDINATOR")
    if not coord:
        return
    # NB: do NOT call jax.process_count()/jax.devices() here — that forces
    # backend initialization and jax.distributed.initialize must run first.
    from jax._src import distributed as _jax_distributed

    if getattr(_jax_distributed.global_state, "client", None) is not None:
        return  # coordination service already joined (runtime or prior init)
    # launcher-set world shape: a garbled value must fail loudly here —
    # a silent default would desynchronize the fleet
    # contract-ok: env -- launcher-set; garbage must crash, not default
    num = int(os.environ["HVD_TPU_NUM_PROCESSES"])
    # contract-ok: env -- launcher-set; garbage must crash, not default
    pid = int(os.environ["HVD_TPU_PROCESS_ID"])
    if num <= 1:
        return
    kwargs = {}
    # boot deadline: how long this process retries connecting to the
    # coordination service.  Configurable because one slow host (cold TF
    # import, first-time bridge compile, loaded single-core CI box) must
    # not turn into a spurious fleet kill (round-4 verdict weak #2: a
    # full-suite run tripped the default while a peer compiled the TF
    # bridge).  The launcher also pre-builds the TF bridge before
    # fan-out, attacking the same failure from the other side.
    boot_timeout = env_float("HVD_TPU_BOOT_TIMEOUT", 0.0)
    if boot_timeout > 0:
        kwargs["initialization_timeout"] = int(boot_timeout)
    if os.environ.get("HVD_TPU_ELASTIC") in ("1", "true"):
        # elastic mode: fail fast instead of blocking on dead peers — the
        # shutdown barrier must give up well before the heartbeat watchdog
        # would kill the surviving process (reference analog: NCCL abort
        # timeouts in the elastic error path, SURVEY.md §5.3)
        kwargs["heartbeat_timeout_seconds"] = env_int(
            "HVD_TPU_HEARTBEAT_TIMEOUT", 30)
        kwargs["shutdown_timeout_seconds"] = env_int(
            "HVD_TPU_SHUTDOWN_TIMEOUT", 8)
    # older jax (< 0.5) lacks the heartbeat/shutdown timeout knobs on
    # initialize(); passing them would TypeError and kill every elastic
    # worker at boot — drop what this jax can't take and say so (the
    # native-transport heartbeats still provide liveness there)
    import inspect

    accepted = inspect.signature(jax.distributed.initialize).parameters
    dropped = [k for k in kwargs if k not in accepted]
    if dropped:
        get_logger().info(
            "jax.distributed.initialize does not accept %s on this jax "
            "version; continuing without", dropped,
        )
        kwargs = {k: v for k, v in kwargs.items() if k in accepted}
    jax.distributed.initialize(
        coordinator_address=coord, num_processes=num, process_id=pid,
        **kwargs,
    )
    _register_early_distributed_shutdown()


_early_shutdown_registered = False


def _register_early_distributed_shutdown() -> None:
    """Run the coordination-service shutdown barrier at the EARLIEST exit
    phase (threading._register_atexit fires before regular atexit
    handlers and before non-daemon thread joins).

    Why: jax's own atexit shutdown can deadlock the whole job when any
    rank blocks in an earlier-registered finalizer before reaching the
    barrier — observed whenever an eager collective ever executed on a
    non-main thread (e.g. the torch adapter's grad hooks running on
    autograd worker threads).  Running the barrier first, while the
    process is still fully alive, sidesteps the ordering problem; jax's
    later atexit then sees a shut-down client and no-ops.
    """
    global _early_shutdown_registered
    if _early_shutdown_registered:
        return
    _early_shutdown_registered = True

    def _early_shutdown():
        try:
            # with fleet recovery in flight the shutdown barrier can
            # never complete — abandon instead of blocking at exit
            # (mirrors elastic worker.clean_shutdown)
            from ..elastic import worker as _elastic_worker

            if _elastic_worker.recovery_pending():
                _elastic_worker._abandon_distributed()
                return
        except Exception:
            pass
        try:
            from jax._src import distributed as _jd

            if getattr(_jd.global_state, "client", None) is not None:
                jax.distributed.shutdown()
        except Exception as e:
            get_logger().info("early distributed shutdown raised (%s)", e)

    threading._register_atexit(_early_shutdown)


def init(devices: Optional[Sequence] = None) -> None:
    """Initialize the framework (idempotent).

    Reference: horovod/common/operations.cc InitializeHorovodOnce — but with
    no rendezvous and no blocking wait: topology comes from PJRT, and the
    native background controller starts immediately.

    Args:
      devices: optional explicit device list (defaults to ``jax.devices()``);
        mainly for tests that carve up a virtual CPU mesh.
    """
    with _state.lock:
        if _state.initialized:
            return
        _maybe_init_distributed()
        _state.config = Config.from_env()
        _state.topology = _topology.discover(devices)
        _state.process_set_registry.attach_world(_state.topology)

        # fault injection: install the HVD_TPU_CHAOS plan for THIS rank
        # before the controller loads (the ctypes controller exports the
        # transport.* rules into the native core).  No spec = one module
        # bool per injection point.
        from .. import chaos as _chaos

        _chaos.install_from_env(rank=_state.topology.process_index)

        from ..ops.engine import CollectiveEngine  # deferred: avoids cycle

        _state.engine = CollectiveEngine(_state.topology, _state.config)

        from ..native import load_controller  # deferred: optional native core

        _state.controller = load_controller(_state.topology, _state.config)
        if _state.controller.is_native:
            _state.controller.set_engine(_state.engine)
        elif _state.config.timeline_filename:
            # python fallback timeline; the native core owns the file when
            # loaded (its C++ writer thread, reference-style)
            from ..utils.timeline import Timeline

            _state.timeline = Timeline(
                _state.config.timeline_filename, rank=_state.topology.rank
            )

        # telemetry: identity gauge + the per-worker /metrics + /healthz
        # endpoint (HVD_TPU_METRICS_PORT opts in; collection itself is
        # always on and costs nothing until scraped)
        from ..metrics import exposition as _metrics_exposition
        from ..metrics import instruments as _instruments

        _instruments.PROCESS_INFO.labels(
            str(_state.topology.rank), str(local_rank()),
            str(_state.topology.size),
            str(_state.topology.num_processes),
        ).set(1)
        _metrics_exposition.maybe_start_from_env(local_rank=local_rank())

        # span recorder + flight recorder (docs/TRACING.md): stamp this
        # rank on exports/bundles, mount /trace on the endpoint above,
        # and baseline the metric-delta snapshot.  Recording itself is
        # on by default (HVD_TPU_TRACE=0 disables) and device-free.
        from .. import trace as _trace
        from ..utils.logging import set_log_context

        _trace.install_from_env(rank=_state.topology.rank)
        set_log_context(rank=_state.topology.rank)

        _state.initialized = True
        get_logger().info(
            "initialized: size=%d local_size=%d rank=%d processes=%d backend=%s",
            _state.topology.size,
            _state.topology.local_size,
            _state.topology.rank,
            _state.topology.num_processes,
            jax.default_backend(),
        )


def shutdown() -> None:
    """Tear down (reference: horovod_shutdown in operations.cc)."""
    with _state.lock:
        if not _state.initialized:
            return
        if _state.controller is not None:
            _state.controller.shutdown()
            _state.controller = None
        if _state.timeline is not None:
            _state.timeline.close()
            _state.timeline = None
        from ..metrics import exposition as _metrics_exposition

        _metrics_exposition.stop_http_server()
        _state.engine = None
        _state.topology = None
        _state.initialized = False


atexit.register(shutdown)


def is_initialized() -> bool:
    """Reference: horovod_is_initialized (operations.cc)."""
    return _state.initialized


def start_timeline(file_path: str, mark_cycles: bool = True) -> None:
    """Begin writing the Chrome-trace timeline at runtime (reference:
    hvd.start_timeline / horovod_start_timeline in operations.cc) — the
    programmatic alternative to setting ``HVD_TPU_TIMELINE`` before init.

    ``mark_cycles`` is accepted for signature parity; cycle markers are
    always emitted while the timeline is active (the native writer's
    MarkCycle)."""
    st = _require_init()
    with st.lock:
        if st.controller is not None and st.controller.is_native:
            if not st.controller.start_timeline(file_path):
                raise ValueError(
                    "timeline already active (stop_timeline() first) or "
                    f"cannot open {file_path!r}"
                )
            return
        if st.timeline is not None:
            raise ValueError(
                "timeline already active (stop_timeline() first)"
            )
        from ..utils.timeline import Timeline

        try:
            st.timeline = Timeline(file_path, rank=st.topology.rank)
        except OSError as e:
            # same error contract as the native path
            raise ValueError(f"cannot open {file_path!r}: {e}") from e


def stop_timeline() -> None:
    """Close the runtime timeline (reference: hvd.stop_timeline)."""
    st = _require_init()
    with st.lock:
        if st.controller is not None and st.controller.is_native:
            st.controller.stop_timeline()
            return
        if st.timeline is not None:
            st.timeline.close()
            st.timeline = None


def _require_init() -> _GlobalState:
    if not _state.initialized:
        raise NotInitializedError()
    return _state


def topology() -> Topology:
    return _require_init().topology


def size() -> int:
    """Total number of workers == TPU chips (reference: horovod_size)."""
    return _require_init().topology.size


def rank() -> int:
    """Global rank of this process's lead chip (reference: horovod_rank).

    Equals the classic Horovod rank when each process drives one chip; with
    multiple local chips it is still unique per process and 0 on the
    coordinator, so ``if hvd.rank() == 0`` checkpoint gating works unchanged.
    """
    return _require_init().topology.rank


def local_size() -> int:
    """Chips driven by this process (reference: horovod_local_size)."""
    return _require_init().topology.local_size


def local_rank() -> int:
    """Index of this process among processes on the same host (reference:
    horovod_local_rank).  The launcher exports HVD_TPU_LOCAL_RANK (the
    per-host slot, like HOROVOD_LOCAL_RANK from horovodrun); without a
    launcher the TPU-pod layout is one process per host, so 0."""
    env = os.environ.get("HVD_TPU_LOCAL_RANK")
    return int(env) if env is not None else 0


def local_process_count() -> int:
    """Processes launched on this host (reference: the process count behind
    horovod_local_size when several workers share a host; distinct from
    :func:`local_size`, which counts this process's chips)."""
    env = os.environ.get("HVD_TPU_LOCAL_SIZE")
    return int(env) if env is not None else 1


def cross_size() -> int:
    """Number of processes (reference: horovod_cross_size — number of nodes)."""
    return _require_init().topology.num_processes


def cross_rank() -> int:
    """This process's index (reference: horovod_cross_rank)."""
    return _require_init().topology.process_index


def is_homogeneous() -> bool:
    """Reference: horovod_is_homogeneous — equal local sizes everywhere.
    TPU slices are homogeneous by construction unless a device subset was
    passed to init()."""
    st = _require_init()
    return st.topology.size == st.topology.local_size * max(
        st.topology.num_processes, 1
    )


# Build-capability probes (reference: horovod/common/basics.py
# mpi_enabled/gloo_built/nccl_built — used by tests for feature-gated skips).
def xla_built() -> bool:
    return True


def nccl_built() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def gloo_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def mpi_built() -> bool:
    return False


def gloo_enabled() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def mpi_threads_supported() -> bool:
    # no MPI at all; scripts that branch on this get the honest answer
    return False


def native_built() -> bool:
    """True when the C++ controller core is loaded (no Python fallback)."""
    st = _require_init()
    return st.controller is not None and st.controller.is_native
