"""HMAC signing for the Python control-plane messages.

Reference parity: horovod/runner/common/util/secret.py +
network.py (SURVEY.md §2.4) — the reference signs every pickled
driver/task RPC message with a per-job shared secret and rejects
messages whose digest does not verify.  Here the analogous channels are
the elastic driver <-> worker JSON-line sockets; the native negotiation
star authenticates separately with a challenge-response hello
(native/src/secret.h).

The secret is the launcher-generated per-job nonce in ``HVD_TPU_SECRET``
(tpurun exports it to every worker).  Signing is per-message (no
sequence numbers): replay within one job's lifetime is accepted, exactly
the reference's HMAC-of-payload property — the fresh per-job secret
kills cross-job replay.  When no secret is set (bare single-host runs
outside the launcher) messages pass unsigned, matching the reference's
behavior when run without horovodrun.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
from typing import Optional

SECRET_ENV = "HVD_TPU_SECRET"


def make_secret() -> str:
    """Fresh per-job secret (reference: secret.make_secret_key)."""
    return os.urandom(32).hex()


def job_secret() -> Optional[str]:
    return os.environ.get(SECRET_ENV) or None


def _mac(secret: str, payload: str) -> str:
    return hmac.new(secret.encode(), payload.encode(),
                    hashlib.sha256).hexdigest()


def sign_message(obj: dict, secret: Optional[str]) -> dict:
    """Return a copy of ``obj`` carrying an ``hmac`` field over its
    canonical JSON encoding; identity when no secret is configured."""
    if not secret:
        return obj
    body = {k: v for k, v in obj.items() if k != "hmac"}
    payload = json.dumps(body, sort_keys=True, separators=(",", ":"))
    out = dict(body)
    out["hmac"] = _mac(secret, payload)
    return out


def verify_message(obj: dict, secret: Optional[str]) -> Optional[dict]:
    """Verify and strip the ``hmac`` field.  Returns the payload dict, or
    None when a secret is configured and the signature is missing/wrong
    (callers must drop the message / close the peer)."""
    if not secret:
        return obj
    mac = obj.get("hmac")
    if not isinstance(mac, str):
        return None
    body = {k: v for k, v in obj.items() if k != "hmac"}
    payload = json.dumps(body, sort_keys=True, separators=(",", ":"))
    if not hmac.compare_digest(mac, _mac(secret, payload)):
        return None
    return body
