"""Framework-agnostic core (reference analog: horovod/common/)."""
