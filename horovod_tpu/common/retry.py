"""Shared retry policy: exponential backoff with full jitter, deadline-aware.

Before this module every transient-failure loop in the control plane was
hand-rolled (fixed 100 ms polls in the native connect path, bare
``create_connection(timeout=30)`` one-shots in the elastic worker, an
unretried discovery-script ``subprocess.run``) — each with its own
timeout constant and its own thundering-herd behavior when a whole fleet
retried in lockstep after a failure.  ``retry_call`` is the one policy
they all share now (the native ``ConnectToRoot`` mirrors it in C++):

  * exponential backoff capped at ``max_delay``;
  * FULL jitter (sleep ~ U[0, cap]) — the AWS-architecture result that
    desynchronizes a fleet better than equal-jitter or raw exponential;
  * deadline-aware — a sleep never overshoots the overall ``timeout``,
    and the last error re-raises when time (or ``attempts``) runs out;
  * instrumented — attempts-per-call land in the
    ``hvd_tpu_retry_attempts`` histogram labeled by ``site``.

Deterministic under chaos testing: pass ``rng`` (any object with
``random()``) to pin the jitter stream.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

from ..metrics import instruments as _metrics
from ..utils.logging import get_logger

__all__ = ["retry_call", "env_float", "env_int"]

T = TypeVar("T")


def env_float(name: str, default: float) -> float:
    """Validated float read of the environment variable ``name`` with a
    fall-through default — the spelling every env-tunable number in the
    package uses (a garbled value warns and falls back rather than
    killing the process; ``tools/check.py`` enforces the convention)."""
    import os

    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        get_logger().warning("%s=%r is not a number; using %s",
                             name, raw, default)
        return default


def env_int(name: str, default: int) -> int:
    """Validated integer read of ``name`` (see :func:`env_float`)."""
    import os

    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        get_logger().warning("%s=%r is not an integer; using %s",
                             name, raw, default)
        return default


def retry_call(
    fn: Callable[[], T],
    *,
    site: str,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    attempts: Optional[int] = None,
    timeout: Optional[float] = None,
    base_delay: float = 0.1,
    max_delay: float = 5.0,
    rng: Optional[random.Random] = None,
    describe: Optional[str] = None,
) -> T:
    """Call ``fn()`` until it succeeds, an exception outside ``retry_on``
    escapes, ``attempts`` are exhausted, or the ``timeout`` deadline
    passes.  The final failure re-raises the last error unchanged (the
    caller's except-clauses keep working).

    Args:
      site: metrics/log label (e.g. ``"elastic.rendezvous"``).
      retry_on: exception classes that mean "transient, try again".
      attempts: max calls (None = bounded by ``timeout`` only; with both
        None, a single failure re-raises immediately).
      timeout: overall wall-clock budget in seconds, measured from the
        first call; sleeps are clipped so the budget is never overshot.
      base_delay/max_delay: backoff cap grows ``base_delay * 2**n`` up to
        ``max_delay``; actual sleep is uniform in [0, cap] (full jitter).
      rng: jitter source (tests/chaos replay); default module random.
      describe: human phrase for warning logs (default: ``site``).
    """
    if attempts is None and timeout is None:
        attempts = 1
    draw = (rng or random).random
    deadline = None if timeout is None else time.monotonic() + timeout
    what = describe or site
    n = 0
    while True:
        n += 1
        try:
            result = fn()
            _metrics.RETRY_ATTEMPTS.labels(site).observe(n)
            return result
        except retry_on as e:
            out_of_attempts = attempts is not None and n >= attempts
            out_of_time = (deadline is not None
                           and time.monotonic() >= deadline)
            if out_of_attempts or out_of_time:
                _metrics.RETRY_ATTEMPTS.labels(site).observe(n)
                get_logger().warning(
                    "%s failed after %d attempt(s) (%s); giving up: %s",
                    what, n,
                    "deadline exceeded" if out_of_time else "attempts "
                    "exhausted", e,
                )
                raise
            cap = min(max_delay, base_delay * (2 ** (n - 1)))
            sleep = cap * draw()
            if deadline is not None:
                sleep = min(sleep, max(0.0, deadline - time.monotonic()))
            get_logger().info(
                "%s attempt %d failed (%s); retrying in %.2fs",
                what, n, e, sleep,
            )
            time.sleep(sleep)
