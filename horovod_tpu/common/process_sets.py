"""Process sets: named device subsets with their own collective scope.

Reference parity: horovod/common/process_set.h/.cc + horovod/common/
process_sets.py (SURVEY.md §2.1).  In the reference each ProcessSet owns a
separate Controller, TensorQueue and communicators; here a process set owns a
sub-``Mesh`` (a subset of chips) and collectives scoped to it compile over
that sub-mesh.  Set 0 is always the global (world) set.

TPU-first note: "rank" in a process set is a *chip* index into the world
device order, mirroring the reference's global-rank lists, so a process set
is literally a named sub-mesh of the pod.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np
from jax.sharding import Mesh

from .exceptions import ProcessSetError
from .topology import WORLD_AXIS, Topology


class ProcessSet:
    """A named subset of world ranks (chips) with its own sub-mesh.

    Reference: horovod/common/process_set.h (ProcessSet struct holding its
    own controller + tensor queue); here the compiled-executable cache is
    keyed by the process-set id instead (SURVEY.md §7.1).
    """

    def __init__(self, ranks: Optional[Sequence[int]] = None):
        self.process_set_id: Optional[int] = None
        self.ranks: Optional[List[int]] = sorted(ranks) if ranks is not None else None
        self._mesh: Optional[Mesh] = None

    def _attach(self, set_id: int, topology: Topology) -> None:
        self.process_set_id = set_id
        if self.ranks is None:  # world set
            self.ranks = list(range(topology.size))
        for r in self.ranks:
            if not 0 <= r < topology.size:
                raise ProcessSetError(
                    f"rank {r} out of range for world size {topology.size}"
                )
        if len(set(self.ranks)) != len(self.ranks):
            raise ProcessSetError(f"duplicate ranks in process set: {self.ranks}")
        devs = np.asarray([topology.devices[r] for r in self.ranks], dtype=object)
        self._mesh = Mesh(devs, (WORLD_AXIS,))

    @property
    def mesh(self) -> Mesh:
        if self._mesh is None:
            raise ProcessSetError("process set is not attached (call add_process_set)")
        return self._mesh

    def size(self) -> int:
        if self.ranks is None:
            raise ProcessSetError("process set is not attached")
        return len(self.ranks)

    def rank_in_set(self, world_rank: int) -> int:
        """Position of a world rank inside this set (reference:
        ProcessSet::controller->GetRank relative numbering)."""
        try:
            return self.ranks.index(world_rank)
        except (ValueError, AttributeError):
            raise ProcessSetError(
                f"world rank {world_rank} is not a member of process set "
                f"{self.process_set_id}"
            )

    def included(self, world_rank: int) -> bool:
        return self.ranks is not None and world_rank in self.ranks

    def __repr__(self) -> str:
        return f"ProcessSet(id={self.process_set_id}, ranks={self.ranks})"


#: The world process set, always id 0 (reference: global_process_set).
global_process_set = ProcessSet()


class ProcessSetRegistry:
    """Registry mapping set ids to :class:`ProcessSet`.

    Reference: horovod/common/process_set.cc (ProcessSetTable) — ids are
    assigned monotonically, id 0 is the world, removal frees the id.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._table: Dict[int, ProcessSet] = {}
        self._next_id = 0

    def attach_world(self, topology: Topology) -> None:
        with self._lock:
            self._table.clear()
            self._next_id = 0
            global_process_set.process_set_id = None
            global_process_set.ranks = None
            global_process_set._mesh = None
            global_process_set._attach(0, topology)
            self._table[0] = global_process_set
            self._next_id = 1
            self._topology = topology

    def add(self, process_set: ProcessSet) -> ProcessSet:
        with self._lock:
            if process_set.process_set_id is not None:
                raise ProcessSetError("process set is already registered")
            # compare against the post-attach expansion (ranks=None means
            # the full world, which must collide with set 0)
            effective = (
                sorted(process_set.ranks)
                if process_set.ranks is not None
                else list(range(self._topology.size))
            )
            for existing in self._table.values():
                if existing.ranks == effective:
                    raise ProcessSetError(
                        f"a process set with ranks {existing.ranks} already exists"
                    )
            set_id = self._next_id
            self._next_id += 1
            process_set._attach(set_id, self._topology)
            self._table[set_id] = process_set
            return process_set

    def remove(self, process_set: ProcessSet) -> None:
        with self._lock:
            set_id = process_set.process_set_id
            if set_id == 0:
                raise ProcessSetError("cannot remove the global process set")
            if set_id is None or set_id not in self._table:
                raise ProcessSetError("process set is not registered")
            del self._table[set_id]
            process_set.process_set_id = None
            process_set._mesh = None

    def get(self, set_id: int) -> ProcessSet:
        with self._lock:
            try:
                return self._table[set_id]
            except KeyError:
                raise ProcessSetError(f"unknown process set id {set_id}")

    def ids(self) -> List[int]:
        with self._lock:
            return sorted(self._table)

    def resolve(self, process_set: Optional[ProcessSet]) -> ProcessSet:
        return process_set if process_set is not None else global_process_set
