"""Framework exceptions.

Reference parity: horovod/common/exceptions.py — ``HorovodInternalError`` is
raised when a collective fails mid-flight (NCCL abort in the reference; a
failed XLA collective / coordination-service loss here) and is the signal the
elastic ``run`` wrapper catches to trigger state rollback.  See SURVEY.md §5.3.
"""

from __future__ import annotations


class HorovodTpuError(Exception):
    """Base class for all framework errors."""


class HorovodInternalError(HorovodTpuError):
    """A collective operation failed and the communicator must be rebuilt.

    Reference: horovod/common/exceptions.py (HorovodInternalError).
    Elastic mode catches this, restores the last committed state, and
    re-initializes (SURVEY.md §3.4).
    """


class HostsUpdatedInterrupt(HorovodTpuError):
    """Raised when the elastic driver notifies of a membership change.

    Reference: horovod/common/elastic.py (HostsUpdatedInterrupt).  Unlike
    ``HorovodInternalError`` the current state is intact: the elastic loop
    keeps it and merely re-runs rendezvous.
    """

    def __init__(self, skip_sync: bool = False):
        super().__init__()
        self.skip_sync = skip_sync


class NotInitializedError(HorovodTpuError):
    """An API needing ``hvd.init()`` was called before initialization.

    Reference: horovod/common/basics.py raises a ValueError with the message
    'Horovod has not been initialized; use hvd.init().' — we keep a dedicated
    type but the same contract.
    """

    def __init__(self, what: str = "Framework"):
        super().__init__(
            f"{what} has not been initialized; call horovod_tpu.init() first."
        )


class ProcessSetError(HorovodTpuError):
    """Invalid process-set operation (unknown set, duplicate ranks, ...).

    Reference: horovod/common/process_sets.py.
    """
