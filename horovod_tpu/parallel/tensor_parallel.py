"""Tensor (model) parallelism: Megatron-style sharded layers.

No reference analog (SURVEY.md §2.6 marks TP absent upstream); provided
because the same mesh machinery makes it first-class here.  (Shoeybi et
al., "Megatron-LM", 2019 — PAPERS.md.)

The classic pairing inside a shard_map'ped step over a ``tp`` mesh axis:

  * :class:`ColumnParallelDense` — weight sharded on the *output* dim;
    no communication on the forward (each chip computes its slice of the
    activations).
  * :class:`RowParallelDense` — weight sharded on the *input* dim; a
    single ``psum`` over the tp axis reassembles the output.

An attention block becomes: QKV projections column-parallel (heads split
across tp), local attention on H/n heads, output projection row-parallel
(one psum).  The MLP becomes column→gelu→row (one psum).  XLA lowers the
psums onto ICI and fuses them with the surrounding matmuls' epilogues.

Gradients: under SPMD autodiff the transpose of psum/identity is
identity/psum, so backward communication is derived automatically — no
hand-written backward collectives (the compiler does what Megatron's
``f``/``g`` autograd functions hand-code).
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


from ._mesh_utils import axis_size_or_1 as _axis_size


def transformer_shard_specs(params, axis: str):
    """PartitionSpec tree laying ``models.transformer.Transformer``
    params out Megatron-style over one tensor axis — the layout
    ``TransformerConfig.shard_axis`` consumes inside ``shard_map``
    (serving.ServingEngine's sharded step programs; docs/SERVING.md):

      * ``attn/{q,k,v}`` kernels (D, H, d): COLUMN-parallel on the head
        dim — each chip projects its local head slice, no comms;
      * ``attn/o`` kernel (H, d, D): ROW-parallel on the head dim — the
        per-chip partial outputs meet in the block's first psum;
      * ``mlp/{gate,up}`` kernels (D, F): column-parallel on F;
      * ``mlp/down`` kernel (F, D): row-parallel on F — the second psum;
      * embedding, norms, everything else: replicated.

    Same-name layers in :class:`MultiAxisTransformer`'s blocks are NOT
    this layout (its attention is one fused qkv) — this helper is
    specific to the flagship ``Transformer`` param tree.
    """
    col_qkv, row_o = P(None, axis, None), P(axis, None, None)
    col_mlp, row_mlp = P(None, axis), P(axis, None)

    def spec(path, leaf):
        names = [getattr(p, "key", str(p)) for p in path]
        if "attn" in names:
            if any(n in names for n in ("q", "k", "v")):
                return col_qkv
            if "o" in names:
                return row_o
        if "mlp" in names:
            if "gate" in names or "up" in names:
                return col_mlp
            if "down" in names:
                return row_mlp
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


class ColumnParallelDense(nn.Module):
    """Dense with output features sharded over ``axis``: this chip holds
    ``features // tp`` columns.  Forward needs no communication."""

    features: int  # GLOBAL output features
    axis: Optional[str] = "tp"
    use_bias: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        tp = _axis_size(self.axis)
        if self.features % tp:
            raise ValueError(
                f"features {self.features} not divisible by tp={tp}"
            )
        local = self.features // tp
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (x.shape[-1], local), jnp.float32,
        )
        y = jnp.dot(x.astype(self.dtype), kernel.astype(self.dtype))
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (local,),
                              jnp.float32)
            y = y + bias.astype(self.dtype)
        return y


class RowParallelDense(nn.Module):
    """Dense with input features sharded over ``axis``: the partial
    products are summed with ONE psum over the tp axis."""

    features: int
    axis: Optional[str] = "tp"
    use_bias: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        tp = _axis_size(self.axis)
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (x.shape[-1], self.features), jnp.float32,
        )
        y = jnp.dot(x.astype(self.dtype), kernel.astype(self.dtype))
        if tp > 1:
            y = jax.lax.psum(y, self.axis)
        if self.use_bias:
            # bias applied once, after the reduction
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), jnp.float32)
            y = y + bias.astype(self.dtype)
        return y


class TensorParallelMlp(nn.Module):
    """Column → activation → Row: the Megatron MLP with one forward psum."""

    d_model: int
    d_ff: int
    axis: Optional[str] = "tp"
    activation: Callable = nn.gelu
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = ColumnParallelDense(self.d_ff, axis=self.axis,
                                dtype=self.dtype, name="wi")(x)
        h = self.activation(h)
        return RowParallelDense(self.d_model, axis=self.axis,
                                dtype=self.dtype, name="wo")(h)


class TensorParallelAttention(nn.Module):
    """Multi-head attention with heads sharded over the tp axis.

    QKV column-parallel (this chip computes H/tp heads), attention local,
    output projection row-parallel (one psum).  ``attn_fn`` defaults to
    exact causal attention and may be swapped for ring/ulysses attention
    to compose TP × SP.
    """

    num_heads: int  # GLOBAL head count
    head_dim: int
    axis: Optional[str] = "tp"
    attn_fn: Optional[Callable] = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        tp = _axis_size(self.axis)
        if self.num_heads % tp:
            raise ValueError(
                f"heads {self.num_heads} not divisible by tp={tp}"
            )
        local_heads = self.num_heads // tp
        d_model = x.shape[-1]
        qkv_features = self.num_heads * self.head_dim
        qkv = ColumnParallelDense(3 * qkv_features, axis=self.axis,
                                  use_bias=False, dtype=self.dtype,
                                  name="qkv")(x)
        b, s = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape(b, s, 3, local_heads, self.head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = self.attn_fn
        if attn is None:
            from ..models.transformer import causal_dot_attention

            attn = causal_dot_attention
        out = attn(q, k, v)  # (B, S, H/tp, D)
        out = out.reshape(b, s, local_heads * self.head_dim)
        return RowParallelDense(d_model, axis=self.axis, use_bias=False,
                                dtype=self.dtype, name="proj")(out)
